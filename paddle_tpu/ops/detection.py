"""Detection ops, static-shape TPU formulations.

Rebuild of the reference detection op family
(reference: python/paddle/fluid/layers/detection.py — prior_box:1657,
density_prior_box:1813, anchor_generator:2280, iou_similarity:680,
box_coder:730, yolo_box:1038, yolov3_loss:912, sigmoid_focal_loss:455,
bipartite_match:1218, target_assign:1307, ssd_loss:1410,
multiclass_nms:3082, detection_output:541, box_clip:2866,
polygon_box_transform:878, generate_proposals:2745,
distribute_fpn_proposals:3363, multi_box_head:1991; C++ kernels under
paddle/fluid/operators/detection/).

The reference emits variable-length LoD outputs (NMS keeps "however many"
boxes). XLA requires static shapes, so every op here uses the padded
formulation: fixed-size outputs ranked by score with a sentinel
(label = -1 / score = 0) marking invalid slots — the standard TPU
detection design. All ops are jit-compatible (lax.fori_loop for the
sequential NMS/matching scans, no data-dependent Python control flow).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..dispatch import apply

__all__ = [
    "iou_similarity", "box_coder", "box_clip", "prior_box",
    "density_prior_box", "anchor_generator", "yolo_box", "yolov3_loss",
    "sigmoid_focal_loss", "bipartite_match", "target_assign", "ssd_loss",
    "multiclass_nms", "detection_output", "polygon_box_transform",
    "roi_align", "roi_pool", "generate_proposals",
    "distribute_fpn_proposals", "collect_fpn_proposals", "multi_box_head",
]


# ---------------------------------------------------------------------------
# box geometry helpers (pure jax, used inside kernels)

def _box_area(box, normalized):
    w = box[..., 2] - box[..., 0]
    h = box[..., 3] - box[..., 1]
    if not normalized:
        w = w + 1.0
        h = h + 1.0
    return jnp.maximum(w, 0.0) * jnp.maximum(h, 0.0)


def _pairwise_iou(a, b, normalized=True):
    """a (..., N, 4), b (..., M, 4) → IoU (..., N, M); xyxy corners."""
    off = 0.0 if normalized else 1.0
    lt = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    rb = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = _box_area(a, normalized)[..., :, None]
    area_b = _box_area(b, normalized)[..., None, :]
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


def iou_similarity(x, y, box_normalized=True, name=None):
    """IoU matrix between row boxes (reference detection.py:680). x (N,4)
    or (B,N,4); y (M,4) or (B,M,4) → (…,N,M)."""
    return apply(
        lambda x, y: _pairwise_iou(x, y, box_normalized), (x, y),
        nondiff=False, name="iou_similarity")


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference detection.py:730,
    operators/detection/box_coder_op.h). prior_box (M,4) xyxy; variance
    either a (M,4)/(4,) array or a python list of 4 floats."""
    ct = code_type.lower()
    if ct not in ("encode_center_size", "decode_center_size"):
        raise ValueError("unknown code_type %s" % code_type)
    var_is_list = isinstance(prior_box_var, (list, tuple))
    var_list = list(prior_box_var) if var_is_list else None

    def impl(prior, target, *maybe_var):
        off = 0.0 if box_normalized else 1.0
        pw = prior[:, 2] - prior[:, 0] + off
        ph = prior[:, 3] - prior[:, 1] + off
        pcx = prior[:, 0] + pw * 0.5
        pcy = prior[:, 1] + ph * 0.5
        if maybe_var:
            var = maybe_var[0]
            var = jnp.broadcast_to(var.reshape(-1, 4), (prior.shape[0], 4))
        elif var_list is not None:
            var = jnp.broadcast_to(jnp.asarray(var_list, prior.dtype),
                                   (prior.shape[0], 4))
        else:
            var = jnp.ones((prior.shape[0], 4), prior.dtype)
        if ct == "encode_center_size":
            # target (N, 4) vs priors (M, 4) → (N, M, 4)
            tw = target[:, 2] - target[:, 0] + off
            th = target[:, 3] - target[:, 1] + off
            tcx = target[:, 0] + tw * 0.5
            tcy = target[:, 1] + th * 0.5
            ex = (tcx[:, None] - pcx[None, :]) / pw[None, :] / var[None, :, 0]
            ey = (tcy[:, None] - pcy[None, :]) / ph[None, :] / var[None, :, 1]
            ew = jnp.log(jnp.maximum(tw[:, None] / pw[None, :], 1e-10)) / \
                var[None, :, 2]
            eh = jnp.log(jnp.maximum(th[:, None] / ph[None, :], 1e-10)) / \
                var[None, :, 3]
            return jnp.stack([ex, ey, ew, eh], axis=-1)
        # decode: target (N, M, 4) or (M, 4); priors broadcast on `axis`
        t = target
        squeeze = False
        if t.ndim == 2:
            t = t[None] if axis == 0 else t[:, None]
            squeeze = True
        if axis == 0:
            pcx_, pcy_, pw_, ph_, v = (pcx[None, :], pcy[None, :],
                                       pw[None, :], ph[None, :], var[None])
        else:
            pcx_, pcy_, pw_, ph_, v = (pcx[:, None], pcy[:, None],
                                       pw[:, None], ph[:, None],
                                       var[:, None])
        dcx = v[..., 0] * t[..., 0] * pw_ + pcx_
        dcy = v[..., 1] * t[..., 1] * ph_ + pcy_
        dw = jnp.exp(jnp.minimum(v[..., 2] * t[..., 2], 30.0)) * pw_
        dh = jnp.exp(jnp.minimum(v[..., 3] * t[..., 3], 30.0)) * ph_
        out = jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                         dcx + dw * 0.5 - off, dcy + dh * 0.5 - off],
                        axis=-1)
        return out[0] if (squeeze and axis == 0) else (
            out[:, 0] if squeeze else out)

    args = (prior_box, target_box)
    if prior_box_var is not None and not var_is_list:
        args = args + (prior_box_var,)
    return apply(impl, args, name="box_coder")


def box_clip(input, im_info, name=None):
    """Clip boxes to image bounds (reference detection.py:2866). im_info
    rows are (H, W, scale)."""
    def impl(boxes, im_info):
        im = im_info.reshape(-1, im_info.shape[-1])
        h = im[:, 0] / im[:, 2] - 1.0
        w = im[:, 1] / im[:, 2] - 1.0
        if boxes.ndim == 2:
            hh, ww = h[0], w[0]
        else:
            hh = h.reshape((-1,) + (1,) * (boxes.ndim - 2))
            ww = w.reshape((-1,) + (1,) * (boxes.ndim - 2))
        x1 = jnp.clip(boxes[..., 0], 0.0, ww)
        y1 = jnp.clip(boxes[..., 1], 0.0, hh)
        x2 = jnp.clip(boxes[..., 2], 0.0, ww)
        y2 = jnp.clip(boxes[..., 3], 0.0, hh)
        return jnp.stack([x1, y1, x2, y2], axis=-1)

    return apply(impl, (input, im_info), name="box_clip")


def polygon_box_transform(input, name=None):
    """Quad offsets → absolute vertex coords (reference detection.py:878).
    input (N, 8, H, W): channel 2k is x-offset, 2k+1 is y-offset."""
    def impl(x):
        n, c, h, w = x.shape
        xs = jax.lax.broadcasted_iota(x.dtype, (h, w), 1)
        ys = jax.lax.broadcasted_iota(x.dtype, (h, w), 0)
        grid = jnp.stack([xs, ys] * (c // 2))  # (C, H, W)
        return grid[None] - x

    return apply(impl, (input,), name="polygon_box_transform")


# ---------------------------------------------------------------------------
# prior / anchor generation (host-side numpy grids are fine: shapes are
# static and the results are constants folded into the XLA program)

def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes over a feature map (reference detection.py:1657).
    Returns (boxes, variances), each (H, W, num_priors, 4)."""
    min_sizes = [float(s) for s in np.atleast_1d(min_sizes)]
    max_sizes = [float(s) for s in np.atleast_1d(max_sizes)] \
        if max_sizes is not None else []
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    def impl(feat, img):
        fh, fw = feat.shape[2], feat.shape[3]
        ih, iw = img.shape[2], img.shape[3]
        step_w = steps[0] if steps[0] > 0 else iw / fw
        step_h = steps[1] if steps[1] > 0 else ih / fh
        cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
        cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
        cx = jnp.broadcast_to(cx[None, :], (fh, fw))
        cy = jnp.broadcast_to(cy[:, None], (fh, fw))
        whs = []
        for k, ms in enumerate(min_sizes):
            if not min_max_aspect_ratios_order:
                for ar in ars:
                    whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
                    if abs(ar - 1.0) < 1e-6 and k < len(max_sizes):
                        bs = math.sqrt(ms * max_sizes[k])
                        whs.append((bs, bs))
            else:
                whs.append((ms, ms))
                if k < len(max_sizes):
                    bs = math.sqrt(ms * max_sizes[k])
                    whs.append((bs, bs))
                for ar in ars:
                    if abs(ar - 1.0) < 1e-6:
                        continue
                    whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
        wh = jnp.asarray(whs, jnp.float32)  # (P, 2)
        boxes = jnp.stack([
            (cx[..., None] - wh[None, None, :, 0] / 2) / iw,
            (cy[..., None] - wh[None, None, :, 1] / 2) / ih,
            (cx[..., None] + wh[None, None, :, 0] / 2) / iw,
            (cy[..., None] + wh[None, None, :, 1] / 2) / ih,
        ], axis=-1)  # (H, W, P, 4)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               boxes.shape)
        return boxes, var

    return apply(impl, (input, image), n_out=2, nondiff=True,
                 name="prior_box")


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, flatten_to_2d=False,
                      name=None):
    """Densified priors (reference detection.py:1813): each fixed_size is
    laid out on a densities[i]×densities[i] sub-grid in every cell."""
    densities = [int(d) for d in densities]
    fixed_sizes = [float(s) for s in fixed_sizes]
    fixed_ratios = [float(r) for r in fixed_ratios]

    def impl(feat, img):
        fh, fw = feat.shape[2], feat.shape[3]
        ih, iw = img.shape[2], img.shape[3]
        step_w = steps[0] if steps[0] > 0 else iw / fw
        step_h = steps[1] if steps[1] > 0 else ih / fh
        cell_x = jnp.arange(fw, dtype=jnp.float32) * step_w
        cell_y = jnp.arange(fh, dtype=jnp.float32) * step_h
        cell_x = jnp.broadcast_to(cell_x[None, :], (fh, fw))
        cell_y = jnp.broadcast_to(cell_y[:, None], (fh, fw))
        pieces = []  # per-prior (dx, dy, w, h) offsets within a cell
        for size, dens in zip(fixed_sizes, densities):
            for ratio in fixed_ratios:
                w = size * math.sqrt(ratio)
                h = size / math.sqrt(ratio)
                shift = int(step_w / dens), int(step_h / dens)
                for dj in range(dens):
                    for di in range(dens):
                        ccx = (di + 0.5) * shift[0]
                        ccy = (dj + 0.5) * shift[1]
                        pieces.append((ccx, ccy, w, h))
        po = jnp.asarray(pieces, jnp.float32)  # (P, 4)
        cx = cell_x[..., None] + po[None, None, :, 0]
        cy = cell_y[..., None] + po[None, None, :, 1]
        w = po[None, None, :, 2]
        h = po[None, None, :, 3]
        boxes = jnp.stack([(cx - w / 2) / iw, (cy - h / 2) / ih,
                           (cx + w / 2) / iw, (cy + h / 2) / ih], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               boxes.shape)
        if flatten_to_2d:
            boxes = boxes.reshape(-1, 4)
            var = var.reshape(-1, 4)
        return boxes, var

    return apply(impl, (input, image), n_out=2, nondiff=True,
                 name="density_prior_box")


def anchor_generator(input, anchor_sizes=(64.0, 128.0, 256.0, 512.0),
                     aspect_ratios=(0.5, 1.0, 2.0),
                     variance=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5, name=None):
    """RPN anchors over a feature map (reference detection.py:2280).
    Returns (anchors, variances), each (H, W, A, 4) in image coords."""
    sizes = [float(s) for s in np.atleast_1d(anchor_sizes)]
    ratios = [float(r) for r in np.atleast_1d(aspect_ratios)]

    def impl(feat):
        fh, fw = feat.shape[2], feat.shape[3]
        cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * stride[0]
        cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * stride[1]
        cx = jnp.broadcast_to(cx[None, :], (fh, fw))
        cy = jnp.broadcast_to(cy[:, None], (fh, fw))
        whs = []
        for r in ratios:
            for s in sizes:
                area = stride[0] * stride[1]
                area_ratios = area / r
                base_w = round(math.sqrt(area_ratios))
                base_h = round(base_w * r)
                scale_w = s / stride[0]
                scale_h = s / stride[1]
                whs.append((scale_w * base_w, scale_h * base_h))
        wh = jnp.asarray(whs, jnp.float32)
        boxes = jnp.stack([
            cx[..., None] - 0.5 * (wh[None, None, :, 0] - 1),
            cy[..., None] - 0.5 * (wh[None, None, :, 1] - 1),
            cx[..., None] + 0.5 * (wh[None, None, :, 0] - 1),
            cy[..., None] + 0.5 * (wh[None, None, :, 1] - 1),
        ], axis=-1)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               boxes.shape)
        return boxes, var

    return apply(impl, (input,), n_out=2, nondiff=True,
                 name="anchor_generator")


# ---------------------------------------------------------------------------
# YOLO family

def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    """Decode YOLOv3 head output (reference detection.py:1038,
    operators/detection/yolo_box_op.h). x (N, A*(5+C), H, W);
    img_size (N, 2) as (h, w). Returns boxes (N, H*W*A, 4) xyxy in image
    coords and scores (N, H*W*A, C); below-threshold boxes zeroed."""
    anchors = [int(a) for a in anchors]
    na = len(anchors) // 2

    def impl(x, img_size):
        n, c, h, w = x.shape
        x5 = x.reshape(n, na, 5 + class_num, h, w)
        grid_x = jax.lax.broadcasted_iota(jnp.float32, (h, w), 1)
        grid_y = jax.lax.broadcasted_iota(jnp.float32, (h, w), 0)
        bias = -0.5 * (scale_x_y - 1.0)
        bx = (grid_x + jax.nn.sigmoid(x5[:, :, 0]) * scale_x_y + bias) / w
        by = (grid_y + jax.nn.sigmoid(x5[:, :, 1]) * scale_x_y + bias) / h
        aw = jnp.asarray(anchors[0::2], jnp.float32).reshape(1, na, 1, 1)
        ah = jnp.asarray(anchors[1::2], jnp.float32).reshape(1, na, 1, 1)
        input_size = downsample_ratio * h
        bw = jnp.exp(x5[:, :, 2]) * aw / input_size
        bh = jnp.exp(x5[:, :, 3]) * ah / input_size
        conf = jax.nn.sigmoid(x5[:, :, 4])
        probs = jax.nn.sigmoid(x5[:, :, 5:]) * conf[:, :, None]
        img_h = img_size[:, 0].astype(jnp.float32).reshape(n, 1, 1, 1)
        img_w = img_size[:, 1].astype(jnp.float32).reshape(n, 1, 1, 1)
        x1 = (bx - bw / 2) * img_w
        y1 = (by - bh / 2) * img_h
        x2 = (bx + bw / 2) * img_w
        y2 = (by + bh / 2) * img_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0.0, img_w - 1)
            y1 = jnp.clip(y1, 0.0, img_h - 1)
            x2 = jnp.clip(x2, 0.0, img_w - 1)
            y2 = jnp.clip(y2, 0.0, img_h - 1)
        keep = conf > conf_thresh
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
        boxes = jnp.where(keep[..., None], boxes, 0.0)
        probs = jnp.where(keep[..., None], probs.transpose(0, 1, 3, 4, 2),
                          0.0)
        # (N, A, H, W, ·) → (N, H*W*A, ·) matching the reference's
        # anchor-major-within-cell ordering
        boxes = boxes.transpose(0, 2, 3, 1, 4).reshape(n, -1, 4)
        probs = probs.transpose(0, 2, 3, 1, 4).reshape(n, -1, class_num)
        return boxes, probs

    return apply(impl, (x, img_size), n_out=2, name="yolo_box")


def _bce_logits(logit, label):
    # stable sigmoid cross-entropy, matches the reference's
    # SigmoidCrossEntropy in yolov3_loss_op.h
    return jnp.maximum(logit, 0.0) - logit * label + \
        jnp.log1p(jnp.exp(-jnp.abs(logit)))


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference detection.py:912,
    operators/detection/yolov3_loss_op.h). Per-sample loss (N,):

    * xy: sigmoid CE, wh: L1 — each scaled by (2 - gw*gh)·score
    * objectness: sigmoid CE; predictions whose best IoU with any gt
      exceeds ignore_thresh are excluded from the negative term
    * class: sigmoid CE with optional label smoothing

    gt boxes are (N, B, 4) cx/cy/w/h normalized; padded slots have w==0
    or h==0 and are masked out (the LoD-free static-shape contract).
    """
    anchors = [int(a) for a in anchors]
    anchor_mask = [int(a) for a in anchor_mask]
    na = len(anchor_mask)
    has_score = gt_score is not None

    def impl(x, gt_box, gt_label, *rest):
        n, c, h, w = x.shape
        nb = gt_box.shape[1]
        score = rest[0] if has_score else jnp.ones((n, nb), x.dtype)
        x5 = x.reshape(n, na, 5 + class_num, h, w)
        valid = (gt_box[:, :, 2] > 0) & (gt_box[:, :, 3] > 0)  # (N, B)

        # --- decode predictions (normalized cx/cy/w/h) for the ignore mask
        grid_x = jax.lax.broadcasted_iota(jnp.float32, (h, w), 1)
        grid_y = jax.lax.broadcasted_iota(jnp.float32, (h, w), 0)
        bias = -0.5 * (scale_x_y - 1.0)
        px = (grid_x + jax.nn.sigmoid(x5[:, :, 0]) * scale_x_y + bias) / w
        py = (grid_y + jax.nn.sigmoid(x5[:, :, 1]) * scale_x_y + bias) / h
        aw = jnp.asarray([anchors[2 * m] for m in anchor_mask],
                         jnp.float32).reshape(1, na, 1, 1)
        ah = jnp.asarray([anchors[2 * m + 1] for m in anchor_mask],
                         jnp.float32).reshape(1, na, 1, 1)
        input_size = float(downsample_ratio * h)
        pw = jnp.exp(jnp.minimum(x5[:, :, 2], 20.0)) * aw / input_size
        ph = jnp.exp(jnp.minimum(x5[:, :, 3], 20.0)) * ah / input_size
        pred = jnp.stack([px - pw / 2, py - ph / 2, px + pw / 2,
                          py + ph / 2], axis=-1)  # (N,A,H,W,4)
        gtc = jnp.stack([
            gt_box[:, :, 0] - gt_box[:, :, 2] / 2,
            gt_box[:, :, 1] - gt_box[:, :, 3] / 2,
            gt_box[:, :, 0] + gt_box[:, :, 2] / 2,
            gt_box[:, :, 1] + gt_box[:, :, 3] / 2], axis=-1)  # (N,B,4)
        iou = _pairwise_iou(pred.reshape(n, -1, 4), gtc)  # (N,AHW,B)
        iou = jnp.where(valid[:, None, :], iou, 0.0)
        best_iou = jnp.max(iou, axis=-1).reshape(n, na, h, w)
        ignore = best_iou > ignore_thresh

        # --- gt → anchor matching (best over ALL anchors by wh IoU)
        all_aw = jnp.asarray(anchors[0::2], jnp.float32) / input_size
        all_ah = jnp.asarray(anchors[1::2], jnp.float32) / input_size
        gw = gt_box[:, :, 2][..., None]
        gh = gt_box[:, :, 3][..., None]
        inter = jnp.minimum(gw, all_aw) * jnp.minimum(gh, all_ah)
        union = gw * gh + all_aw * all_ah - inter
        wh_iou = inter / jnp.maximum(union, 1e-10)  # (N, B, num_anchors)
        best_n = jnp.argmax(wh_iou, axis=-1)  # (N, B)
        mask_arr = jnp.asarray(anchor_mask)
        an_idx = jnp.argmax(best_n[..., None] == mask_arr, axis=-1)
        matched = jnp.any(best_n[..., None] == mask_arr, axis=-1) & valid
        gi = jnp.clip((gt_box[:, :, 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gt_box[:, :, 1] * h).astype(jnp.int32), 0, h - 1)

        # gather predictions at matched cells: flat index (N, B)
        flat = ((an_idx * h) + gj) * w + gi  # into (A, H, W)
        xf = x5.reshape(n, na, 5 + class_num, h * w)
        xf = xf.transpose(0, 1, 3, 2).reshape(n, na * h * w, 5 + class_num)
        sel = jnp.take_along_axis(xf, flat[..., None], axis=1)  # (N,B,5+C)

        tx = gt_box[:, :, 0] * w - gi.astype(jnp.float32)
        ty = gt_box[:, :, 1] * h - gj.astype(jnp.float32)
        aw_m = jnp.take(all_aw, jnp.clip(best_n, 0, len(anchors) // 2 - 1))
        ah_m = jnp.take(all_ah, jnp.clip(best_n, 0, len(anchors) // 2 - 1))
        tw = jnp.log(jnp.maximum(gt_box[:, :, 2] / aw_m, 1e-10))
        th = jnp.log(jnp.maximum(gt_box[:, :, 3] / ah_m, 1e-10))
        box_scale = (2.0 - gt_box[:, :, 2] * gt_box[:, :, 3]) * score
        loc = (_bce_logits(sel[..., 0], tx) + _bce_logits(sel[..., 1], ty) +
               jnp.abs(sel[..., 2] - tw) + jnp.abs(sel[..., 3] - th))
        loc_loss = jnp.sum(jnp.where(matched, loc * box_scale, 0.0), axis=1)

        if use_label_smooth:
            sw = min(1.0 / class_num, 1.0 / 40.0)
            pos, neg = 1.0 - sw, sw
        else:
            pos, neg = 1.0, 0.0
        onehot = jax.nn.one_hot(gt_label, class_num, dtype=x.dtype)
        tgt = onehot * pos + (1.0 - onehot) * neg
        cls = jnp.sum(_bce_logits(sel[..., 5:], tgt), axis=-1)
        cls_loss = jnp.sum(jnp.where(matched, cls * score, 0.0), axis=1)

        # objectness: positives at matched cells (weight=score), negatives
        # everywhere else unless ignored
        obj_logit = x5[:, :, 4]  # (N, A, H, W)
        pos_map = jnp.zeros((n, na * h * w), x.dtype)
        wsrc = jnp.where(matched, score, 0.0)
        pos_map = pos_map.at[jnp.arange(n)[:, None], flat].max(wsrc)
        pos_map = pos_map.reshape(n, na, h, w)
        is_pos = pos_map > 0
        obj_pos = _bce_logits(obj_logit, 1.0) * pos_map
        obj_neg = jnp.where(is_pos | ignore, 0.0,
                            _bce_logits(obj_logit, 0.0))
        obj_loss = jnp.sum((obj_pos + obj_neg).reshape(n, -1), axis=1)
        return loc_loss + cls_loss + obj_loss

    args = (x, gt_box, gt_label)
    if has_score:
        args = args + (gt_score,)
    return apply(impl, args, name="yolov3_loss")


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    """Focal loss (reference detection.py:455,
    operators/detection/sigmoid_focal_loss_op.h). x (N, C) logits; label
    (N, 1) int in [0, C] where 0 is background; fg_num (1,) normalizer."""
    def impl(x, label, fg_num):
        n, c = x.shape
        lbl = label.reshape(-1)
        fg = jnp.maximum(fg_num.astype(x.dtype).reshape(()), 1.0)
        cls_ids = jnp.arange(1, c + 1)
        tgt = (lbl[:, None] == cls_ids).astype(x.dtype)
        p = jax.nn.sigmoid(x)
        ce = _bce_logits(x, tgt)
        p_t = tgt * p + (1 - tgt) * (1 - p)
        a_t = tgt * alpha + (1 - tgt) * (1 - alpha)
        return a_t * jnp.power(1 - p_t, gamma) * ce / fg

    return apply(impl, (x, label, fg_num), name="sigmoid_focal_loss")


# ---------------------------------------------------------------------------
# matching / assignment

def _encode_center_size(boxes, matched, weights=None, pixel_offset=1.0):
    """Rowwise center-size box-delta encoding (tx, ty, tw, th) of
    `matched` against `boxes`, the shared math behind box_coder encode,
    rpn/retinanet target assignment and proposal labeling (reference:
    box_coder_op.h EncodeCenterSize)."""
    off = pixel_offset
    bw = boxes[..., 2] - boxes[..., 0] + off
    bh = boxes[..., 3] - boxes[..., 1] + off
    bcx = boxes[..., 0] + bw / 2
    bcy = boxes[..., 1] + bh / 2
    mw = matched[..., 2] - matched[..., 0] + off
    mh = matched[..., 3] - matched[..., 1] + off
    tx = ((matched[..., 0] + mw / 2) - bcx) / bw
    ty = ((matched[..., 1] + mh / 2) - bcy) / bh
    tw = jnp.log(jnp.maximum(mw / bw, 1e-10))
    th = jnp.log(jnp.maximum(mh / bh, 1e-10))
    out = jnp.stack([tx, ty, tw, th], axis=-1)
    if weights is not None:
        out = out / jnp.asarray(weights, out.dtype)
    return out


def _greedy_bipartite(dist):
    """Greedy bipartite scan over one (N, M) distance matrix → per-column
    (match_indices, match_dist). Shared by bipartite_match and ssd_loss
    (reference: bipartite_match_op.cc BipartiteMatch)."""
    n, m = dist.shape

    def body(_, carry):
        mi, md, dm = carry
        flat = jnp.argmax(dm)
        i, j = flat // m, flat % m
        ok = dm[i, j] > 0
        mi = jnp.where(ok, mi.at[j].set(i.astype(jnp.int32)), mi)
        md = jnp.where(ok, md.at[j].set(dist[i, j]), md)
        dm = jnp.where(ok, dm.at[i, :].set(-1.0).at[:, j].set(-1.0), dm)
        return mi, md, dm

    mi0 = jnp.full((m,), -1, jnp.int32)
    md0 = jnp.zeros((m,), dist.dtype)
    mi, md, _ = lax.fori_loop(0, min(n, m), body, (mi0, md0, dist))
    return mi, md


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """Greedy bipartite matching (reference detection.py:1218,
    operators/detection/bipartite_match_op.cc). dist (B, N, M) (N gt rows,
    M priors). Returns (match_indices (B, M) int32 — row matched to each
    column, -1 if none — and match_dist (B, M))."""
    per_pred = match_type == "per_prediction"
    thr = 0.5 if dist_threshold is None else float(dist_threshold)

    def one(dist):
        mi, md = _greedy_bipartite(dist)
        if per_pred:
            # second pass: unmatched columns take their best row if the
            # distance clears the threshold
            best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
            best_val = jnp.max(dist, axis=0)
            extra = (mi < 0) & (best_val > thr)
            mi = jnp.where(extra, best_row, mi)
            md = jnp.where(extra, best_val, md)
        return mi, md

    def impl(dist):
        if dist.ndim == 2:
            return one(dist)
        return jax.vmap(one)(dist)

    return apply(impl, (dist_matrix,), n_out=2, nondiff=True,
                 name="bipartite_match")


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    """Gather targets by match indices (reference detection.py:1307).
    input (B, N, K), matched_indices (B, M) → out (B, M, K), weights
    (B, M, 1): mismatch slots get mismatch_value / weight 0."""
    def impl(inp, match):
        idx = jnp.maximum(match, 0)
        out = jnp.take_along_axis(inp, idx[..., None], axis=1)
        matched = (match >= 0)[..., None]
        out = jnp.where(matched, out, mismatch_value)
        wt = matched.astype(inp.dtype)
        return out, wt

    return apply(impl, (input, matched_indices), n_out=2, nondiff=True,
                 name="target_assign")


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True,
             sample_size=None, name=None):
    """SSD multibox loss (reference detection.py:1410). Static-shape
    redesign: gt is (B, G, 4) xyxy normalized + (B, G) labels with padded
    slots marked by all-zero boxes; matching, hard-negative mining
    (max_negative), smooth-L1 loc loss and softmax conf loss all run
    under jit. Returns (B, M) per-prior weighted loss (sum it for the
    scalar)."""
    if mining_type != "max_negative":
        raise NotImplementedError("only max_negative mining on TPU")
    var = list(prior_box_var) if isinstance(prior_box_var, (list, tuple)) \
        else None

    def impl(loc, conf, gt_box, gt_label, prior, *maybe_var):
        b, m, _ = loc.shape
        g = gt_box.shape[1]
        pvar = maybe_var[0] if maybe_var else (
            jnp.asarray(var, loc.dtype) if var is not None
            else jnp.asarray([0.1, 0.1, 0.2, 0.2], loc.dtype))
        valid = jnp.any(jnp.abs(gt_box) > 0, axis=-1)  # (B, G)
        iou = _pairwise_iou(gt_box, jnp.broadcast_to(
            prior[None], (b,) + prior.shape))  # (B, G, M)
        iou = jnp.where(valid[..., None], iou, -1.0)

        # bipartite pass (shared greedy scan)
        match = jax.vmap(lambda d: _greedy_bipartite(d)[0])(iou)  # (B, M)
        if match_type == "per_prediction":
            best_row = jnp.argmax(iou, axis=1).astype(jnp.int32)
            best_val = jnp.max(iou, axis=1)
            extra = (match < 0) & (best_val > overlap_threshold)
            match = jnp.where(extra, best_row, match)
        pos = match >= 0  # (B, M)

        # loc loss: smooth-L1 on encoded offsets, positives only
        gidx = jnp.maximum(match, 0)
        mgt = jnp.take_along_axis(gt_box, gidx[..., None], axis=1)
        pw = prior[:, 2] - prior[:, 0]
        ph = prior[:, 3] - prior[:, 1]
        pcx = prior[:, 0] + pw / 2
        pcy = prior[:, 1] + ph / 2
        gw = mgt[..., 2] - mgt[..., 0]
        gh = mgt[..., 3] - mgt[..., 1]
        gcx = mgt[..., 0] + gw / 2
        gcy = mgt[..., 1] + gh / 2
        pv = jnp.broadcast_to(pvar.reshape(-1, 4), (m, 4))
        tx = (gcx - pcx) / pw / pv[:, 0]
        ty = (gcy - pcy) / ph / pv[:, 1]
        tw = jnp.log(jnp.maximum(gw / pw, 1e-10)) / pv[:, 2]
        th = jnp.log(jnp.maximum(gh / ph, 1e-10)) / pv[:, 3]
        tgt_loc = jnp.stack([tx, ty, tw, th], axis=-1)
        diff = loc - tgt_loc
        sl1 = jnp.where(jnp.abs(diff) < 1.0, 0.5 * diff * diff,
                        jnp.abs(diff) - 0.5).sum(-1)
        loc_loss = jnp.where(pos, sl1, 0.0) * loc_loss_weight

        # conf loss: softmax CE against matched label / background
        mlbl = jnp.take_along_axis(gt_label, gidx, axis=1)
        tgt_cls = jnp.where(pos, mlbl, background_label)
        logp = jax.nn.log_softmax(conf, axis=-1)
        ce = -jnp.take_along_axis(logp, tgt_cls[..., None],
                                  axis=-1)[..., 0]

        # hard negative mining: top (ratio·npos) negatives by conf loss
        npos = jnp.sum(pos, axis=1)  # (B,)
        nneg = jnp.minimum((npos * neg_pos_ratio).astype(jnp.int32),
                           m - npos)
        neg_cand = (~pos) & (jnp.max(iou, axis=1) < neg_overlap)
        neg_score = jnp.where(neg_cand, ce, -jnp.inf)
        order = jnp.argsort(-neg_score, axis=1)
        rank = jnp.argsort(order, axis=1)  # rank of each prior
        neg_sel = rank < nneg[:, None]
        conf_loss = jnp.where(pos | neg_sel, ce, 0.0) * conf_loss_weight

        total = loc_loss + conf_loss
        if normalize:
            total = total / jnp.maximum(npos.astype(loc.dtype),
                                        1.0)[:, None]
        return total

    args = (location, confidence, gt_box, gt_label, prior_box)
    if prior_box_var is not None and var is None:
        args = args + (prior_box_var,)
    return apply(impl, args, name="ssd_loss")


# ---------------------------------------------------------------------------
# NMS family (fixed-size top-k outputs + validity sentinel)

def _nms_keep(boxes, scores, iou_threshold, normalized=True, eta=1.0):
    """Sequential greedy NMS over boxes (K,4) ranked by scores (K,).
    Returns keep mask (K,) bool. O(K²) IoU + lax.fori_loop (static shapes;
    `iou[:, i]` is a dynamic-slice of static size) — jit-safe."""
    k = boxes.shape[0]
    order = jnp.argsort(-scores)
    sb = boxes[order]
    iou = _pairwise_iou(sb, sb, normalized)
    rng = jnp.arange(k)

    def body(i, carry):
        keep, thr = carry
        col = iou[:, i]
        sup = jnp.any((rng < i) & keep & (col > thr))
        ki = keep[i] & ~sup
        keep = keep.at[i].set(ki)
        thr = jnp.where(ki & (eta < 1.0) & (thr > 0.5), thr * eta, thr)
        return keep, thr

    keep0 = scores[order] > -jnp.inf
    keep, _ = lax.fori_loop(
        0, k, body, (keep0, jnp.asarray(iou_threshold, boxes.dtype)))
    inv = jnp.argsort(order)
    return keep[inv]


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None, return_index=False):
    """Multi-class NMS (reference detection.py:3082,
    operators/detection/multiclass_nms_op.cc). bboxes (N, M, 4);
    scores (N, C, M). Static-shape output: (N, keep_top_k, 6) rows
    [label, score, x1, y1, x2, y2] ranked by score with label = -1 in
    empty slots, plus a (N,) count of valid detections (the reference's
    LoD), plus flat indices when return_index."""
    nms_top_k = int(nms_top_k)
    keep_top_k = int(keep_top_k) if keep_top_k > 0 else None

    def impl(bboxes, scores):
        n, c, m = scores.shape
        ktop = min(nms_top_k, m) if nms_top_k > 0 else m

        def per_image(boxes, sc):
            def per_class(cls_scores):
                s = jnp.where(cls_scores > score_threshold, cls_scores,
                              -jnp.inf)
                top_s, top_i = lax.top_k(s, ktop)
                cb = boxes[top_i]
                keep = _nms_keep(cb, top_s, nms_threshold, normalized,
                                 nms_eta) & (top_s > -jnp.inf)
                return jnp.where(keep, top_s, -jnp.inf), top_i
            cls_s, cls_i = jax.vmap(per_class)(sc)  # (C, ktop)
            if background_label >= 0:
                cls_s = cls_s.at[background_label].set(-jnp.inf)
            labels = jnp.broadcast_to(jnp.arange(c)[:, None],
                                      (c, ktop))
            flat_s = cls_s.reshape(-1)
            flat_l = labels.reshape(-1)
            flat_i = cls_i.reshape(-1)
            kk = keep_top_k or flat_s.shape[0]
            kk = min(kk, flat_s.shape[0])
            sel_s, sel = lax.top_k(flat_s, kk)
            sel_l = flat_l[sel]
            sel_b = boxes[flat_i[sel]]
            validk = sel_s > -jnp.inf
            out = jnp.concatenate([
                jnp.where(validk, sel_l, -1).astype(boxes.dtype)[:, None],
                jnp.where(validk, sel_s, 0.0)[:, None],
                jnp.where(validk[:, None], sel_b, 0.0)], axis=-1)
            return out, jnp.sum(validk.astype(jnp.int32)), \
                jnp.where(validk, flat_i[sel], -1)

        out, counts, idx = jax.vmap(per_image)(bboxes, scores)
        return (out, counts, idx) if return_index else (out, counts)

    return apply(impl, (bboxes, scores), n_out=3 if return_index else 2,
                 nondiff=True, name="multiclass_nms")


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     name=None):
    """SSD inference head: decode + multiclass NMS (reference
    detection.py:541). loc (N, M, 4) offsets; scores (N, M, C) softmax-ed
    here; priors (M, 4)+(M, 4). Returns ((N, keep_top_k, 6), (N,))."""
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size", axis=0)
    probs = apply(lambda s: jax.nn.softmax(s, axis=-1).transpose(0, 2, 1),
                  (scores,), name="softmax_transpose")
    return multiclass_nms(decoded, probs, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold, True, nms_eta,
                          background_label)


# ---------------------------------------------------------------------------
# RoI ops

def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_num=None,
              name=None):
    """RoI Align (reference detection.py:2381 roi_* family,
    operators/roi_align_op.h). input (N, C, H, W); rois (R, 4) xyxy in
    input-image coords; rois_num (N,) counts per image (defaults to all
    rois on image 0 — the LoD-free contract). Bilinear sampling averaged
    over a per-bin sample grid.

    Static-shape note: the reference's adaptive sampling_ratio<=0 mode
    sizes the grid per-roi (ceil(roi/pool)) — a data-dependent shape XLA
    cannot compile. Here sampling_ratio<=0 uses a FIXED 2×2 grid per bin
    (the detectron default, and exact for rois up to 2× the pooled size);
    pass an explicit sampling_ratio for denser grids."""
    sr = int(sampling_ratio)

    def impl(x, rois, *maybe_num):
        n, c, h, w = x.shape
        r = rois.shape[0]
        if maybe_num:
            # rois_num (N,): counts per image → batch index per roi
            counts = maybe_num[0]
            batch_idx = jnp.repeat(jnp.arange(n), counts, axis=0,
                                   total_repeat_length=r)
        else:
            batch_idx = jnp.zeros((r,), jnp.int32)
        x1 = rois[:, 0] * spatial_scale
        y1 = rois[:, 1] * spatial_scale
        x2 = rois[:, 2] * spatial_scale
        y2 = rois[:, 3] * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pooled_width
        bin_h = rh / pooled_height
        gx = sr if sr > 0 else 2
        gy = sr if sr > 0 else 2

        # sample coords (R, PH, PW, gy, gx)
        py = jnp.arange(pooled_height, dtype=x.dtype)
        px = jnp.arange(pooled_width, dtype=x.dtype)
        sy = (jnp.arange(gy, dtype=x.dtype) + 0.5) / gy
        sx = (jnp.arange(gx, dtype=x.dtype) + 0.5) / gx
        yy = y1[:, None, None] + (py[None, :, None] + sy[None, None, :]) * \
            bin_h[:, None, None]  # (R, PH, gy)
        xx = x1[:, None, None] + (px[None, :, None] + sx[None, None, :]) * \
            bin_w[:, None, None]  # (R, PW, gx)

        def bilinear(img, ys, xs):
            # img (C, H, W); ys (PH, gy); xs (PW, gx) →  (C, PH, PW)
            y0 = jnp.clip(jnp.floor(ys), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xs), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
            y0i = y0.astype(jnp.int32)
            x0i = x0.astype(jnp.int32)
            ly = jnp.clip(ys - y0, 0.0, 1.0)
            lx = jnp.clip(xs - x0, 0.0, 1.0)
            # gather rows then cols: (C, PH, gy, W) → (C, PH, gy, PW, gx)
            def gy_(img, yi):
                return img[:, yi, :]  # (C, PH, gy, W)
            r0 = gy_(img, y0i)
            r1 = gy_(img, y1i)
            def gx_(rows, xi):
                return rows[:, :, :, xi]  # (C, PH, gy, PW, gx)
            v00 = gx_(r0, x0i)
            v01 = gx_(r0, x1i)
            v10 = gx_(r1, x0i)
            v11 = gx_(r1, x1i)
            ly_ = ly[None, :, :, None, None]
            lx_ = lx[None, None, None, :, :]
            val = (v00 * (1 - ly_) * (1 - lx_) + v01 * (1 - ly_) * lx_ +
                   v10 * ly_ * (1 - lx_) + v11 * ly_ * lx_)
            return jnp.mean(val, axis=(2, 4))  # avg over sample grid

        imgs = x[batch_idx]  # (R, C, H, W)
        out = jax.vmap(bilinear)(imgs, yy, xx)
        return out  # (R, C, PH, PW)

    args = (input, rois)
    if rois_num is not None:
        args = args + (rois_num,)
    return apply(impl, args, name="roi_align")


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_num=None, name=None):
    """RoI max pooling (reference operators/roi_pool_op.h). Same contract
    as roi_align but hard bin edges + max."""
    def impl(x, rois, *maybe_num):
        n, c, h, w = x.shape
        r = rois.shape[0]
        if maybe_num:
            counts = maybe_num[0]
            batch_idx = jnp.repeat(jnp.arange(n), counts, axis=0,
                                   total_repeat_length=r)
        else:
            batch_idx = jnp.zeros((r,), jnp.int32)
        x1 = jnp.round(rois[:, 0] * spatial_scale)
        y1 = jnp.round(rois[:, 1] * spatial_scale)
        x2 = jnp.round(rois[:, 2] * spatial_scale)
        y2 = jnp.round(rois[:, 3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)

        ygrid = jnp.arange(h, dtype=x.dtype)
        xgrid = jnp.arange(w, dtype=x.dtype)

        def one(img, x1_, y1_, rw_, rh_):
            # bin index of every pixel row/col for this roi; outside → -1.
            # Separable two-stage masked max (rows then cols) keeps the
            # largest intermediate at (PH, C, W) — never the (C,PH,PW,H,W)
            # broadcast a joint mask would need.
            by = jnp.floor((ygrid - y1_) * pooled_height / rh_)
            bx = jnp.floor((xgrid - x1_) * pooled_width / rw_)
            by = jnp.where((ygrid >= y1_) & (ygrid <= y1_ + rh_ - 1), by,
                           -1.0)
            bx = jnp.where((xgrid >= x1_) & (xgrid <= x1_ + rw_ - 1), bx,
                           -1.0)
            rowmax = []
            for p in range(pooled_height):
                msk = (by == p)[None, :, None]  # (1, H, 1)
                rowmax.append(jnp.max(jnp.where(msk, img, -jnp.inf),
                                      axis=1))  # (C, W)
            rows = jnp.stack(rowmax)  # (PH, C, W)
            colmax = []
            for q in range(pooled_width):
                msk = (bx == q)[None, None, :]  # (1, 1, W)
                colmax.append(jnp.max(jnp.where(msk, rows, -jnp.inf),
                                      axis=2))  # (PH, C)
            out = jnp.stack(colmax, axis=-1)  # (PH, C, PW)
            out = jnp.transpose(out, (1, 0, 2))  # (C, PH, PW)
            return jnp.where(jnp.isfinite(out), out, 0.0)

        imgs = x[batch_idx]
        return jax.vmap(one)(imgs, x1, y1, rw, rh)

    args = (input, rois)
    if rois_num is not None:
        args = args + (rois_num,)
    return apply(impl, args, name="roi_pool")


# ---------------------------------------------------------------------------
# proposals

def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    """RPN proposal generation (reference detection.py:2745). Static-shape:
    returns (N, post_nms_top_n, 4) proposals + (N, post_nms_top_n) scores
    (invalid slots score 0). scores (N, A, H, W); bbox_deltas
    (N, 4A, H, W); anchors/variances (H, W, A, 4)."""
    def impl(scores, deltas, im_info, anchors, variances):
        n, a, h, w = scores.shape
        sc = scores.transpose(0, 2, 3, 1).reshape(n, -1)  # (N, HWA)
        dl = deltas.reshape(n, a, 4, h, w).transpose(0, 3, 4, 1, 2) \
            .reshape(n, -1, 4)
        anc = anchors.reshape(-1, 4)
        varr = variances.reshape(-1, 4)
        k = min(pre_nms_top_n, sc.shape[1])

        def per_image(s, d, im):
            top_s, top_i = lax.top_k(s, k)
            an = anc[top_i]
            va = varr[top_i]
            de = d[top_i]
            aw = an[:, 2] - an[:, 0] + 1.0
            ah_ = an[:, 3] - an[:, 1] + 1.0
            acx = an[:, 0] + aw / 2
            acy = an[:, 1] + ah_ / 2
            cx = va[:, 0] * de[:, 0] * aw + acx
            cy = va[:, 1] * de[:, 1] * ah_ + acy
            bw = jnp.exp(jnp.minimum(va[:, 2] * de[:, 2], 30.0)) * aw
            bh = jnp.exp(jnp.minimum(va[:, 3] * de[:, 3], 30.0)) * ah_
            props = jnp.stack([cx - bw / 2, cy - bh / 2,
                               cx + bw / 2 - 1, cy + bh / 2 - 1], -1)
            hh, ww = im[0] - 1.0, im[1] - 1.0
            props = jnp.stack([
                jnp.clip(props[:, 0], 0, ww), jnp.clip(props[:, 1], 0, hh),
                jnp.clip(props[:, 2], 0, ww), jnp.clip(props[:, 3], 0, hh),
            ], -1)
            ms = min_size * im[2]
            keep_sz = ((props[:, 2] - props[:, 0] + 1 >= ms) &
                       (props[:, 3] - props[:, 1] + 1 >= ms))
            s2 = jnp.where(keep_sz, top_s, -jnp.inf)
            keep = _nms_keep(props, s2, nms_thresh, False, eta) & \
                (s2 > -jnp.inf)
            s3 = jnp.where(keep, s2, -jnp.inf)
            kk = min(post_nms_top_n, k)
            fs, fi = lax.top_k(s3, kk)
            fp = props[fi]
            ok = fs > -jnp.inf
            return jnp.where(ok[:, None], fp, 0.0), jnp.where(ok, fs, 0.0)

        return jax.vmap(per_image)(sc, dl, im_info)

    return apply(impl, (scores, bbox_deltas, im_info, anchors, variances),
                 n_out=2, nondiff=True, name="generate_proposals")


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    """Assign RoIs to FPN levels (reference detection.py:3363). Static
    shape: returns per-level (R, 4) roi tensors where off-level rows are
    zeroed + a mask list + restore index."""
    nlvl = max_level - min_level + 1

    def impl(rois):
        w = rois[:, 2] - rois[:, 0]
        h = rois[:, 3] - rois[:, 1]
        scale = jnp.sqrt(jnp.maximum(w * h, 1e-10))
        lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6)) + refer_level
        lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
        outs = []
        for L in range(min_level, max_level + 1):
            m = (lvl == L)
            outs.append(jnp.where(m[:, None], rois, 0.0))
            outs.append(m)
        order = jnp.argsort(lvl)
        restore = jnp.argsort(order)
        return tuple(outs) + (restore,)

    return apply(impl, (fpn_rois,), n_out=2 * nlvl + 1, nondiff=True,
                 name="distribute_fpn_proposals")


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None,
                          name=None):
    """Merge per-level RoIs by score (reference detection.py:3519). Inputs
    are lists of (R_i, 4)/(R_i,) tensors; output (post_nms_top_n, 4)."""
    k = len(multi_rois)

    def impl(*args):
        rois = jnp.concatenate(args[:k], axis=0)
        scores = jnp.concatenate(args[k:], axis=0)
        kk = min(int(post_nms_top_n), scores.shape[0])
        top_s, top_i = lax.top_k(scores, kk)
        return rois[top_i], top_s

    return apply(impl, tuple(multi_rois) + tuple(multi_scores), n_out=2,
                 nondiff=True, name="collect_fpn_proposals")


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1,
                   name=None, min_max_aspect_ratios_order=False):
    """SSD multibox head (reference detection.py:1991): conv loc/conf
    predictions + priors for a list of feature maps. Returns
    (mbox_locs (N, M, 4), mbox_confs (N, M, C), priors (M, 4), vars)."""
    from . import nn_ops as F
    from .. import nn as nn_mod

    nin = len(inputs)
    if min_sizes is None:
        # the reference's ratio interpolation
        min_sizes, max_sizes = [], []
        mr, xr = int(min_ratio), int(max_ratio)
        step = int(math.floor((xr - mr) / (nin - 2))) if nin > 2 else 0
        for ratio in range(mr, xr + 1, max(step, 1)):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes[:nin - 1]
        max_sizes = [base_size * 0.2] + max_sizes[:nin - 1]

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i]
        xs = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i] if isinstance(aspect_ratios[0],
                                            (list, tuple)) else aspect_ratios
        st = steps[i] if steps else (
            (step_w[i] if step_w else 0.0, step_h[i] if step_h else 0.0))
        if not isinstance(st, (list, tuple)):
            st = (st, st)
        pb, pv = prior_box(feat, image, ms, xs, ar, variance, flip, clip,
                           st, offset,
                           min_max_aspect_ratios_order=
                           min_max_aspect_ratios_order)
        npri = int(np.prod(pb.shape[:-1]) // (pb.shape[0] * pb.shape[1]))
        boxes_all.append(pb.reshape([-1, 4]))
        vars_all.append(pv.reshape([-1, 4]))
        cin = feat.shape[1]
        loc_conv = nn_mod.Conv2D(cin, npri * 4, kernel_size, stride=stride,
                                 padding=pad)
        conf_conv = nn_mod.Conv2D(cin, npri * num_classes, kernel_size,
                                  stride=stride, padding=pad)
        loc = loc_conv(feat).transpose([0, 2, 3, 1]).reshape([
            feat.shape[0], -1, 4])
        conf = conf_conv(feat).transpose([0, 2, 3, 1]).reshape([
            feat.shape[0], -1, num_classes])
        locs.append(loc)
        confs.append(conf)

    from .manip import concat
    return (concat(locs, axis=1), concat(confs, axis=1),
            concat(boxes_all, axis=0), concat(vars_all, axis=0))
