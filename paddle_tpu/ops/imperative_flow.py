"""Imperative control-flow classes + tensor arrays.

TPU-native rebuild of the reference's class-style control flow
(reference: python/paddle/fluid/layers/control_flow.py — IfElse:2678,
Switch:2521, DynamicRNN:2854, array_write:1375, array_read:1604,
array_length:1744, create_array:1177).

Redesign notes (the reference builds conditional sub-*blocks* that run on
a row subset; XLA wants dense static-shape compute):

* **IfElse** — the reference physically partitions rows by the condition,
  runs each sub-block on its subset and merges. Here both branches compute
  densely over ALL rows and `ie()` merges rowwise with `where(cond, t, f)`
  — identical results for rowwise branch bodies, no dynamic shapes, and
  both branches' FLOPs overlap on the MXU (the same trade `lax.cond`
  makes under vmap).
* **Switch** — the reference's case-blocks guard `assign` side effects.
  Here `assign(x, output=var)` calls inside an active case register
  (condition, value) pairs and the exit of the Switch writes a single
  first-match-wins `where`-chain — works eagerly and records one fused op
  under tracing/static mode (the LR-schedule pattern).
* **DynamicRNN** — the reference iterates LoD sequences step-by-step in a
  C++ while op. Here sequences are padded (B, T, ...) + lengths, and the
  step body (recorded once as a mini static Program by the `block()`
  context) runs under `lax.scan`; outputs past a sequence's length hold
  the last valid state, matching LoD semantics for the `()`/last-state
  readouts.
* **Tensor arrays** — a Python-backed list (`TensorArray`): concrete
  indices write/read eagerly; `stack()` bridges into jit-land. The
  reference's dynamic LoDTensorArray+While pattern maps to `lax.scan`
  (see nn/rnn.py) — inside compiled loops carry stacked tensors instead.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..tensor import Tensor, as_tensor
from ..dispatch import apply

__all__ = ["IfElse", "Switch", "While", "DynamicRNN", "TensorArray",
           "create_array", "array_write", "array_read", "array_length"]


# ---------------------------------------------------------------------------
# tensor arrays

class TensorArray:
    """LoDTensorArray stand-in: list of Tensors + stack bridge."""

    def __init__(self):
        self._items = []

    def append(self, x):
        self._items.append(as_tensor(x))

    def __len__(self):
        return len(self._items)

    def __getitem__(self, i):
        return self._items[i]

    def __setitem__(self, i, v):
        if i == len(self._items):
            self._items.append(as_tensor(v))
        else:
            self._items[i] = as_tensor(v)

    def stack(self, axis=0):
        from .manip import stack as stack_op
        return stack_op(list(self._items), axis=axis)


def create_array(dtype="float32"):
    """reference: control_flow.py:1177 create_array."""
    return TensorArray()


def _concrete_index(i):
    if isinstance(i, Tensor):
        i = i.data
    if isinstance(i, jax.core.Tracer):
        raise ValueError(
            "tensor-array indices must be concrete (python int or eager "
            "tensor); inside compiled loops carry stacked tensors through "
            "lax.scan instead (see paddle_tpu.nn.rnn)")
    return int(np.asarray(jax.device_get(i)).item()) \
        if not isinstance(i, int) else i


def array_write(x, i, array=None):
    """reference: control_flow.py:1375."""
    if array is None:
        array = TensorArray()
    array[_concrete_index(i)] = x
    return array


def array_read(array, i):
    """reference: control_flow.py:1604."""
    return array[_concrete_index(i)]


def array_length(array):
    """reference: control_flow.py:1744."""
    from .creation import assign
    return assign(np.asarray(len(array), "i8"))


# ---------------------------------------------------------------------------
# IfElse

class IfElse:
    """Rowwise conditional (reference control_flow.py:2678). cond is
    (N, 1) bool; both blocks run densely and ie() merges rowwise."""

    def __init__(self, cond, name=None):
        self.cond = as_tensor(cond)
        self._true_out = None
        self._false_out = None
        self._phase = None

    @contextlib.contextmanager
    def true_block(self):
        self._phase = True
        yield
        self._phase = None

    @contextlib.contextmanager
    def false_block(self):
        self._phase = False
        yield
        self._phase = None

    def input(self, x):
        """The reference slices x to the rows matching the phase; dense
        redesign returns x whole (merge happens in __call__)."""
        if self._phase is None:
            raise ValueError("IfElse.input() outside true_block/false_block")
        return as_tensor(x)

    def output(self, *outs):
        if self._phase is None:
            raise ValueError("IfElse.output() outside a block")
        outs = tuple(as_tensor(o) for o in outs)
        if self._phase:
            self._true_out = outs
        else:
            self._false_out = outs

    def __call__(self):
        if self._true_out is None or self._false_out is None:
            raise ValueError("both true_block and false_block must set "
                             "output() before calling IfElse()")

        results = []
        for t, f in zip(self._true_out, self._false_out):
            def impl(c, t, f):
                cb = c
                while cb.ndim < t.ndim:
                    cb = cb[..., None]
                return jnp.where(cb.astype(bool), t, f)

            results.append(apply(impl, (self.cond, t, f), name="ifelse"))
        return results if len(results) > 1 else [results[0]]


# ---------------------------------------------------------------------------
# Switch

_active_switch = []


class Switch:
    """First-match-wins conditional assignment (reference
    control_flow.py:2521; the LR-warmup pattern). `assign(value,
    output=var)` inside case blocks registers instead of writing; exit
    merges with a where-chain."""

    def __init__(self, name=None):
        # target id → list of (cond or None, value); None cond = default
        self._cases = {}
        self._targets = {}
        self._current_cond = None
        self._in_default = False

    def __enter__(self):
        _active_switch.append(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        _active_switch.pop()
        if exc_type is not None:
            return False
        for tid, entries in self._cases.items():
            target = self._targets[tid]
            conds = [c for c, _ in entries if c is not None]
            vals = [v for c, v in entries if c is not None]
            defaults = [v for c, v in entries if c is None]
            base = defaults[-1] if defaults else target

            def impl(base, *cv):
                n = len(cv) // 2
                out = base
                # reverse order → earlier cases win
                for c, v in reversed(list(zip(cv[:n], cv[n:]))):
                    out = jnp.where(c.astype(bool), v, out)
                return out

            merged = apply(impl, (base,) + tuple(conds) + tuple(vals),
                           name="switch_merge")
            target.set_value(merged.data if isinstance(merged, Tensor)
                             else merged)
        return False

    @contextlib.contextmanager
    def case(self, condition):
        if self._in_default or self._current_cond is not None:
            raise ValueError("nested Switch cases are not supported")
        self._current_cond = as_tensor(condition)
        yield
        self._current_cond = None

    @contextlib.contextmanager
    def default(self):
        self._in_default = True
        yield
        self._in_default = False

    def _register(self, value, target):
        tid = id(target)
        self._targets[tid] = target
        cond = self._current_cond if not self._in_default else None
        self._cases.setdefault(tid, []).append((cond, as_tensor(value)))

    @staticmethod
    def active():
        return _active_switch[-1] if _active_switch else None

    @staticmethod
    def in_case_block():
        sw = Switch.active()
        return sw is not None and (sw._current_cond is not None or
                                   sw._in_default)


# ---------------------------------------------------------------------------
# While (block-style)

class While:
    """Block-style while (reference control_flow.py:While). The reference
    records the block into a sub-program consumed by the C++ while op; the
    eager redesign runs the block as a plain python loop over a CONCRETE
    condition variable that block code updates in place (assign/set_value)
    — the pattern every fluid While example uses. For compiled
    data-dependent loops use ops.while_loop / the AST to_static pass."""

    def __init__(self, cond, is_test=False, name=None):
        self.cond = as_tensor(cond)
        self._body = None

    @contextlib.contextmanager
    def block(self):
        recorded = []
        token = _WhileRecorder(recorded)
        _while_stack.append(token)
        try:
            yield
        finally:
            _while_stack.pop()
        import numpy as _np
        import jax as _jax

        def concrete(c):
            return bool(_np.asarray(_jax.device_get(c.data)).item())

        # strict contract: the body MUST go through While.record — raw
        # statements in the with-block would have executed once already
        # (python `with` semantics), which breaks the cond-initially-
        # False case; enforcing record keeps semantics exact.
        if not recorded:
            raise ValueError(
                "While.block: register the loop body with "
                "While.record(fn) inside the block (raw statements in "
                "the block run once regardless of the condition), or "
                "use ops.while_loop / the AST to_static pass")
        while concrete(self.cond):
            for fn in recorded:
                fn()

    @staticmethod
    def record(fn):
        """Register the loop body callable (executed while cond holds)."""
        if _while_stack:
            _while_stack[-1].recorded.append(fn)
        return fn


class _WhileRecorder:
    def __init__(self, recorded):
        self.recorded = recorded


_while_stack = []


# ---------------------------------------------------------------------------
# DynamicRNN

class DynamicRNN:
    """Sequence RNN over padded (B, T, ...) inputs (reference
    control_flow.py:2854). The `block()` context records the step body
    once as a mini static Program; `__call__` interprets it per-step under
    `lax.scan` with the memories as carry. Steps past `lengths` freeze the
    memory (LoD parity: shorter sequences stop early).

    Usage (reference-shaped)::

        drnn = DynamicRNN()
        with drnn.block():
            w = drnn.step_input(sentence, lengths)   # (B, T, D) + (B,)
            prev = drnn.memory(shape=(H,), value=0.0)
            h = some_layers(w, prev)
            drnn.update_memory(prev, h)
            drnn.output(h)
        outs = drnn()            # (B, T, H) stacked step outputs
        last = drnn.last_state() # (B, H) state at each row's length
    """

    def __init__(self, name=None):
        self._program = None
        self._inputs = []      # (var_name, tensor (B, T, ...))
        self._lengths = None
        self._memories = []    # (var_name, init value (B, ...))
        self._updates = {}     # memory var_name -> new var_name
        self._outputs = []     # var names
        self._static_inputs = []  # (var_name, tensor (B, ...))
        self._batch = None
        self._result = None

    # -- block recording ----------------------------------------------------
    @contextlib.contextmanager
    def block(self):
        from .. import static as pstatic
        from .. import dispatch
        self._program = pstatic.Program()
        startup = pstatic.Program()
        was_static = dispatch.in_static_mode()
        with pstatic.program_guard(self._program, startup):
            if not was_static:
                dispatch.set_static_mode(True)
            try:
                yield
            finally:
                if not was_static:
                    dispatch.set_static_mode(False)

    def _data(self, shape, dtype, prefix):
        from ..static import data as sdata
        name = self._program._unique_name(prefix)
        return sdata(name, shape, dtype)

    def step_input(self, x, level=0, lengths=None):
        x = as_tensor(x)
        if x.data is None:
            raise ValueError("step_input needs an eager padded (B, T, ...)"
                             " tensor")
        b, t = x.data.shape[:2]
        self._batch = b
        if lengths is not None:
            self._lengths = as_tensor(lengths)
        var = self._data([None] + list(x.data.shape[2:]),
                         str(x.data.dtype), "drnn_step_in")
        self._inputs.append((var.name, x))
        return var

    def static_input(self, x):
        x = as_tensor(x)
        var = self._data([None] + list(x.data.shape[1:]),
                         str(x.data.dtype), "drnn_static_in")
        self._static_inputs.append((var.name, x))
        return var

    def memory(self, init=None, shape=None, value=0.0, dtype="float32"):
        if init is not None:
            init = as_tensor(init)
            arr = init.data
        else:
            if self._batch is None:
                raise ValueError("call step_input before memory(shape=...)"
                                 " so the batch size is known")
            arr = jnp.full((self._batch,) + tuple(shape), value,
                           dtype=dtype)
        var = self._data([None] + list(arr.shape[1:]), str(arr.dtype),
                         "drnn_mem")
        self._memories.append((var.name, Tensor(arr)))
        return var

    def update_memory(self, mem, new):
        self._updates[mem.name] = new.name

    def output(self, *outs):
        self._outputs.extend(o.name for o in outs)

    # -- execution ----------------------------------------------------------
    def _interpret(self, env):
        for op in self._program.global_block().ops:
            ins = []
            for n in op.inputs:
                if n in env:
                    ins.append(env[n])
                elif n in self._program.param_vars:
                    ins.append(self._program.param_vars[n].data)
                else:
                    ins.append(self._program.const_vars[n].data)
            outs = op.impl(*ins, **op.attrs)
            if isinstance(outs, (tuple, list)):
                for n, o in zip(op.outputs, outs):
                    env[n] = o
            else:
                env[op.outputs[0]] = outs
        return env

    def _run(self):
        if self._result is not None:
            return self._result
        if not self._inputs:
            raise ValueError("DynamicRNN has no step_input")
        mem_names = [n for n, _ in self._memories]
        out_names = list(self._outputs)
        updates = dict(self._updates)
        static_env = {n: t for n, t in self._static_inputs}
        t_len = self._inputs[0][1].data.shape[1]

        seqs = tuple(t for _, t in self._inputs)
        mems = tuple(t for _, t in self._memories)
        statics = tuple(t for _, t in self._static_inputs)
        has_len = self._lengths is not None
        len_args = (self._lengths,) if has_len else ()

        def impl(*arrays):
            ns, nm, nst = len(seqs), len(mems), len(statics)
            seq_a = arrays[:ns]
            mem_a = arrays[ns:ns + nm]
            st_a = arrays[ns + nm:ns + nm + nst]
            lengths = arrays[-1] if has_len else None

            def run_body(mem_vals, xs0, st_vals):
                env = {}
                for (name, _), x in zip(self._inputs, xs0):
                    env[name] = x
                for (name, _), m in zip(self._memories, mem_vals):
                    env[name] = m
                for (name, _), s in zip(self._static_inputs, st_vals):
                    env[name] = s
                env = self._interpret(env)
                return env

            def step(carry, xs):
                t, mem_vals, prev_outs = carry
                env = run_body(mem_vals, xs, st_a)
                alive_row = None if lengths is None else (t < lengths)

                def freeze(new, old):
                    if alive_row is None:
                        return new
                    al = alive_row.reshape((-1,) + (1,) * (new.ndim - 1))
                    return jnp.where(al, new, old)

                new_mems = tuple(
                    freeze(env.get(updates.get(name, name), old), old)
                    for name, old in zip(mem_names, mem_vals))
                # outputs freeze past each row's length too (LoD parity:
                # step t >= len(row) re-emits the last valid output)
                outs = tuple(freeze(env[n], po)
                             for n, po in zip(out_names, prev_outs))
                return (t + 1, new_mems, outs), outs

            xs = tuple(jnp.moveaxis(s, 0, 1) for s in seq_a)  # (T, B, ...)
            # zero-init the "previous output" carry from an abstract probe
            # of one step body (shapes only — nothing executes)
            probe = jax.eval_shape(
                lambda mems, x0, st: tuple(
                    run_body(mems, x0, st)[n] for n in out_names),
                tuple(mem_a), tuple(x[0] for x in xs), st_a)
            prev0 = tuple(jnp.zeros(av.shape, av.dtype) for av in probe)
            (t_fin, last, _), ys = lax.scan(
                step, (0, tuple(mem_a), prev0), xs)
            ys = tuple(jnp.moveaxis(y, 0, 1) for y in ys)  # (B, T, ...)
            return ys + last

        flat_in = seqs + mems + statics + len_args
        n_out = len(out_names) + len(mem_names)
        res = apply(impl, flat_in, n_out=n_out, name="dynamic_rnn")
        if not isinstance(res, tuple):
            res = (res,)
        self._result = (res[:len(out_names)], res[len(out_names):])
        return self._result

    def __call__(self):
        outs, _ = self._run()
        return outs if len(outs) > 1 else outs[0]

    def last_state(self):
        _, mems = self._run()
        return mems if len(mems) > 1 else mems[0]
