"""paddle_tpu.ops.math — elementwise math, reductions, linear algebra.

TPU-native rebuild of the reference's math operators
(reference: paddle/fluid/operators/elementwise/*, reduce_ops/*, matmul_op.cc,
activation_op.cc; python surface in python/paddle/fluid/layers/{nn,ops,
tensor}.py). One pure-jax impl per op, dispatched through
paddle_tpu.dispatch.apply so the same definition serves dygraph (tape),
to_static (traced), and static Program recording. Matmuls stay big and
batched for the MXU; no per-element Python.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..tensor import Tensor, as_tensor, convert_dtype
from ..dispatch import apply

# ---------------------------------------------------------------------------
# binary elementwise (numpy broadcasting, like reference elementwise ops)

def _promote(x, y):
    return x, y


def _bin(name, fn):
    def op(x, y, name=None):
        return apply(fn, (x, y), name=name or op.__name__)
    op.__name__ = name
    return op


elementwise_add = add = _bin("add", lambda x, y: jnp.add(x, y))
elementwise_sub = subtract = _bin("subtract", lambda x, y: jnp.subtract(x, y))
elementwise_mul = multiply = _bin("multiply", lambda x, y: jnp.multiply(x, y))
elementwise_div = divide = _bin("divide", lambda x, y: jnp.divide(x, y))
elementwise_pow = pow = _bin("pow", lambda x, y: jnp.power(x, y))
elementwise_mod = mod = remainder = _bin("mod", lambda x, y: jnp.mod(x, y))
elementwise_floordiv = floor_divide = _bin(
    "floor_divide", lambda x, y: jnp.floor_divide(x, y))
elementwise_max = maximum = _bin("maximum", lambda x, y: jnp.maximum(x, y))
elementwise_min = minimum = _bin("minimum", lambda x, y: jnp.minimum(x, y))
atan2 = _bin("atan2", lambda x, y: jnp.arctan2(x, y))


# ---------------------------------------------------------------------------
# unary elementwise (reference: activation_op.cc + ops.py one-liners)

def _un(name, fn, nondiff=False):
    def op(x, name=None, **kw):
        return apply(fn, (x,), attrs=kw, nondiff=nondiff,
                     name=name or op.__name__)
    op.__name__ = name
    return op


exp = _un("exp", jnp.exp)
log = _un("log", jnp.log)
log2 = _un("log2", jnp.log2)
log10 = _un("log10", jnp.log10)
log1p = _un("log1p", jnp.log1p)
sqrt = _un("sqrt", jnp.sqrt)
rsqrt = _un("rsqrt", lax.rsqrt)
square = _un("square", jnp.square)
abs = _un("abs", jnp.abs)
neg = negative = _un("negative", jnp.negative)
reciprocal = _un("reciprocal", jnp.reciprocal)
sin = _un("sin", jnp.sin)
cos = _un("cos", jnp.cos)
tan = _un("tan", jnp.tan)
asin = arcsin = _un("asin", jnp.arcsin)
acos = arccos = _un("acos", jnp.arccos)
atan = arctan = _un("atan", jnp.arctan)
sinh = _un("sinh", jnp.sinh)
cosh = _un("cosh", jnp.cosh)
tanh = _un("tanh", jnp.tanh)
asinh = _un("asinh", jnp.arcsinh)
acosh = _un("acosh", jnp.arccosh)
atanh = _un("atanh", jnp.arctanh)
ceil = _un("ceil", jnp.ceil)
floor = _un("floor", jnp.floor)
round = _un("round", jnp.round)
trunc = _un("trunc", jnp.trunc)
sign = _un("sign", jnp.sign)
erf = _un("erf", jax.scipy.special.erf)
expm1 = _un("expm1", jnp.expm1)
logical_not = _un("logical_not", jnp.logical_not, nondiff=True)
isnan = _un("isnan", jnp.isnan, nondiff=True)
isinf = _un("isinf", jnp.isinf, nondiff=True)
isfinite = _un("isfinite", jnp.isfinite, nondiff=True)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, name=None):
    """reference: paddle/fluid/operators/scale_op.cc"""
    def impl(x, scale, bias, bias_after_scale):
        if bias_after_scale:
            return x * scale + bias
        return (x + bias) * scale
    return apply(impl, (x,), dict(scale=scale, bias=bias,
                                  bias_after_scale=bias_after_scale),
                 name="scale")


def clip(x, min=None, max=None, name=None):
    """reference: clip_op.cc"""
    return apply(lambda x, lo, hi: jnp.clip(x, lo, hi), (x,),
                 dict(lo=min, hi=max), name="clip")


def cast(x, dtype):
    """reference: cast_op.cc"""
    dt = convert_dtype(dtype)
    return apply(lambda x, dt: x.astype(dt), (x,), dict(dt=dt), name="cast")


# ---------------------------------------------------------------------------
# comparisons / logical (nondiff; reference: controlflow/compare_op.cc)

def _binn(name, fn):
    def op(x, y, name=None):
        return apply(fn, (x, y), nondiff=True, name=name or op.__name__)
    op.__name__ = name
    return op


equal = _binn("equal", lambda x, y: jnp.equal(x, y))
not_equal = _binn("not_equal", lambda x, y: jnp.not_equal(x, y))
less_than = _binn("less_than", lambda x, y: jnp.less(x, y))
less_equal = _binn("less_equal", lambda x, y: jnp.less_equal(x, y))
greater_than = _binn("greater_than", lambda x, y: jnp.greater(x, y))
greater_equal = _binn("greater_equal", lambda x, y: jnp.greater_equal(x, y))
logical_and = _binn("logical_and", lambda x, y: jnp.logical_and(x, y))
logical_or = _binn("logical_or", lambda x, y: jnp.logical_or(x, y))
logical_xor = _binn("logical_xor", lambda x, y: jnp.logical_xor(x, y))


# ---------------------------------------------------------------------------
# reductions (reference: reduce_ops/reduce_{sum,mean,max,min,prod}_op)

def _axis_attr(axis, keepdim):
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return dict(axis=axis, keepdims=keepdim)


def sum(x, axis=None, keepdim=False, dtype=None, name=None):
    dt = convert_dtype(dtype)
    return apply(lambda x, axis, keepdims: jnp.sum(
        x if dt is None else x.astype(dt), axis=axis, keepdims=keepdims),
        (x,), _axis_attr(axis, keepdim), name="reduce_sum")


def mean(x, axis=None, keepdim=False, name=None):
    return apply(lambda x, axis, keepdims: jnp.mean(x, axis=axis,
                                                    keepdims=keepdims),
                 (x,), _axis_attr(axis, keepdim), name="reduce_mean")


def max(x, axis=None, keepdim=False, name=None):
    return apply(lambda x, axis, keepdims: jnp.max(x, axis=axis,
                                                   keepdims=keepdims),
                 (x,), _axis_attr(axis, keepdim), name="reduce_max")


def min(x, axis=None, keepdim=False, name=None):
    return apply(lambda x, axis, keepdims: jnp.min(x, axis=axis,
                                                   keepdims=keepdims),
                 (x,), _axis_attr(axis, keepdim), name="reduce_min")


def prod(x, axis=None, keepdim=False, name=None):
    return apply(lambda x, axis, keepdims: jnp.prod(x, axis=axis,
                                                    keepdims=keepdims),
                 (x,), _axis_attr(axis, keepdim), name="reduce_prod")


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply(lambda x, axis, keepdims: jax.scipy.special.logsumexp(
        x, axis=axis, keepdims=keepdims), (x,), _axis_attr(axis, keepdim),
        name="logsumexp")


def all(x, axis=None, keepdim=False, name=None):
    return apply(lambda x, axis, keepdims: jnp.all(x, axis=axis,
                                                   keepdims=keepdims),
                 (x,), _axis_attr(axis, keepdim), nondiff=True, name="all")


def any(x, axis=None, keepdim=False, name=None):
    return apply(lambda x, axis, keepdims: jnp.any(x, axis=axis,
                                                   keepdims=keepdims),
                 (x,), _axis_attr(axis, keepdim), nondiff=True, name="any")


def cumsum(x, axis=None, name=None):
    def impl(x, axis):
        if axis is None:
            return jnp.cumsum(x.reshape(-1))
        return jnp.cumsum(x, axis=axis)
    return apply(impl, (x,), dict(axis=axis), name="cumsum")


def cumprod(x, dim=None, name=None):
    return apply(lambda x, axis: jnp.cumprod(x, axis=axis), (x,),
                 dict(axis=dim), name="cumprod")


# ---------------------------------------------------------------------------
# argmax / argmin / argsort / topk / sort (reference: arg_max_op.cc, top_k_op)

def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = convert_dtype(dtype)
    def impl(x, axis, keepdims):
        out = jnp.argmax(x, axis=axis).astype(dt)
        if keepdims and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out
    return apply(impl, (x,), dict(axis=axis, keepdims=keepdim), nondiff=True,
                 name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = convert_dtype(dtype)
    def impl(x, axis, keepdims):
        out = jnp.argmin(x, axis=axis).astype(dt)
        if keepdims and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out
    return apply(impl, (x,), dict(axis=axis, keepdims=keepdim), nondiff=True,
                 name="argmin")


def argsort(x, axis=-1, descending=False, name=None):
    def impl(x, axis, descending):
        idx = jnp.argsort(-x if descending else x, axis=axis)
        return idx.astype(convert_dtype("int64"))
    return apply(impl, (x,), dict(axis=axis, descending=descending),
                 nondiff=True, name="argsort")


def sort(x, axis=-1, descending=False, name=None):
    def impl(x, axis, descending):
        out = jnp.sort(x, axis=axis)
        if descending:
            out = jnp.flip(out, axis=axis)
        return out
    return apply(impl, (x,), dict(axis=axis, descending=descending),
                 name="sort")


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    """reference: top_k_op.cc — returns (values, indices)."""
    def impl(x, k, axis, largest):
        xm = jnp.moveaxis(x, axis, -1)
        if largest:
            v, i = lax.top_k(xm, k)
        else:
            v, i = lax.top_k(-xm, k)
            v = -v
        return jnp.moveaxis(v, -1, axis), jnp.moveaxis(i, -1, axis).astype(convert_dtype("int64"))
    out = apply(impl, (x,), dict(k=k, axis=axis, largest=largest), n_out=2,
                name="top_k")
    out[1].stop_gradient = True
    return out


# ---------------------------------------------------------------------------
# linear algebra (MXU path — keep matmuls batched, let XLA tile)

def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    """reference: matmul_op.cc. Batched matmul with optional transposes;
    lowers to a single dot_general on the MXU. AMP white-listed."""
    from .. import amp
    if amp.is_enabled():
        dt = amp.compute_dtype()
        x, y = cast(x, dt), cast(y, dt)
    def impl(x, y, transpose_x, transpose_y, alpha):
        if transpose_x:
            x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
        if transpose_y:
            y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
        out = jnp.matmul(x, y)
        if alpha != 1.0:
            out = out * alpha
        return out
    return apply(impl, (x, y), dict(transpose_x=transpose_x,
                                    transpose_y=transpose_y, alpha=alpha),
                 name="matmul")


mm = matmul


def dot(x, y, name=None):
    def impl(x, y):
        return jnp.sum(x * y, axis=-1)
    return apply(impl, (x, y), name="dot")


def bmm(x, y, name=None):
    return apply(lambda x, y: jnp.matmul(x, y), (x, y), name="bmm")


def t(x, name=None):
    return apply(lambda x: x.T, (x,), name="t")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply(lambda i, x, y, beta, alpha: beta * i + alpha * (x @ y),
                 (input, x, y), dict(beta=beta, alpha=alpha), name="addmm")


def norm(x, p=2, axis=None, keepdim=False, name=None):
    def impl(x, p, axis, keepdims):
        if p == "fro" or p == 2:
            return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis,
                                    keepdims=keepdims))
        if p == 1:
            return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims)
        if p == float("inf"):
            return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdims)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis,
                                 keepdims=keepdims), 1.0 / p)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply(impl, (x,), dict(p=p, axis=ax, keepdims=keepdim),
                 name="norm")


# ---------------------------------------------------------------------------
# misc

def where(condition, x, y, name=None):
    """reference: where_op / select. condition is nondiff."""
    def impl(c, x, y):
        return jnp.where(c, x, y)
    return apply(impl, (condition, x, y), name="where")


def maximum_(x, y):
    return maximum(x, y)


def increment(x, value=1.0, name=None):
    """reference: increment_op.cc — in static mode this mutates the var; in
    dygraph we return x + value and also update in place."""
    out = apply(lambda x, value: x + value, (x,), dict(value=value),
                name="increment")
    return out


def accuracy_top1(pred, label):
    """Helper used by metrics: fraction of argmax==label."""
    def impl(pred, label):
        p = jnp.argmax(pred, axis=-1)
        return jnp.mean((p == label.reshape(p.shape)).astype(jnp.float32))
    return apply(impl, (pred, label), nondiff=True, name="accuracy")


# ---------------------------------------------------------------------------
# paddle 2.0-alpha top-level tensor API (reference: python/paddle/tensor/
# {math,linalg,logic,search,creation}.py — the names python/paddle/__init__
# exported in the v1.7 tree)

def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    """reference: allclose_op.cc"""
    return apply(lambda x, y: jnp.allclose(x, y, rtol=rtol, atol=atol,
                                           equal_nan=equal_nan),
                 (x, y), name="allclose")


def addcmul(input, tensor1, tensor2, value=1.0, name=None):
    """reference: paddle/tensor/math.py:addcmul — input + value*t1*t2."""
    return apply(lambda a, b, c: a + value * b * c,
                 (input, tensor1, tensor2), name="addcmul")


def cholesky(x, upper=False, name=None):
    """reference: cholesky_op.cc (cuSOLVER there; XLA's blocked Cholesky
    here — MXU-shaped panels on TPU)."""
    def impl(x):
        L = jnp.linalg.cholesky(x)
        return jnp.swapaxes(L, -1, -2) if upper else L
    return apply(impl, (x,), name="cholesky")


def inverse(x, name=None):
    """reference: inverse_op.cc"""
    return apply(lambda x: jnp.linalg.inv(x), (x,), name="inverse")


def cross(x, y, axis=None, name=None):
    """reference: cross_op.cc — axis=None means the FIRST axis whose
    length is 3 (paddle contract), not the last."""
    def impl(x, y):
        ax = axis
        if ax is None:
            ax = next((i for i, d in enumerate(x.shape) if d == 3), None)
            if ax is None:
                raise ValueError("cross: no axis of length 3 found")
        return jnp.cross(x, y, axis=ax)
    return apply(impl, (x, y), name="cross")


def dist(x, y, p=2, name=None):
    """reference: dist_op.cc — p-norm of (x - y)."""
    def impl(x, y):
        d = (x - y).ravel()
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        if p == 0:
            return jnp.sum(d != 0).astype(x.dtype)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)
    return apply(impl, (x, y), name="dist")


def kron(x, y, name=None):
    """reference: kron_op.cc"""
    return apply(lambda x, y: jnp.kron(x, y), (x, y), name="kron")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    """reference: trace_op.cc"""
    return apply(lambda x: jnp.trace(x, offset=offset, axis1=axis1,
                                     axis2=axis2), (x,), name="trace")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    """reference: paddle/tensor/stat.py:std"""
    return apply(lambda x: jnp.std(x, axis=axis, ddof=1 if unbiased else 0,
                                   keepdims=keepdim), (x,), name="std")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    """reference: paddle/tensor/stat.py:var"""
    return apply(lambda x: jnp.var(x, axis=axis, ddof=1 if unbiased else 0,
                                   keepdims=keepdim), (x,), name="var")


def index_sample(x, index, name=None):
    """reference: index_sample_op.cc — per-row gather x[i, index[i, j]]."""
    return apply(lambda x, ix: jnp.take_along_axis(
        x, ix.astype(jnp.int32), axis=1), (x, index), name="index_sample")


def nonzero(x, as_tuple=False, name=None):
    """reference: where_index_op (nonzero). Dynamic-shaped output → host
    sync (documented; use masks inside jit)."""
    import numpy as _np
    arr = _np.asarray(jax.device_get(
        x.data if hasattr(x, "data") else x))
    idx = _np.nonzero(arr)
    from ..tensor import Tensor
    if as_tuple:
        return tuple(Tensor(_np.asarray(i)[:, None]) for i in idx)
    return Tensor(_np.stack(idx, axis=1).astype("int64"))


def is_empty(x, name=None):
    """reference: is_empty_op.cc"""
    n = 1
    for d in x.shape:
        n *= d
    from ..tensor import Tensor
    return Tensor(jnp.asarray(n == 0))


def rank(input, name=None):
    """reference: rank of the tensor (ndim)."""
    from ..tensor import Tensor
    return Tensor(jnp.asarray(len(input.shape), jnp.int32))


def shape(input, name=None):
    """reference: shape_op.cc"""
    from ..tensor import Tensor
    return Tensor(jnp.asarray(input.shape, jnp.int32))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    """reference: stanh_op.cc — b * tanh(a * x)."""
    return apply(lambda x: scale_b * jnp.tanh(scale_a * x), (x,),
                 name="stanh")


def elementwise_sum(inputs, name=None):
    """reference: sum_op.cc over a list."""
    def impl(*xs):
        acc = xs[0]
        for x in xs[1:]:
            acc = acc + x
        return acc
    return apply(impl, tuple(inputs), name="elementwise_sum")


def elementwise_equal(x, y, name=None):
    """reference: equal op (elementwise)."""
    return apply(lambda x, y: x == y, (x, y), name="elementwise_equal")


def has_inf(x, name=None):
    """reference: isinf_op"""
    return apply(lambda x: jnp.any(jnp.isinf(x)), (x,), name="has_inf")


def has_nan(x, name=None):
    """reference: isnan_op"""
    return apply(lambda x: jnp.any(jnp.isnan(x)), (x,), name="has_nan")


def crop_tensor(x, shape=None, offsets=None, name=None):
    """reference: crop_tensor_op.cc — static slice."""
    def impl(x):
        offs = offsets or [0] * x.ndim
        shp = shape or list(x.shape)
        idx = tuple(slice(o, o + (x.shape[i] - o if s in (None, -1) else s))
                    for i, (o, s) in enumerate(zip(offs, shp)))
        return x[idx]
    return apply(impl, (x,), name="crop_tensor")


clamp = clip
mul = multiply
div = divide
