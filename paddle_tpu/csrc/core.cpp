// paddle_tpu native host runtime core.
//
// TPU-native rebuild of the reference's C++ host-side memory + input
// pipeline (reference: paddle/fluid/memory/detail/buddy_allocator.cc +
// allocation/auto_growth_best_fit_allocator.cc for the arena;
// paddle/fluid/operators/reader/buffered_reader.cc + fluid/framework/
// data_feed.cc for the threaded feeding pipeline).
//
// On TPU, device memory belongs to XLA's arena, so the native runtime's
// job is the HOST side: a pinned, aligned arena for staging batches, and a
// background-thread batcher that shuffles + assembles contiguous batches
// off the GIL so the Python step loop never blocks on memcpy.
//
// Built as libpaddle_tpu_core.so (plain C ABI, driven via ctypes — the
// reference used pybind11; ctypes keeps the build dependency-free).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// arena allocator: bump allocator over one big aligned region with reset
// semantics (the reference's auto-growth allocator reduced to the staging
// use-case: per-step transient host buffers).

struct Arena {
  char* base;
  size_t capacity;
  std::atomic<size_t> offset;
  std::atomic<size_t> peak;
};

void* ptc_arena_create(size_t bytes) {
  Arena* a = new Arena();
  if (posix_memalign(reinterpret_cast<void**>(&a->base), 4096, bytes) != 0) {
    delete a;
    return nullptr;
  }
  a->capacity = bytes;
  a->offset.store(0);
  a->peak.store(0);
  return a;
}

void ptc_arena_destroy(void* arena) {
  Arena* a = static_cast<Arena*>(arena);
  if (a == nullptr) return;
  free(a->base);
  delete a;
}

void* ptc_arena_alloc(void* arena, size_t bytes, size_t align) {
  Arena* a = static_cast<Arena*>(arena);
  if (align == 0) align = 64;
  size_t cur, aligned, next;
  do {
    cur = a->offset.load(std::memory_order_relaxed);
    aligned = (cur + align - 1) & ~(align - 1);
    next = aligned + bytes;
    if (next > a->capacity) return nullptr;
  } while (!a->offset.compare_exchange_weak(cur, next));
  size_t prev_peak = a->peak.load(std::memory_order_relaxed);
  while (next > prev_peak &&
         !a->peak.compare_exchange_weak(prev_peak, next)) {
  }
  return a->base + aligned;
}

void ptc_arena_reset(void* arena) {
  static_cast<Arena*>(arena)->offset.store(0);
}

size_t ptc_arena_used(void* arena) {
  return static_cast<Arena*>(arena)->offset.load();
}

size_t ptc_arena_peak(void* arena) {
  return static_cast<Arena*>(arena)->peak.load();
}

// ---------------------------------------------------------------------------
// multithreaded row gather: dst[i] = src[idx[i]] for row-major tables.

void ptc_gather_rows(const char* src, size_t row_bytes, const int64_t* idx,
                     size_t n_idx, char* dst, int n_threads) {
  if (n_threads <= 1 || n_idx < 1024) {
    for (size_t i = 0; i < n_idx; ++i) {
      memcpy(dst + i * row_bytes, src + idx[i] * row_bytes, row_bytes);
    }
    return;
  }
  std::vector<std::thread> threads;
  size_t chunk = (n_idx + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    size_t lo = t * chunk;
    size_t hi = lo + chunk < n_idx ? lo + chunk : n_idx;
    if (lo >= hi) break;
    threads.emplace_back([=]() {
      for (size_t i = lo; i < hi; ++i) {
        memcpy(dst + i * row_bytes, src + idx[i] * row_bytes, row_bytes);
      }
    });
  }
  for (auto& t : threads) t.join();
}

// ---------------------------------------------------------------------------
// batcher: background thread shuffles indices (Fisher-Yates over a
// xoshiro256** stream) and assembles batches for every feature array into
// slot buffers; the consumer pops finished slots from a bounded queue.

struct Slot {
  std::vector<char*> buffers;  // one per feature array
  size_t rows;
};

struct Batcher {
  std::vector<const char*> arrays;
  std::vector<size_t> row_bytes;
  size_t n_rows;
  size_t batch;
  bool shuffle;
  bool drop_last;
  uint64_t seed;
  uint64_t epoch;

  std::vector<Slot> slots;
  std::queue<int> free_q;
  std::queue<int> ready_q;
  std::mutex mu;
  std::condition_variable cv_free, cv_ready;
  std::thread worker;
  std::atomic<bool> stop;
  std::atomic<bool> epoch_done;
  std::vector<int64_t> perm;

  ~Batcher() {
    stop.store(true);
    cv_free.notify_all();
    if (worker.joinable()) worker.join();
    for (auto& s : slots)
      for (auto* b : s.buffers) free(b);
  }
};

static uint64_t splitmix64(uint64_t& x) {
  uint64_t z = (x += 0x9E3779B97f4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

static void fill_perm(Batcher* b) {
  b->perm.resize(b->n_rows);
  for (size_t i = 0; i < b->n_rows; ++i) b->perm[i] = (int64_t)i;
  if (b->shuffle) {
    uint64_t s = b->seed + 0x9E3779B97f4A7C15ULL * (b->epoch + 1);
    for (size_t i = b->n_rows - 1; i > 0; --i) {
      size_t j = splitmix64(s) % (i + 1);
      std::swap(b->perm[i], b->perm[j]);
    }
  }
}

static void worker_loop(Batcher* b) {
  fill_perm(b);
  size_t n_batches =
      b->drop_last ? b->n_rows / b->batch
                   : (b->n_rows + b->batch - 1) / b->batch;
  for (size_t bi = 0; bi < n_batches && !b->stop.load(); ++bi) {
    int slot_id;
    {
      std::unique_lock<std::mutex> lk(b->mu);
      b->cv_free.wait(lk, [&] { return !b->free_q.empty() || b->stop; });
      if (b->stop.load()) return;
      slot_id = b->free_q.front();
      b->free_q.pop();
    }
    Slot& s = b->slots[slot_id];
    size_t lo = bi * b->batch;
    size_t hi = lo + b->batch < b->n_rows ? lo + b->batch : b->n_rows;
    s.rows = hi - lo;
    for (size_t ai = 0; ai < b->arrays.size(); ++ai) {
      char* dst = s.buffers[ai];
      const char* src = b->arrays[ai];
      size_t rb = b->row_bytes[ai];
      for (size_t r = 0; r < s.rows; ++r) {
        memcpy(dst + r * rb, src + b->perm[lo + r] * rb, rb);
      }
    }
    {
      std::lock_guard<std::mutex> lk(b->mu);
      b->ready_q.push(slot_id);
    }
    b->cv_ready.notify_one();
  }
  {
    std::lock_guard<std::mutex> lk(b->mu);
    b->ready_q.push(-1);  // end-of-epoch sentinel
  }
  b->cv_ready.notify_one();
}

void* ptc_batcher_create(const void** arrays, const size_t* row_bytes,
                         int n_arrays, size_t n_rows, size_t batch_size,
                         int shuffle, int drop_last, uint64_t seed,
                         int prefetch_slots) {
  Batcher* b = new Batcher();
  for (int i = 0; i < n_arrays; ++i) {
    b->arrays.push_back(static_cast<const char*>(arrays[i]));
    b->row_bytes.push_back(row_bytes[i]);
  }
  b->n_rows = n_rows;
  b->batch = batch_size;
  b->shuffle = shuffle != 0;
  b->drop_last = drop_last != 0;
  b->seed = seed;
  b->epoch = 0;
  b->stop.store(false);
  if (prefetch_slots < 2) prefetch_slots = 2;
  b->slots.resize(prefetch_slots);
  for (int s = 0; s < prefetch_slots; ++s) {
    for (int i = 0; i < n_arrays; ++i) {
      char* buf;
      if (posix_memalign(reinterpret_cast<void**>(&buf), 4096,
                         batch_size * row_bytes[i]) != 0) {
        delete b;
        return nullptr;
      }
      b->slots[s].buffers.push_back(buf);
    }
    b->free_q.push(s);
  }
  b->worker = std::thread(worker_loop, b);
  return b;
}

// Returns slot id >= 0 with per-array pointers in out_ptrs and row count
// in out_rows; returns -1 at end of epoch.
int ptc_batcher_next(void* batcher, void** out_ptrs, size_t* out_rows) {
  Batcher* b = static_cast<Batcher*>(batcher);
  int slot_id;
  {
    std::unique_lock<std::mutex> lk(b->mu);
    b->cv_ready.wait(lk, [&] { return !b->ready_q.empty(); });
    slot_id = b->ready_q.front();
    b->ready_q.pop();
  }
  if (slot_id < 0) return -1;
  Slot& s = b->slots[slot_id];
  for (size_t i = 0; i < s.buffers.size(); ++i) out_ptrs[i] = s.buffers[i];
  *out_rows = s.rows;
  return slot_id;
}

void ptc_batcher_release(void* batcher, int slot_id) {
  Batcher* b = static_cast<Batcher*>(batcher);
  {
    std::lock_guard<std::mutex> lk(b->mu);
    b->free_q.push(slot_id);
  }
  b->cv_free.notify_one();
}

void ptc_batcher_new_epoch(void* batcher) {
  Batcher* b = static_cast<Batcher*>(batcher);
  if (b->worker.joinable()) b->worker.join();
  b->epoch += 1;
  // drain queues back to a clean state
  {
    std::lock_guard<std::mutex> lk(b->mu);
    while (!b->ready_q.empty()) {
      int s = b->ready_q.front();
      b->ready_q.pop();
      if (s >= 0) b->free_q.push(s);
    }
  }
  b->worker = std::thread(worker_loop, b);
}

void ptc_batcher_destroy(void* batcher) {
  delete static_cast<Batcher*>(batcher);
}


// ---------------------------------------------------------------------------
// MultiSlot text parser (reference: paddle/fluid/framework/data_feed.cc
// MultiSlotDataFeed::ParseOneInstance — the C++ hot path of the CTR
// ingest pipeline; rebuilt here as a single-pass strtod/strtoll token
// stream so fluid.dataset does not pay python-level tokenization).
//
// Format: whitespace-separated tokens; per record, for each of n_slots:
// an integer count then that many values. Line boundaries are plain
// whitespace (the format is self-describing via counts).
//
// out_vals holds 8-byte lanes: double for float slots, int64 bit
// patterns for slots flagged in slot_is_int (exact for full int64
// range, unlike a float64 round-trip). out_counts is [n_records x
// n_slots]. Returns the record count, or -1 on malformed input.

long long ptc_multislot_parse(const char* text, size_t len, int n_slots,
                              const int* slot_is_int,
                              double* out_vals, long long* out_counts,
                              long long max_vals, long long max_recs,
                              long long* n_vals_out) {
  const char* p = text;
  const char* end = text + len;
  long long rec = 0, vi = 0;
  auto skip_ws = [&]() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' ||
                       *p == '\r')) ++p;
  };
  // every token must END at whitespace/EOF: a partial numeric parse
  // ('1.5' read as count 1) would silently misalign the whole stream
  auto at_boundary = [&](const char* q) {
    return q >= end || *q == ' ' || *q == '\t' || *q == '\n' ||
           *q == '\r' || *q == '\0';
  };
  while (true) {
    skip_ws();
    if (p >= end) break;
    if (rec >= max_recs) return -1;
    for (int s = 0; s < n_slots; ++s) {
      skip_ws();
      char* q = nullptr;
      long long cnt = strtoll(p, &q, 10);
      // cnt > max_vals - vi also rejects strtoll's LLONG_MAX overflow
      // clamp without ever computing vi + cnt (signed-overflow UB)
      if (q == p || !at_boundary(q) || cnt < 0 ||
          cnt > max_vals - vi) return -1;
      p = q;
      out_counts[rec * n_slots + s] = cnt;
      for (long long i = 0; i < cnt; ++i) {
        skip_ws();
        if (p >= end) return -1;  // truncated record
        if (slot_is_int[s]) {
          long long v = strtoll(p, &q, 10);
          if (q == p || !at_boundary(q)) return -1;
          memcpy(&out_vals[vi], &v, sizeof v);
        } else {
          double v = strtod(p, &q);
          if (q == p || !at_boundary(q)) return -1;
          out_vals[vi] = v;
        }
        p = q;
        ++vi;
      }
    }
    ++rec;
  }
  *n_vals_out = vi;
  return rec;
}

}  // extern "C"

