"""paddle_tpu.initializer — parameter initializers.

TPU-native rebuild of the reference's initializer families
(reference: python/paddle/fluid/initializer.py — Constant, Uniform, Normal,
TruncatedNormal, Xavier, MSRA, Bilinear, NumpyArrayInitializer). Instead of
appending fill ops to a startup Program, each initializer is a pure function
``(key, shape, dtype) -> jax.Array`` driven by the global threaded PRNG.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from .tensor import convert_dtype, get_default_dtype
from . import random as prandom


class Initializer:
    def __call__(self, shape, dtype=None, key=None):
        dtype = convert_dtype(dtype) or get_default_dtype()
        if key is None:
            key = prandom.next_key()
        return self._init(key, tuple(shape), dtype)

    def _init(self, key, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _init(self, key, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def _init(self, key, shape, dtype):
        return jax.random.uniform(key, shape, dtype, self.low, self.high)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _init(self, key, shape, dtype):
        return jax.random.normal(key, shape, dtype) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _init(self, key, shape, dtype):
        return jax.random.truncated_normal(key, -2.0, 2.0, shape,
                                           dtype) * self.std + self.mean


def _fans(shape):
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    elif len(shape) >= 3:
        # conv weight OIHW / (out, in, *spatial)
        receptive = int(np.prod(shape[2:]))
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
    else:
        fan_in = fan_out = int(np.prod(shape))
    return fan_in, fan_out


class XavierUniform(Initializer):
    """reference: initializer.py XavierInitializer(uniform=True)"""
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _init(self, key, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, dtype, -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _init(self, key, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(key, shape, dtype) * std


class KaimingUniform(Initializer):
    """reference: MSRAInitializer(uniform=True)"""
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in

    def _init(self, key, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        limit = math.sqrt(6.0 / fi)
        return jax.random.uniform(key, shape, dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in

    def _init(self, key, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        std = math.sqrt(2.0 / fi)
        return jax.random.normal(key, shape, dtype) * std


class Bilinear(Initializer):
    """reference: BilinearInitializer — for conv-transpose upsampling."""
    def _init(self, key, shape, dtype):
        weight = np.zeros(shape, dtype=np.float32)
        if len(shape) != 4:
            raise ValueError("Bilinear expects a 4-D conv weight")
        f = math.ceil(shape[3] / 2)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            idx = np.unravel_index(i, shape)
            weight[idx] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return jnp.asarray(weight, dtype)


class Assign(Initializer):
    """reference: NumpyArrayInitializer"""
    def __init__(self, value):
        self.value = np.asarray(value)

    def _init(self, key, shape, dtype):
        if tuple(self.value.shape) != tuple(shape):
            raise ValueError(
                f"Assign initializer shape {self.value.shape} != {shape}")
        return jnp.asarray(self.value, dtype)


# fluid-style aliases
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
XavierInitializer = XavierUniform
MSRAInitializer = KaimingNormal
NumpyArrayInitializer = Assign


def _resolve(init, default=None):
    """Accept Initializer instances, None, or numbers (→Constant)."""
    if init is None:
        return default
    if isinstance(init, Initializer):
        return init
    if isinstance(init, (int, float)):
        return Constant(float(init))
    raise TypeError(f"cannot interpret initializer: {init!r}")

# fluid-era aliases (reference: initializer.py __all__). The reference
# classes take uniform= selecting the uniform/normal variant (default
# True); these factories dispatch accordingly.
def Xavier(uniform=True, fan_in=None, fan_out=None, seed=0, gain=1.0):
    """reference: XavierInitializer(uniform=True, fan_in, fan_out)."""
    cls = XavierUniform if uniform else XavierNormal
    return cls(fan_in=fan_in, fan_out=fan_out, gain=gain)


def MSRA(uniform=True, fan_in=None, seed=0):
    """reference: MSRAInitializer(uniform=True, fan_in)."""
    cls = KaimingUniform if uniform else KaimingNormal
    return cls(fan_in=fan_in)


BilinearInitializer = Bilinear
