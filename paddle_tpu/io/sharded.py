"""paddle_tpu.io.sharded — per-shard checkpoints with a checksummed
manifest and topology-elastic restore.

The monolithic :class:`~paddle_tpu.io.CheckpointManager` pickle path
writes one blob from one process — a single lost host (or a pod resize)
loses the run. This module is the sharded contract underneath
``CheckpointManager(sharded=True)``:

* **save**: each process writes only the unique data shards it owns —
  one ``.npy`` per (pytree leaf, mesh shard), keyed by the leaf's live
  ``NamedSharding``/``PartitionSpec`` (``parallel.layout``), plus a
  ``manifest.json`` recording the global tree structure, per-shard
  sha256 + byte counts, the saving mesh's signature, and the step. The
  whole checkpoint is staged in a ``.tmp-<pid>`` directory and
  committed with one ``os.replace`` — a SIGKILL mid-save leaves a stray
  tmp dir, never a half-visible checkpoint.
* **restore**: reads the manifest, verifies every shard's checksum,
  reassembles the global arrays, and reshards them onto the *current*
  mesh even when its topology differs from the one that saved (dp×tp
  resize, replica-count change). A missing or corrupt shard fails
  validation as a unit — the manager quarantines that checkpoint and
  falls back to the newest *complete* one (``ckpt.quorum_fallback``);
  there is no partial load.

Monitor series: ``ckpt.shard_bytes`` (counter), ``ckpt.shard_seconds``
(histogram, per-shard write time), ``ckpt.restore_resharded`` (restores
that landed on a different topology), ``ckpt.quorum_fallback``. Fault
kinds ``shard_corrupt`` / ``shard_slow_write``
(:mod:`paddle_tpu.resilience.faults`) hit the write path so the failure
handling is deterministically testable. Per-shard I/O retries transient
OS errors under :mod:`paddle_tpu.resilience.retry`.

Single-controller note: with one process (the CPU test topology and
single-host TPU), that process owns every shard and the manifest; on a
multi-process pod each process writes its ``replica_id == 0`` shards
whose device is local, and process 0 writes the manifest.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import time

import numpy as np
import jax

from ..tensor import Tensor
from .. import monitor as _monitor
from ..parallel import layout as _layout

MANIFEST = "manifest.json"
FORMAT_VERSION = 1


def _sha256_file(path, chunk=1 << 20):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _is_array_leaf(x):
    return isinstance(x, (Tensor, jax.Array, np.ndarray))


def _encode_tree(node, leaves):
    """Nested state → JSON structure; array leaves become ``{"leaf": id}``
    references into the manifest's leaf table (the *global tree
    structure* the restore side rebuilds)."""
    if _is_array_leaf(node):
        leaves.append(node)
        return {"t": "leaf", "id": len(leaves) - 1}
    if isinstance(node, dict):
        return {"t": "dict",
                "items": {str(k): _encode_tree(v, leaves)
                          for k, v in node.items()}}
    if isinstance(node, (list, tuple)):
        return {"t": "list" if isinstance(node, list) else "tuple",
                "items": [_encode_tree(v, leaves) for v in node]}
    if isinstance(node, np.generic):
        return {"t": "val", "v": node.item()}
    if isinstance(node, (bool, int, float, str)) or node is None:
        return {"t": "val", "v": node}
    raise TypeError(
        f"sharded checkpoint cannot serialize a {type(node).__name__} "
        "leaf — state trees must hold arrays/Tensors and JSON scalars")


def _decode_tree(node, leaf_values):
    t = node["t"]
    if t == "leaf":
        return leaf_values[node["id"]]
    if t == "dict":
        return {k: _decode_tree(v, leaf_values)
                for k, v in node["items"].items()}
    if t in ("list", "tuple"):
        seq = [_decode_tree(v, leaf_values) for v in node["items"]]
        return seq if t == "list" else tuple(seq)
    return node["v"]


def _unique_shards(arr):
    """[(bounds, host_array)] covering `arr` exactly once. A NamedSharded
    jax.Array contributes its ``replica_id == 0`` shards (the unique
    data, deduped across replicas); anything else is one full shard."""
    if isinstance(arr, jax.Array) and _layout.spec_of(arr) is not None \
            and arr.is_fully_addressable:
        out = []
        for s in arr.addressable_shards:
            if s.replica_id != 0:
                continue
            out.append((_layout.shard_index_bounds(s.index, arr.shape),
                        np.asarray(s.data)))
        if out:
            return out
    host = np.asarray(jax.device_get(arr) if isinstance(arr, jax.Array)
                      else arr)
    return [(_layout.shard_index_bounds(
        tuple(slice(None) for _ in host.shape), host.shape), host)]


def _write_shard(path, data, step=None):
    """One shard write: fault-injectable, retried, fsynced, metered."""
    from ..resilience import faults as _faults
    from ..resilience import retry as _retry

    def _write():
        if _faults.enabled():
            _faults.maybe_sleep("shard_slow_write", step)
        with open(path, "wb") as f:
            np.save(f, data, allow_pickle=False)
            f.flush()
            os.fsync(f.fileno())

    t0 = time.perf_counter()
    _retry.retry_call(_write, label="ckpt_shard_write")
    if _monitor.enabled():
        _monitor.counter("ckpt.shard_bytes").inc(int(data.nbytes))
        _monitor.histogram("ckpt.shard_seconds").observe(
            time.perf_counter() - t0)


def save_state(dirname, state, step=None, mesh=None):
    """Write `state` (a nested dict/list tree of Tensors/arrays and JSON
    scalars) as a sharded checkpoint directory at `dirname`. Atomic:
    stages under ``<dirname>.tmp-<pid>`` and commits via ``os.replace``.
    Returns the manifest dict."""
    from ..parallel import collective as _collective
    from ..resilience import faults as _faults
    mesh = mesh if mesh is not None else _collective.get_mesh()
    final = os.path.abspath(dirname)
    tmp = f"{final}.tmp-{os.getpid()}"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)

    leaves = []
    tree = _encode_tree(state, leaves)
    leaf_table = []
    fileno = 0
    for i, leaf in enumerate(leaves):
        arr = leaf.data if isinstance(leaf, Tensor) else leaf
        spec = _layout.spec_of(arr)
        shape = tuple(int(d) for d in np.shape(arr))
        dtype = str(arr.dtype) if hasattr(arr, "dtype") \
            else str(np.asarray(arr).dtype)
        shard_recs = []
        for bounds, data in _unique_shards(arr):
            fn = f"s{fileno:05d}.npy"
            fileno += 1
            fpath = os.path.join(tmp, fn)
            _write_shard(fpath, data, step=step)
            shard_recs.append({
                "file": fn, "index": bounds,
                "bytes": int(os.path.getsize(fpath)),
                "sha256": _sha256_file(fpath)})
        leaf_table.append({
            "id": i, "shape": list(shape), "dtype": dtype,
            "spec": _layout.spec_to_lists(spec, len(shape))
            if spec is not None else None,
            "shards": shard_recs})

    manifest = {
        "format": FORMAT_VERSION,
        "step": None if step is None else int(step),
        "process_index": int(jax.process_index()),
        "mesh": _layout.mesh_signature(mesh),
        "tree": tree,
        "leaves": leaf_table,
    }
    mpath = os.path.join(tmp, MANIFEST)
    blob = json.dumps(manifest, sort_keys=True).encode()
    with open(mpath, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    with open(mpath + ".sha256", "w", encoding="utf-8") as f:
        f.write(hashlib.sha256(blob).hexdigest() + "\n")

    if os.path.isdir(final):
        # re-save of the same step: swap the old dir out from under the
        # name, then drop it — the name never points at a partial state
        old = f"{final}.old-{os.getpid()}"
        os.replace(final, old)
        os.replace(tmp, final)
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.replace(tmp, final)

    if _faults.enabled():
        # bit-rot simulation: garble one committed shard so restore-side
        # checksum verification (and quorum fallback) is exercised for
        # real — fires AFTER the manifest recorded the clean hash
        spec_fired = _faults.fire("shard_corrupt", step)
        if spec_fired is not None and leaf_table and \
                leaf_table[0]["shards"]:
            _faults.garble_file(os.path.join(
                final, leaf_table[0]["shards"][0]["file"]))
    return manifest


def read_manifest(dirname, verify=True):
    """Parse (and by default checksum-verify) a checkpoint's manifest.
    Raises ValueError when missing or corrupt."""
    mpath = os.path.join(dirname, MANIFEST)
    if not os.path.isfile(mpath):
        raise ValueError(f"no {MANIFEST} in {dirname}")
    with open(mpath, "rb") as f:
        blob = f.read()
    side = mpath + ".sha256"
    if verify and os.path.exists(side):
        with open(side, encoding="utf-8") as f:
            want = f.read().strip()
        if hashlib.sha256(blob).hexdigest() != want:
            raise ValueError(f"manifest checksum mismatch in {dirname}")
    try:
        manifest = json.loads(blob.decode())
    except Exception as e:
        raise ValueError(f"unparseable manifest in {dirname}: {e}") from e
    if manifest.get("format") != FORMAT_VERSION:
        raise ValueError(
            f"unsupported sharded-checkpoint format "
            f"{manifest.get('format')!r} in {dirname}")
    return manifest


def validate(dirname):
    """Full quorum check: manifest parses + checksums, and EVERY shard
    file exists with matching size and sha256. Returns ``(ok, why)`` —
    one missing/corrupt shard fails the whole checkpoint, which is what
    keeps a partial load impossible."""
    try:
        manifest = read_manifest(dirname)
    except ValueError as e:
        return False, str(e)
    for leaf in manifest["leaves"]:
        for rec in leaf["shards"]:
            path = os.path.join(dirname, rec["file"])
            try:
                if os.path.getsize(path) != rec["bytes"]:
                    return False, f"shard {rec['file']} size mismatch"
            except OSError:
                return False, f"shard {rec['file']} missing"
            if _sha256_file(path) != rec["sha256"]:
                return False, f"shard {rec['file']} checksum mismatch"
    return True, None


def load_state(dirname, mesh=None, place=False, verify=True):
    """Reassemble a sharded checkpoint into its global state tree.

    Each leaf's shards are checksum-verified (unless ``verify=False``
    when the caller just validated), loaded, and stitched into one host
    array. With ``place=True`` every leaf that recorded a PartitionSpec
    is ``device_put`` onto `mesh` (default: the current global mesh)
    under :func:`paddle_tpu.parallel.layout.adapt_spec` — restoring onto
    a resized mesh reshards rather than failing. Returns
    ``(state, manifest)``.
    """
    from ..parallel import collective as _collective
    from ..resilience import retry as _retry
    manifest = read_manifest(dirname, verify=verify)
    mesh = mesh if mesh is not None else _collective.get_mesh()

    leaf_values = []
    resharded = 0
    for leaf in manifest["leaves"]:
        shape = tuple(leaf["shape"])
        dtype = np.dtype(leaf["dtype"])
        out = np.empty(shape, dtype)
        for rec in leaf["shards"]:
            path = os.path.join(dirname, rec["file"])
            if verify and _sha256_file(path) != rec["sha256"]:
                raise ValueError(
                    f"shard {rec['file']} checksum mismatch in {dirname}")
            data = _retry.retry_call(np.load, path,
                                     label="ckpt_shard_read")
            sl = _layout.bounds_to_slices(rec["index"])
            if shape == ():
                out[()] = np.asarray(data)
            else:
                out[sl] = data
        value = out
        if place and leaf["spec"] is not None:
            value, changed = _layout.reshard(out, leaf["spec"], mesh)
            resharded += bool(changed)
        leaf_values.append(value)

    state = _decode_tree(manifest["tree"], leaf_values)
    if _monitor.enabled():
        cur_sig = _layout.mesh_signature(mesh)
        if not _layout.same_signature(manifest.get("mesh"), cur_sig):
            _monitor.counter("ckpt.restore_resharded").inc()
            _monitor.emit(kind="ckpt", event="restore_resharded",
                          step=manifest.get("step"),
                          saved_mesh=manifest.get("mesh"),
                          current_mesh=cur_sig,
                          leaves_respecced=resharded)
    return state, manifest
