"""paddle_tpu.io — save/load, DataLoader, datasets.

TPU-native rebuild of the reference's IO stack
(reference: python/paddle/fluid/io.py save/load_persistables +
save/load_inference_model; dygraph/checkpoint.py save_dygraph/load_dygraph;
python/paddle/fluid/reader.py + dataloader/ DataLoader).

Checkpointing: simple pickled-numpy state dicts for parity, plus an
orbax-backed sharded checkpoint path (paddle_tpu.io.orbax_save/orbax_restore)
for large distributed state — the TPU equivalent of the reference's
per-variable persistables files.

DataLoader: index-sampling + batch assembly with background-thread prefetch;
a C++ native fast path (paddle_tpu/csrc) assembles batches of array datasets
off the GIL (the reference uses C++ BufferedReader + pin-memory threads).
"""
from __future__ import annotations

import os
import pickle
import shutil
import threading
import time
import queue as _queue
import warnings

import numpy as np
import jax

from ..tensor import Tensor, Parameter
from ..nn.layer import Layer
from .. import monitor as _monitor
from . import bucketing  # noqa: F401  (shape bucketing / pad-and-mask)
from .bucketing import (next_bucket, pad_to_bucket, batch_mask,  # noqa: F401
                        unpad, split_rows)
from .prefetch import prefetch_to_device  # noqa: F401


# ---------------------------------------------------------------------------
# state-dict save/load (reference: save_dygraph / load_dygraph)

def _to_numpy_tree(obj):
    if isinstance(obj, Tensor):
        return obj.numpy()
    if isinstance(obj, dict):
        return {k: _to_numpy_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy_tree(v) for v in obj)
    return obj


def save(obj, path, protocol=4):
    """paddle.save parity: pickles state dicts (Tensors → numpy)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_numpy_tree(obj), f, protocol=protocol)


def load(path):
    with open(path, "rb") as f:
        return pickle.load(f)


def save_dygraph(state_dict, model_path):
    """reference: dygraph/checkpoint.py:save_dygraph — model state goes to
    .pdparams, optimizer state to .pdopt. Optimizer dicts are recognized by
    their slot-key shape ("param@slot" / "__aux__" / bare "lr")."""
    suffix = ".pdparams"
    keys = [k for k in state_dict if isinstance(k, str)]
    if keys and any("@" in k or k.startswith("__") or k == "lr"
                    for k in keys):
        suffix = ".pdopt"
    save(state_dict, model_path + suffix)


def load_dygraph(model_path):
    """reference: load_dygraph — returns (param_dict, opt_dict)."""
    params = load(model_path + ".pdparams") if os.path.exists(
        model_path + ".pdparams") else None
    opt = load(model_path + ".pdopt") if os.path.exists(
        model_path + ".pdopt") else None
    return params, opt


# ---------------------------------------------------------------------------
# inference model (reference: io.py save_inference_model)

def save_inference_model(path_prefix, layer, input_spec=None):
    """Pickle the whole Layer (structure + weights). The TPU inference
    engine is `jax.jit` over the restored layer's forward (AOT-compilable
    via paddle_tpu.inference.Predictor)."""
    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    layer.eval()
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump(layer, f, protocol=4)
    save(layer.state_dict(), path_prefix + ".pdiparams")


def load_inference_model(path_prefix):
    with open(path_prefix + ".pdmodel", "rb") as f:
        layer = pickle.load(f)
    params = load(path_prefix + ".pdiparams")
    layer.set_state_dict(params)
    layer.eval()
    return layer


# ---------------------------------------------------------------------------
# orbax sharded checkpointing (reference: fleet checkpoint / persistables —
# rebuilt over orbax for multi-host sharded state)

def orbax_save(path, state_dict, step=None):
    """Sharded checkpoint save (reference: fleet save_persistables /
    python/paddle/fluid/io.py:save_persistables — rebuilt over orbax)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    tree = _to_numpy_tree(state_dict)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path if step is None else os.path.join(path, str(step)),
               tree, force=True)


def orbax_restore(path, step=None, template=None):
    """Restore an orbax checkpoint. With `template` (a state_dict whose
    leaves are live — possibly mesh-sharded — Tensors/arrays), every
    restored leaf is placed with the template leaf's sharding, so a
    dp×tp-sharded model resumes with placement preserved."""
    import jax as _jax
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    tree = ckptr.restore(path if step is None else
                         os.path.join(path, str(step)))
    if template is None:
        return tree

    def place(t, value):
        arr = t.data if isinstance(t, Tensor) else t
        if isinstance(arr, _jax.Array):
            return _jax.device_put(value, arr.sharding)
        return value

    def walk(tmpl, got):
        if isinstance(got, dict):
            return {k: walk(tmpl[k], v) if isinstance(tmpl, dict) and
                    k in tmpl else v for k, v in got.items()}
        if isinstance(got, (list, tuple)):
            if not isinstance(tmpl, (list, tuple)) or \
                    len(tmpl) != len(got):
                raise ValueError(
                    f"orbax_restore: checkpoint list of {len(got)} entries "
                    "does not match the live template "
                    f"({len(tmpl) if isinstance(tmpl, (list, tuple)) else type(tmpl).__name__})")
            return type(got)(walk(a, b) for a, b in zip(tmpl, got))
        return place(tmpl, got)

    return walk(template, tree)


def _sha256_file(path, chunk=1 << 20):
    import hashlib
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


class CheckpointManager:
    """Train-loop checkpoint/resume helper (keeps last-k, tracks step).

    Preemption-safe: saves write to ``ckpt-{step}.pkl.tmp`` + fsync and
    ``os.replace`` into place (a SIGKILL mid-save leaves a stray .tmp,
    never a truncated checkpoint), with a sha256 sidecar
    (``ckpt-{step}.pkl.sha256``) written after the data lands.
    ``latest_step()``/``restore()`` only ever pick *valid* checkpoints
    — unreadable or checksum-mismatched files are warned about, skipped
    and (on restore) quarantined to ``*.corrupt`` with a
    ``resilience.ckpt_quarantine`` event, falling back to the newest
    checkpoint that does load. A checkpoint whose fresh ``.tmp`` staging
    file/dir is still warm (< ``in_progress_grace`` seconds old) is a
    save in progress — skipped silently, not warned about. Checkpoint
    I/O retries transient OS errors under resilience.retry.

    ``sharded=True`` switches saves to the per-shard format of
    :mod:`paddle_tpu.io.sharded`: every process writes only the pytree
    leaves it owns (keyed by their live ``NamedSharding`` layout) into a
    ``ckpt-{step}.sharded/`` directory with a checksummed manifest, and
    ``restore()`` reassembles + reshards the state onto the *current*
    mesh even when its dp×tp topology differs from the one that saved.
    Validation is a quorum rule: one missing or corrupt shard fails the
    whole checkpoint, which is then quarantined and the newest
    *complete* one wins (``ckpt.quorum_fallback``) — never a partial
    load. Both formats can coexist in one directory; ``restore()``
    reads whichever a step has.
    """

    def __init__(self, directory, max_to_keep=3, sharded=False,
                 in_progress_grace=60.0):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.sharded = bool(sharded)
        self.in_progress_grace = float(in_progress_grace)
        self._valid_cache = {}  # step -> (fingerprint, ok)

    def _path(self, step):
        return os.path.join(self.directory, f"ckpt-{step}.pkl")

    def _sharded_path(self, step):
        return os.path.join(self.directory, f"ckpt-{step}.sharded")

    def _has_sharded(self, step):
        return os.path.isdir(self._sharded_path(step))

    def save(self, step, model=None, optimizer=None, extra=None,
             program=None):
        """Atomic save. ``program=`` captures a static Program's
        parameter values (plus its recorded optimizers' state) so
        Executor loops checkpoint through the same manager."""
        from ..resilience import retry as _retry
        if self.sharded:
            # keep LIVE leaves: the sharded writer reads each array's
            # NamedSharding to decide which shards this process owns
            state = {"step": step}
            if model is not None:
                state["model"] = dict(model.state_dict())
            if optimizer is not None:
                state["optimizer"] = optimizer.state_dict()
            if program is not None:
                state["program"] = dict(program.param_vars)
                state["program_optimizers"] = [
                    opt.state_dict()
                    if opt._parameter_list is not None else {}
                    for opt, _ in getattr(program, "optimizers", [])]
            if extra:
                state["extra"] = extra
            from . import sharded as _sharded
            _t0_save = time.perf_counter()
            with _monitor.trace.span("checkpoint.save", step=step,
                                     sharded=True):
                _sharded.save_state(self._sharded_path(step), state,
                                    step=step)
            if _monitor.enabled():
                # wall seconds the train loop spent inside the save —
                # the checkpoint category of the goodput ledger
                _monitor.counter("ckpt.save_s").inc(
                    time.perf_counter() - _t0_save)
            self._valid_cache.pop(step, None)
            self._gc()
            return
        state = {"step": step}
        if model is not None:
            state["model"] = _to_numpy_tree(model.state_dict())
        if optimizer is not None:
            state["optimizer"] = _to_numpy_tree(optimizer.state_dict())
        if program is not None:
            state["program"] = {
                n: np.asarray(jax.device_get(p.data))
                for n, p in program.param_vars.items()}
            # recorded optimizers have slots only after the first run
            state["program_optimizers"] = [
                _to_numpy_tree(opt.state_dict())
                if opt._parameter_list is not None else {}
                for opt, _ in getattr(program, "optimizers", [])]
        if extra:
            state["extra"] = extra
        path = self._path(step)
        tmp = path + ".tmp"

        def _write():
            with open(tmp, "wb") as f:
                pickle.dump(_to_numpy_tree(state), f, protocol=4)
                f.flush()
                os.fsync(f.fileno())

        _t0_save = time.perf_counter()
        with _monitor.trace.span("checkpoint.save", step=step):
            _retry.retry_call(_write, label="ckpt_save")
            digest = _sha256_file(tmp)
            os.replace(tmp, path)
            # sidecar lands AFTER the data: a crash in between leaves a
            # checkpoint without a sidecar, which validation falls back
            # to verifying by unpickling
            with open(path + ".sha256", "w", encoding="utf-8") as f:
                f.write(digest + "\n")
        if _monitor.enabled():
            _monitor.counter("ckpt.save_s").inc(
                time.perf_counter() - _t0_save)
        self._valid_cache.pop(step, None)
        self._gc()

    def _steps(self):
        out = set()
        for fn in os.listdir(self.directory):
            if not fn.startswith("ckpt-"):
                continue
            if fn.endswith(".pkl"):
                try:
                    out.add(int(fn[5:-4]))
                except ValueError:
                    pass
            elif fn.endswith(".sharded"):
                try:
                    out.add(int(fn[5:-8]))
                except ValueError:
                    pass
        return sorted(out)

    def _gc(self):
        steps = self._steps()
        for s in steps[:-self.max_to_keep]:
            for suffix in ("", ".sha256", ".tmp"):
                try:
                    os.remove(self._path(s) + suffix)
                except FileNotFoundError:
                    pass
            shutil.rmtree(self._sharded_path(s), ignore_errors=True)
            self._valid_cache.pop(s, None)

    def _fingerprint(self, step):
        """Change-detection key for the validity cache: (size, mtime) of
        the pkl, or the sorted (name, size, mtime) listing of a sharded
        dir — any rewrite or corruption-in-place changes it."""
        path = self._path(step)
        try:
            st = os.stat(path)
            return ("pkl", st.st_size, st.st_mtime_ns)
        except OSError:
            pass
        sdir = self._sharded_path(step)
        try:
            entries = []
            for fn in sorted(os.listdir(sdir)):
                st = os.stat(os.path.join(sdir, fn))
                entries.append((fn, st.st_size, st.st_mtime_ns))
            return ("sharded", tuple(entries))
        except OSError:
            return None

    def _is_valid(self, step):
        """Readable + checksum-clean. Pickle checkpoints verify via the
        sha256 sidecar (else a full unpickle probe); sharded ones apply
        the quorum rule — manifest plus EVERY shard must check out.
        Cached per content fingerprint."""
        fp = self._fingerprint(step)
        if fp is None:
            return False
        cached = self._valid_cache.get(step)
        if cached is not None and cached[0] == fp:
            return cached[1]
        ok = False
        if fp[0] == "sharded":
            from . import sharded as _sharded
            ok, _why = _sharded.validate(self._sharded_path(step))
        else:
            path = self._path(step)
            try:
                sidecar = path + ".sha256"
                if os.path.exists(sidecar):
                    with open(sidecar, encoding="utf-8") as f:
                        want = f.read().strip()
                    ok = bool(want) and _sha256_file(path) == want
                else:
                    with open(path, "rb") as f:
                        pickle.load(f)
                    ok = True
            except Exception:
                ok = False
        self._valid_cache[step] = (fp, ok)
        return ok

    def valid_steps(self):
        return [s for s in self._steps() if self._is_valid(s)]

    def _in_progress(self, step):
        """True while a save of `step` looks live: a ``.tmp`` staging
        file/dir younger than ``in_progress_grace`` seconds. Such steps
        are skipped silently — an interrupted save older than the grace
        window is treated as corrupt like any other invalid file."""
        candidates = [self._path(step) + ".tmp"]
        prefix = f"ckpt-{step}.sharded.tmp-"
        try:
            candidates += [os.path.join(self.directory, fn)
                           for fn in os.listdir(self.directory)
                           if fn.startswith(prefix)]
        except OSError:
            pass
        now = time.time()
        for c in candidates:
            try:
                if now - os.stat(c).st_mtime < self.in_progress_grace:
                    return True
            except OSError:
                continue
        return False

    def _quarantine(self, step, why):
        from ..resilience import record as _record
        sharded = self._has_sharded(step) and not os.path.exists(
            self._path(step))
        path = self._sharded_path(step) if sharded else self._path(step)
        warnings.warn(
            f"CheckpointManager: quarantining corrupt checkpoint "
            f"{path} ({why})")
        if sharded:
            try:
                os.replace(path, path + ".corrupt")
            except OSError:
                pass
        else:
            for suffix in ("", ".sha256"):
                try:
                    os.replace(path + suffix, path + suffix + ".corrupt")
                except OSError:
                    pass
        self._valid_cache.pop(step, None)
        _record("ckpt_quarantine", step=step, path=path, why=why,
                sharded=sharded)

    def latest_step(self):
        """Newest *valid* checkpoint step. Corrupt/truncated files are
        skipped with a warning — they never win; a save still in
        progress (warm ``.tmp``) is skipped silently."""
        for s in reversed(self._steps()):
            if self._is_valid(s):
                return s
            if self._in_progress(s):
                continue
            shown = self._sharded_path(s) if self._has_sharded(s) and \
                not os.path.exists(self._path(s)) else self._path(s)
            warnings.warn(
                f"CheckpointManager: skipping unreadable/corrupt "
                f"checkpoint {shown}")
        return None

    def restore(self, model=None, optimizer=None, step=None, program=None):
        """Restore the requested (default: newest valid) checkpoint.
        Corrupt candidates found on the way are quarantined and the
        next-newest valid one is used (for sharded candidates that is the
        quorum fallback: one bad shard disqualifies the whole step —
        ``ckpt.quorum_fallback``); an explicitly requested corrupt step
        raises. In-progress saves are skipped, not quarantined."""
        from ..resilience import retry as _retry
        if step is not None:
            if not self._is_valid(step):
                self._quarantine(step, "explicitly requested but invalid")
                raise ValueError(
                    f"checkpoint step {step} is corrupt or missing")
            chosen = step
        else:
            chosen = None
            for s in reversed(self._steps()):
                if self._is_valid(s):
                    chosen = s
                    break
                if self._in_progress(s):
                    continue
                if self._has_sharded(s) and not os.path.exists(
                        self._path(s)):
                    _monitor.counter("ckpt.quorum_fallback").inc()
                    _monitor.emit(kind="ckpt", event="quorum_fallback",
                                  step=s)
                self._quarantine(s, "failed validation during restore")
            if chosen is None:
                return None
        sharded = self._has_sharded(chosen) and not os.path.exists(
            self._path(chosen))
        _t0_restore = time.perf_counter()
        if sharded:
            from . import sharded as _sharded
            from ..parallel import collective as _collective
            with _monitor.trace.span("checkpoint.restore", step=chosen,
                                     sharded=True):
                state, _manifest = _retry.retry_call(
                    _sharded.load_state, self._sharded_path(chosen),
                    mesh=_collective.get_mesh(), label="ckpt_load")
        else:
            with _monitor.trace.span("checkpoint.restore", step=chosen):
                state = _retry.retry_call(
                    load, self._path(chosen), label="ckpt_load")
        if _monitor.enabled():
            # restores happen on resume/rollback — the goodput ledger's
            # restart_rollback category
            _monitor.counter("ckpt.restore_s").inc(
                time.perf_counter() - _t0_restore)
        if model is not None and "model" in state:
            model.set_state_dict(state["model"])
        if optimizer is not None and "optimizer" in state:
            optimizer.set_state_dict(state["optimizer"])
        if program is not None and "program" in state:
            for n, v in state["program"].items():
                holder = program.param_vars.get(n)
                if holder is not None:
                    holder.set_value(np.asarray(v))
            for (opt, _), ostate in zip(
                    getattr(program, "optimizers", []),
                    state.get("program_optimizers", [])):
                if ostate and opt._parameter_list is not None:
                    opt.set_state_dict(ostate)
        return state


# ---------------------------------------------------------------------------
# Dataset / DataLoader (reference: fluid/reader.py, dataloader/)

class Dataset:
    """Map-style dataset (reference: dataloader/dataset.py)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset:
    def __iter__(self):
        raise NotImplementedError


class TensorDataset(Dataset):
    def __init__(self, *tensors):
        self.arrays = [t.numpy() if isinstance(t, Tensor) else np.asarray(t)
                       for t in tensors]

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.arrays)

    def __len__(self):
        return len(self.arrays[0])


class BatchSampler:
    """reference: dataloader/batch_sampler.py."""

    def __init__(self, dataset=None, shuffle=False, batch_size=1,
                 drop_last=False, seed=None):
        self.n = len(dataset) if dataset is not None else 0
        self.shuffle = shuffle
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.epoch = 0
        self.seed = seed

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        idx = np.arange(self.n)
        if self.shuffle:
            rng = np.random.default_rng(
                None if self.seed is None else self.seed + self.epoch)
            rng.shuffle(idx)
        self.epoch += 1
        bs = self.batch_size
        end = (self.n // bs) * bs if self.drop_last else self.n
        for i in range(0, end, bs):
            yield idx[i:i + bs].tolist()

    def __len__(self):
        if self.drop_last:
            return self.n // self.batch_size
        return (self.n + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch):
    """Stack samples into numpy batches (tuple-of-fields layout)."""
    first = batch[0]
    if isinstance(first, (tuple, list)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(first)))
    if isinstance(first, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in first}
    return np.stack([np.asarray(b) for b in batch])


def _mp_worker_loop(dataset, collate_fn, index_q, data_q):
    """Worker-process body (reference: fluid/dataloader/dataloader_iter.py
    _worker_loop): pull (batch_id, indices), push (batch_id, batch).
    Runs dataset[i] + collate in a separate PROCESS, so Python-level
    decode/augment transforms scale past the GIL.

    Workers are FORKED (zero-copy dataset inheritance) after jax may have
    initialized in the parent — safe ONLY because this loop never touches
    jax: datasets/collate for num_workers>0 must return numpy, not device
    arrays (same rule as the reference's worker processes, which must not
    touch CUDA)."""
    # forked children inherit the parent's numpy RNG state: without a
    # per-worker reseed every worker would draw IDENTICAL augmentation
    # streams (and every epoch would replay them)
    np.random.seed((os.getpid() * 1000003 + int(
        time.time() * 1e6)) % (2 ** 32))
    while True:
        job = index_q.get()
        if job is None:
            break
        bid, idx = job
        try:
            data_q.put((bid, collate_fn([dataset[i] for i in idx])))
        except BaseException as e:  # surface to the consumer
            try:
                pickle.dumps(e)  # Queue.put pickles in a FEEDER THREAD —
                # a pickling failure there is silent, so pre-validate
                data_q.put((bid, _WorkerError(e)))
            except Exception:
                import traceback
                data_q.put((bid, _WorkerError(RuntimeError(
                    "worker failed (original exception unpicklable):\n"
                    + traceback.format_exc()))))


class DataLoader:
    """reference: fluid/reader.py DataLoader +
    fluid/dataloader/dataloader_iter.py (multiprocess workers).

    num_workers=0: background-thread prefetch (the C++ fast path in csrc
    covers contiguous array datasets). num_workers>0: that many worker
    PROCESSES run dataset[i] + collate (order-preserving, windowed
    dispatch of num_workers*prefetch_factor batches ahead).

    prefetch_to_device=N additionally stages the next N assembled batches
    on DEVICE via a background jax.device_put thread (sharded over
    `device_mesh` when given) — see io.prefetch.prefetch_to_device."""

    def __init__(self, dataset, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, prefetch_factor=2,
                 batch_sampler=None, return_list=True, feed_list=None,
                 places=None, use_native=True, seed=None,
                 prefetch_to_device=0, device_mesh=None, retry=True):
        self.dataset = dataset
        # transient batch-assembly errors retry under backoff
        # (resilience.retry); retry=False disables, a RetryPolicy
        # customizes the budget
        if retry is True:
            from ..resilience.retry import default_policy
            self._retry_policy = default_policy()
        else:
            self._retry_policy = retry or None
        self._device_prefetch = int(prefetch_to_device or 0)
        self._device_mesh = device_mesh
        # stream-style datasets (reference: dataloader_iter's
        # _DataLoaderIterForIterableDataset): no sampler/len — batches
        # are cut from the iterator in order
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            if batch_sampler is not None:
                raise ValueError(
                    "batch_sampler is incompatible with IterableDataset")
            if shuffle:
                raise ValueError(
                    "shuffle=True is incompatible with IterableDataset "
                    "(the stream defines its own order; shuffle inside "
                    "the dataset, e.g. via reader.shuffle)")
            if num_workers > 0:
                warnings.warn(
                    "num_workers is ignored for IterableDataset (process "
                    "workers would need per-worker stream sharding); "
                    "running single-stream with threaded prefetch")
            self._batch_size = batch_size
            self._drop_last = drop_last
            self.batch_sampler = None
            self.collate_fn = collate_fn or default_collate_fn
            self.prefetch = max(1, prefetch_factor)
            self.num_workers = 0
            self._native = None
            self._native_epoch = None
            return
        self.batch_sampler = batch_sampler or BatchSampler(
            dataset, shuffle=shuffle, batch_size=batch_size,
            drop_last=drop_last, seed=seed)
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch = max(1, prefetch_factor)
        self.num_workers = num_workers
        self._native = None
        self._native_epoch = None
        if use_native and isinstance(dataset, TensorDataset):
            try:
                from .native import NativeBatcher
                self._native = NativeBatcher(dataset.arrays)
                if collate_fn is None and batch_sampler is None:
                    # full native path: C++ worker shuffles + assembles
                    self._native_epoch = NativeBatcher(
                        dataset.arrays, batch_size=batch_size,
                        shuffle=shuffle, drop_last=drop_last,
                        seed=seed or 0)
            except Exception:
                self._native = None

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset loader has no length")
        return len(self.batch_sampler)

    def _iter_stream(self):
        buf = []
        for item in self.dataset:
            buf.append(item)
            if len(buf) == self._batch_size:
                yield self.collate_fn(buf)
                buf = []
        if buf and not self._drop_last:
            yield self.collate_fn(buf)

    @staticmethod
    def _guarded_put(q, item, stop):
        """Bounded put the consumer's shutdown can always interrupt — an
        abandoned iterator must not leave the producer parked forever on
        a full queue (a daemon-thread leak per discarded iterator)."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except _queue.Full:
                continue
        return False

    def _assemble(self, idx, batch_index):
        """One batch's assembly, with fault injection + transient-error
        retry (resilience.retry): an I/O hiccup in dataset[i] retries
        under the backoff budget instead of killing the epoch; budget
        exhaustion and terminal errors still propagate."""
        from ..resilience import faults as _faults
        from ..resilience import retry as _retry

        def attempt():
            if _faults.enabled():
                _faults.maybe_raise("loader", step=batch_index)
            if self._native is not None:
                return self._native.gather(idx)
            return self.collate_fn([self.dataset[i] for i in idx])

        if self._retry_policy is None:
            with _monitor.trace.span("dataloader.assemble",
                                     batch=batch_index):
                return attempt()
        with _monitor.trace.span("dataloader.assemble", batch=batch_index):
            return _retry.retry_call(attempt, policy=self._retry_policy,
                                     label="dataloader")

    def _produce(self, q, stop):
        try:
            for bi, idx in enumerate(self.batch_sampler):
                item = self._assemble(idx, bi)
                if not self._guarded_put(q, item, stop):
                    return
            self._guarded_put(q, _SENTINEL, stop)
        except BaseException as e:  # surface worker errors to the consumer
            self._guarded_put(q, _WorkerError(e), stop)

    def _produce_stream(self, q, stop):
        try:
            for batch in self._iter_stream():
                if not self._guarded_put(q, batch, stop):
                    return
            self._guarded_put(q, _SENTINEL, stop)
        except BaseException as e:  # surface generator errors
            self._guarded_put(q, _WorkerError(e), stop)

    def __iter__(self):
        it = self._iter_host()
        if self._device_prefetch > 0:
            from .prefetch import prefetch_to_device
            it = prefetch_to_device(it, size=self._device_prefetch,
                                    mesh=self._device_mesh)
        return it

    def _iter_host(self):
        if self._iterable:
            if self.prefetch <= 1:
                yield from self._iter_stream()
                return
            producer = self._produce_stream
        else:
            if self.num_workers > 0 and self._native_epoch is None:
                yield from self._iter_multiprocess()
                return
            from ..resilience import faults as _faults
            if self._native_epoch is not None and not _faults.enabled():
                # the all-in-memory C++ batcher has no I/O to fail; with
                # faults registered, take the _assemble path so chaos
                # runs exercise injection + retry end-to-end
                yield from self._native_epoch
                return
            if self.num_workers == 0 and self.prefetch <= 1:
                for bi, idx in enumerate(self.batch_sampler):
                    yield self._assemble(idx, bi)
                return
            producer = self._produce
        q = _queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        t = threading.Thread(target=producer, args=(q, stop), daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _SENTINEL:
                    break
                if isinstance(item, _WorkerError):
                    raise item.exc
                yield item
        finally:
            stop.set()
            try:  # drain so a producer parked on put() can see the stop
                while True:
                    q.get_nowait()
            except _queue.Empty:
                pass
            t.join(timeout=5.0)

    def _iter_multiprocess(self):
        """Order-preserving multiprocess iteration (reference:
        dataloader_iter.py _DataLoaderIterMultiProcess). Fork-start
        workers inherit the dataset without pickling; index batches are
        dispatched num_workers*prefetch ahead and results are reordered
        by batch id."""
        import multiprocessing as mp
        try:
            ctx = mp.get_context("fork")
        except ValueError:  # pragma: no cover - non-posix fallback
            warnings.warn("fork unavailable; num_workers>0 falls back to "
                          "the threaded loader")
            saved, self.num_workers = self.num_workers, 0
            try:
                yield from self.__iter__()
            finally:
                self.num_workers = saved
            return
        index_q = ctx.Queue()
        data_q = ctx.Queue()
        workers = [
            ctx.Process(target=_mp_worker_loop,
                        args=(self.dataset, self.collate_fn, index_q,
                              data_q), daemon=True)
            for _ in range(self.num_workers)
        ]
        for w in workers:
            w.start()
        batches = list(self.batch_sampler)
        ahead = max(1, self.num_workers * self.prefetch)
        sent = 0
        pending = {}
        try:
            while sent < min(ahead, len(batches)):
                index_q.put((sent, batches[sent]))
                sent += 1
            stall_limit = 120.0  # seconds without ANY batch arriving
            for want in range(len(batches)):
                waited = 0.0
                while want not in pending:
                    try:
                        bid, item = data_q.get(timeout=5.0)
                    except _queue.Empty:
                        dead = [w for w in workers
                                if not w.is_alive() and w.exitcode]
                        if dead:
                            raise RuntimeError(
                                f"DataLoader worker died (exitcode "
                                f"{dead[0].exitcode}) — batch {want} "
                                "will never arrive")
                        waited += 5.0
                        if waited >= stall_limit:
                            raise RuntimeError(
                                f"DataLoader stalled {stall_limit:.0f}s "
                                f"waiting for batch {want}: a worker's "
                                "batch likely failed to pickle (batches "
                                "must be numpy, not device arrays) or a "
                                "transform is hung")
                        continue
                    waited = 0.0
                    if isinstance(item, _WorkerError):
                        raise item.exc
                    pending[bid] = item
                if sent < len(batches):
                    index_q.put((sent, batches[sent]))
                    sent += 1
                yield pending.pop(want)
        finally:
            for _ in workers:
                try:
                    index_q.put_nowait(None)
                except Exception:
                    pass
            for w in workers:
                w.join(timeout=1.0)
                if w.is_alive():  # pragma: no cover
                    w.terminate()


_SENTINEL = object()


class _WorkerError:
    def __init__(self, exc):
        self.exc = exc


# fluid-era reader decorators (reference: python/paddle/reader/decorator.py)
def batch_reader(reader, batch_size, drop_last=False):
    def _reader():
        batch = []
        for item in reader():
            batch.append(item)
            if len(batch) == batch_size:
                yield default_collate_fn(batch)
                batch = []
        if batch and not drop_last:
            yield default_collate_fn(batch)
    return _reader


def shuffle_reader(reader, buf_size, seed=None):
    def _reader():
        rng = np.random.default_rng(seed)
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        rng.shuffle(buf)
        yield from buf
    return _reader


# ---------------------------------------------------------------------------
# static-graph persistable/parameter save+load family (reference:
# fluid/io.py save_vars/save_params/save_persistables and the load side;
# vars live in Program.param_vars + the optimizer slot state, stored one
# .npy per var, or one pickle when `filename` is given)

def is_parameter(var):
    """reference io.py:is_parameter."""
    from ..tensor import Parameter as _P
    return isinstance(var, _P)


def is_persistable(var):
    """reference io.py:is_persistable — parameters and anything flagged
    .persistable survive across Executor runs."""
    return is_parameter(var) or bool(getattr(var, "persistable", False))


def is_belong_to_optimizer(var):
    """reference io.py:is_belong_to_optimizer — optimizer slot naming uses
    'param@slot' here."""
    name = getattr(var, "name", "") or ""
    return "@" in name


def get_program_parameter(program):
    """reference io.py:get_program_parameter."""
    return list(program.param_vars.values())


def get_program_persistable_vars(program):
    """reference io.py:get_program_persistable_vars."""
    return [v for v in program.param_vars.values() if is_persistable(v)]


def _default_program(main_program):
    if main_program is not None:
        return main_program
    from ..static import default_main_program
    return default_main_program()


def _named_vars(program, vars=None, predicate=None):
    if vars is not None:
        return {getattr(v, "name", f"var_{i}"): v
                for i, v in enumerate(vars)}
    out = {}
    for name, v in program.param_vars.items():
        if predicate is None or predicate(v):
            out[name] = v
    return out


def save_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None):
    """reference io.py:save_vars (executor is unused — no C++ scope to
    drain; values are device-resident jax arrays)."""
    program = _default_program(main_program)
    named = _named_vars(program, vars, predicate)
    os.makedirs(dirname, exist_ok=True)
    if filename is not None:
        save({k: v for k, v in named.items()},
             os.path.join(dirname, filename))
        return
    for name, v in named.items():
        arr = v.numpy() if hasattr(v, "numpy") else np.asarray(v)
        np.save(os.path.join(dirname, name.replace("/", "_") + ".npy"),
                arr)


def save_params(executor=None, dirname=None, main_program=None,
                filename=None):
    """reference io.py:save_params."""
    save_vars(executor, dirname, main_program, predicate=is_parameter,
              filename=filename)


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    """reference io.py:save_persistables."""
    save_vars(executor, dirname, main_program, predicate=is_persistable,
              filename=filename)


def load_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None):
    """reference io.py:load_vars — writes values back into the program's
    parameters in place."""
    program = _default_program(main_program)
    named = _named_vars(program, vars, predicate)
    if filename is not None:
        state = load(os.path.join(dirname, filename))
    else:
        state = {}
        for name in named:
            p = os.path.join(dirname, name.replace("/", "_") + ".npy")
            if os.path.exists(p):
                state[name] = np.load(p)
    set_program_state(program, state, _named=named)


def load_params(executor=None, dirname=None, main_program=None,
                filename=None):
    load_vars(executor, dirname, main_program, predicate=is_parameter,
              filename=filename)


def load_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    load_vars(executor, dirname, main_program, predicate=is_persistable,
              filename=filename)


def load_program_state(model_path, var_list=None):
    """reference io.py:load_program_state — returns {name: ndarray} from a
    save_params/save_persistables directory (or its single-file form)."""
    state = {}
    if os.path.isfile(model_path):
        return {k: np.asarray(v) for k, v in load(model_path).items()}
    for fn in sorted(os.listdir(model_path)):
        if fn.endswith(".npy"):
            state[fn[:-4]] = np.load(os.path.join(model_path, fn))
    if var_list is not None:
        # keys on disk are '/'-mangled (save_vars name.replace('/', '_'))
        want = {str(getattr(v, "name", v)).replace("/", "_")
                for v in var_list}
        state = {k: v for k, v in state.items() if k in want}
    return state


def set_program_state(program, state_dict, _named=None):
    """reference io.py:set_program_state — in-place assignment into the
    program's parameters."""
    named = _named if _named is not None else dict(program.param_vars)
    for name, v in named.items():
        key = name.replace("/", "_")
        val = state_dict.get(name, state_dict.get(key))
        if val is None:
            continue
        arr = val.numpy() if hasattr(val, "numpy") else np.asarray(val)
        v.set_value(arr)


def get_parameter_value(para, executor=None):
    """reference io.py:get_parameter_value."""
    return para.numpy()


def get_parameter_value_by_name(name, executor=None, program=None):
    """reference io.py:get_parameter_value_by_name."""
    program = _default_program(program)
    return program.param_vars[name].numpy()


def prepend_feed_ops(*a, **kw):
    """reference io.py:prepend_feed_ops — the jitted executor feeds
    arguments directly; nothing to prepend."""


def append_fetch_ops(*a, **kw):
    """reference io.py:append_fetch_ops — fetches are jit outputs here."""


from . import sharded  # noqa: E402,F401  (per-shard checkpoint format)
