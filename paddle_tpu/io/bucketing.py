"""paddle_tpu.io.bucketing — recompile-proof shape bucketing (pad-and-mask).

Every new feed shape mints a new XLA executable: the classic utilization
killer is the ragged final batch of an epoch (n % batch_size rows), which
retraces and recompiles the whole step for one short batch. Bucketing
rounds ragged dims up to a small, closed set of bucket sizes so an epoch
compiles once per bucket instead of once per distinct shape.

Semantics: padding REPEATS the last real row by default (keeps padded
rows in-distribution so batch statistics — BN, softmax temperature —
stay sane) or zero-fills (``mode="zeros"``). Per-example fetches are
sliced back to the real length by the callers (Executor.run /
jit.to_static); scalar reductions (a mean loss) include the padded rows
— use :func:`batch_mask` inside a masked loss when exact loss values on
ragged batches matter. The trade is explicit: bit-exact ragged-batch
losses vs. one executable per bucket.
"""
from __future__ import annotations

import numpy as np


def next_bucket(n, buckets=None):
    """Smallest bucket >= n. With ``buckets=None`` the bucket set is the
    powers of two; with an explicit iterable, the smallest listed bucket
    that fits (falling back to exact ``n`` past the largest — that mints
    a shape, but silently truncating data would be worse)."""
    n = int(n)
    if n <= 0:
        return n
    if buckets:
        for b in sorted(int(b) for b in buckets):
            if b >= n:
                return b
        return n
    b = 1
    while b < n:
        b <<= 1
    return b


def grow_buckets(base, factor=2.0, cap=None):
    """A geometric-growth bucket *family* for sequence lengths: ``base``,
    then each next bucket ``ceil(prev * factor)`` (strictly increasing
    even for factors close to 1), stopping at the first bucket >= ``cap``.

    Returns a tuple — immutable and hashable, so the family itself is a
    stable cache key: the same ``(base, factor, cap)`` always yields the
    same tuple, and executables keyed on a family member never collide
    across families. This is the growth schedule the serving KV-cache
    pool compiles against: capacity only ever moves along a closed,
    pre-declared family, so cache growth never mints a fresh shape.
    """
    base = int(base)
    if base < 1:
        raise ValueError(f"grow_buckets: base must be >= 1, got {base}")
    factor = float(factor)
    if factor <= 1.0:
        raise ValueError(
            f"grow_buckets: factor must be > 1, got {factor}")
    if cap is None:
        raise ValueError("grow_buckets: cap is required")
    cap = int(cap)
    if cap < base:
        raise ValueError(
            f"grow_buckets: cap {cap} is below base {base}")
    out = [base]
    while out[-1] < cap:
        nxt = int(np.ceil(out[-1] * factor))
        if nxt <= out[-1]:       # paranoia: ceil already guarantees this
            nxt = out[-1] + 1
        out.append(nxt)
    return tuple(out)


def pad_to_bucket(array, target, axis=0, mode="repeat"):
    """Pad ``array`` along ``axis`` up to ``target`` rows. Works on numpy
    and jax arrays alike (stays in the input's array namespace, so a
    device-resident batch pads on device). No-op at exact size."""
    n = array.shape[axis]
    if n == target:
        return array
    if n > target:
        raise ValueError(
            f"pad_to_bucket: size {n} exceeds bucket {target} on axis "
            f"{axis}")
    if isinstance(array, np.ndarray):
        xp = np
    else:
        import jax.numpy as xp  # device array: pad on device
    pad = target - n
    if mode == "repeat":
        idx = [slice(None)] * array.ndim
        idx[axis] = slice(n - 1, n)
        reps = [1] * array.ndim
        reps[axis] = pad
        fill = xp.tile(array[tuple(idx)], reps)
    elif mode == "zeros":
        shape = list(array.shape)
        shape[axis] = pad
        fill = xp.zeros(shape, dtype=array.dtype)
    else:
        raise ValueError(f"pad_to_bucket: unknown mode {mode!r} "
                         "(use 'repeat' or 'zeros')")
    return xp.concatenate([array, fill], axis=axis)


def batch_mask(real_n, padded_n, dtype="float32"):
    """A (padded_n,) 0/1 mask — 1 for real rows. Multiply into
    per-example losses (and divide by ``mask.sum()``) to make bucketed
    ragged batches bit-exact with the unpadded computation."""
    m = np.zeros((int(padded_n),), dtype=dtype)
    m[:int(real_n)] = 1
    return m


def unpad(array, real_n, axis=0):
    """Drop the pad rows again: the first ``real_n`` rows along
    ``axis``. The inverse of :func:`pad_to_bucket` for per-example
    outputs (no-op when the array is already ``real_n`` long, or has no
    batch dimension to slice)."""
    if real_n is None or getattr(array, "ndim", 0) < 1:
        return array
    if array.shape[axis] <= int(real_n):
        return array
    idx = [slice(None)] * array.ndim
    idx[axis] = slice(0, int(real_n))
    return array[tuple(idx)]


def split_rows(array, sizes, axis=0):
    """Split the leading real rows of a (possibly bucket-padded) batch
    back into per-request chunks of ``sizes`` rows each; trailing pad
    rows past ``sum(sizes)`` are dropped. The scatter half of dynamic
    batching: requests of 1/3/7/13 rows coalesced and padded to a
    32-bucket come back as four correctly-sized outputs."""
    out = []
    off = 0
    for n in sizes:
        n = int(n)
        idx = [slice(None)] * array.ndim
        idx[axis] = slice(off, off + n)
        out.append(array[tuple(idx)])
        off += n
    if off > array.shape[axis]:
        raise ValueError(
            f"split_rows: sizes sum to {off} but axis {axis} has only "
            f"{array.shape[axis]} rows")
    return out


def pad_feed_dict(feed, buckets=None, axis=0, mode="repeat"):
    """Bucket-pad every array in a name→array feed dict along ``axis``.

    Returns ``(new_feed, real_n, padded_n)``. ``real_n``/``padded_n``
    describe the (single) pad that was applied so the caller can slice
    per-example fetches back; they are ``None`` when nothing was padded
    or when feeds padded inconsistently (different batch dims — then no
    fetch slicing is safe and outputs pass through at bucket size).
    """
    out = dict(feed)
    pads = set()
    for k, v in feed.items():
        ndim = getattr(v, "ndim", 0)
        if ndim < 1 or v.shape[axis] == 0:
            continue
        n = v.shape[axis]
        t = next_bucket(n, buckets)
        if t != n:
            out[k] = pad_to_bucket(v, t, axis=axis, mode=mode)
            pads.add((n, t))
    if len(pads) == 1:
        (real_n, padded_n), = pads
        return out, real_n, padded_n
    return out, None, None
