"""paddle_tpu.io.prefetch — async host→device transfer pipelining.

``Executor.run`` / a compiled ``to_static`` step otherwise pays a
BLOCKING host→device feed transfer at the top of every step: the device
sits idle while the host copies, then the host sits idle while the
device computes. :func:`prefetch_to_device` overlaps the two — a
background thread ``jax.device_put``\\ s the next ``size`` batches while
step *i* runs, so by the time the training loop asks for batch *i+1* it
is already device-resident (sharded over the batch axis when a mesh is
active — the multi-chip shape of the same overlap).

Monitor series (when ``paddle_tpu.monitor`` is enabled):

* ``prefetch.batches``       — batches handed to the consumer
* ``prefetch.stall_seconds`` — total seconds the CONSUMER waited on the
                               queue; ~0 means the input pipeline keeps
                               up and the device is never starved
* ``prefetch.drops``         — batches abandoned after the transient
                               retry budget (resilience.retry) ran out

The producer survives transient source errors: a failure classified
transient (resilience.retry.is_transient) is retried under a backoff
budget, and when the budget is spent the batch is *dropped* (counted,
never silently) and the stream continues — one bad batch no longer
permanently stalls every consumer of ``prefetch_to_device``. Terminal
errors still propagate to the consumer on its next ``next()``.
"""
from __future__ import annotations

import queue as _queue
import threading
import time

import numpy as np
import jax

from .. import monitor as _monitor
from ..resilience import faults as _faults
from ..resilience import retry as _retry
from ..resilience._common import record as _record

_SENTINEL = object()


class _PrefetchError:
    def __init__(self, exc):
        self.exc = exc


def _batch_sharding(mesh, axis_name, arr):
    """Batch-shard over the mesh when the leading dim divides; replicate
    otherwise (scalars, per-step metadata)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    ndev = mesh.devices.size
    ndim = getattr(arr, "ndim", 0)
    if ndim >= 1 and arr.shape[0] % ndev == 0:
        return NamedSharding(mesh, P(*((axis_name,) + (None,) * (ndim - 1))))
    return NamedSharding(mesh, P())


def _place(batch, mesh, axis_name, sharding, device):
    from ..tensor import Tensor

    def leaf(a):
        if isinstance(a, Tensor):
            a = a.data
        if not isinstance(a, (np.ndarray, jax.Array)):
            a = np.asarray(a)
        if sharding is not None:
            return jax.device_put(a, sharding)
        if mesh is not None:
            return jax.device_put(a, _batch_sharding(mesh, axis_name, a))
        return jax.device_put(a, device)

    if isinstance(batch, dict):
        return {k: leaf(v) for k, v in batch.items()}
    if isinstance(batch, (tuple, list)):
        return type(batch)(leaf(v) for v in batch)
    return leaf(batch)


def _guarded_put(q, item, stop):
    """Bounded put that a consumer shutdown can always interrupt — the
    producer must never block forever on a queue nobody will drain."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.2)
            return True
        except _queue.Full:
            continue
    return False


def prefetch_to_device(iterator, size=2, mesh=None, axis_name="dp",
                       sharding=None, device=None, retry=None,
                       max_drops=16):
    """Wrap a batch iterator so the next ``size`` batches are moved to
    device on a background thread while the current step computes.

    Batches may be arrays, tuples/lists, or name→array dicts (the
    Executor feed shape); every array leaf is ``jax.device_put``. With
    ``mesh``, leaves batch-shard over ``axis_name`` (leading dim must
    divide the mesh size; non-dividing leaves replicate). An explicit
    ``sharding`` overrides the per-leaf inference; ``device`` pins a
    single device when no mesh is given.

    ``retry`` (a resilience.RetryPolicy; default 3 attempts with short
    backoff) bounds transient-error recovery per batch; after the
    budget the batch is dropped (``prefetch.drops``) and the stream
    continues, up to ``max_drops`` cumulative drops before the error is
    surfaced as terminal. Terminal errors propagate immediately.

    The wrapper is a generator: closing it (break / .close() / GC) stops
    and joins the worker thread — no thread leaks across iterators.
    """
    it = iter(iterator)
    q = _queue.Queue(maxsize=max(1, int(size)))
    stop = threading.Event()
    policy = retry or _retry.default_policy()

    def produce():
        drops = 0
        i = 0  # slot index: advances per delivered-or-dropped batch
        while not stop.is_set():
            attempts = 0
            placed = None
            delivered = False
            while True:  # per-batch transient-retry loop
                try:
                    if _faults.enabled():
                        _faults.maybe_raise("loader", step=i)
                    # the producer-thread span: its track overlapping the
                    # main thread's step spans IS the pipelining evidence
                    with _monitor.trace.span("prefetch.produce", batch=i):
                        batch = next(it)
                        placed = _place(batch, mesh, axis_name, sharding,
                                        device)
                    delivered = True
                    break
                except StopIteration:
                    _guarded_put(q, _SENTINEL, stop)
                    return
                except BaseException as e:
                    if not policy.is_transient(e):
                        _guarded_put(q, _PrefetchError(e), stop)
                        return
                    attempts += 1
                    if attempts >= policy.max_attempts:
                        drops += 1
                        if _monitor.enabled():
                            _monitor.counter("prefetch.drops").inc()
                        _record("drop", where="prefetch", step=i,
                                error=repr(e))
                        if drops > max_drops:
                            _guarded_put(q, _PrefetchError(RuntimeError(
                                f"prefetch: {drops} dropped batches "
                                f"(> max_drops={max_drops}); last "
                                f"transient error: {e!r}")), stop)
                            return
                        break  # drop this slot, move to the next batch
                    _record("retry", where="prefetch", step=i,
                            attempt=attempts, error=repr(e))
                    with _monitor.trace.span("resilience.backoff",
                                             where="prefetch",
                                             attempt=attempts):
                        if stop.wait(policy.delay(attempts - 1)):
                            return
            i += 1
            if delivered and not _guarded_put(q, placed, stop):
                return

    t = threading.Thread(target=produce, name="paddle_tpu-prefetch",
                         daemon=True)
    t.start()
    # live queue-depth gauge for the telemetry sampler (cold path: one
    # dict write per iterator; the provider dies with the generator)
    from ..monitor import sampler as _sampler
    _provider_key = _sampler.register_provider(
        f"prefetch-{id(q)}",
        lambda: {"prefetch.queue_depth": q.qsize()})
    try:
        while True:
            t0 = time.perf_counter()
            with _monitor.trace.span("prefetch.wait"):
                item = q.get()
            if _monitor.enabled():
                _monitor.counter("prefetch.stall_seconds").inc(
                    time.perf_counter() - t0)
            if item is _SENTINEL:
                break
            if isinstance(item, _PrefetchError):
                raise item.exc
            if _monitor.enabled():
                _monitor.counter("prefetch.batches").inc()
            yield item
    finally:
        _sampler.unregister_provider(_provider_key)
        stop.set()
        try:  # unblock a producer parked on a full queue
            while True:
                q.get_nowait()
        except _queue.Empty:
            pass
        t.join(timeout=5.0)
