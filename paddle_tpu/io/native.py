"""paddle_tpu.io.native — ctypes bindings to the C++ host runtime core.

TPU-native rebuild of the reference's C++ feeding pipeline bindings
(reference: paddle/fluid/pybind/reader_py.cc over buffered_reader.cc; here
ctypes over paddle_tpu/csrc/core.cpp — see that file for the design).

The library auto-builds on first import (g++, no external deps); failures
degrade gracefully to the pure-Python DataLoader path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.join(os.path.dirname(__file__), "..", "csrc")
_LIB_PATH = os.path.join(_DIR, "libpaddle_tpu_core.so")
_lib = None


def _build():
    subprocess.run(["make", "-s", "-C", _DIR], check=True,
                   capture_output=True)


def get_lib():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH) or (
            os.path.getmtime(_LIB_PATH) <
            os.path.getmtime(os.path.join(_DIR, "core.cpp"))):
        _build()
    lib = ctypes.CDLL(_LIB_PATH)
    lib.ptc_arena_create.restype = ctypes.c_void_p
    lib.ptc_arena_create.argtypes = [ctypes.c_size_t]
    lib.ptc_arena_destroy.argtypes = [ctypes.c_void_p]
    lib.ptc_arena_alloc.restype = ctypes.c_void_p
    lib.ptc_arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                    ctypes.c_size_t]
    lib.ptc_arena_reset.argtypes = [ctypes.c_void_p]
    lib.ptc_arena_used.restype = ctypes.c_size_t
    lib.ptc_arena_used.argtypes = [ctypes.c_void_p]
    lib.ptc_arena_peak.restype = ctypes.c_size_t
    lib.ptc_arena_peak.argtypes = [ctypes.c_void_p]
    lib.ptc_gather_rows.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_size_t, ctypes.c_void_p,
        ctypes.c_int]
    lib.ptc_batcher_create.restype = ctypes.c_void_p
    lib.ptc_batcher_create.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_int, ctypes.c_size_t, ctypes.c_size_t, ctypes.c_int,
        ctypes.c_int, ctypes.c_uint64, ctypes.c_int]
    lib.ptc_batcher_next.restype = ctypes.c_int
    lib.ptc_batcher_next.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_void_p),
                                     ctypes.POINTER(ctypes.c_size_t)]
    lib.ptc_batcher_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptc_batcher_new_epoch.argtypes = [ctypes.c_void_p]
    lib.ptc_batcher_destroy.argtypes = [ctypes.c_void_p]
    lib.ptc_multislot_parse.restype = ctypes.c_longlong
    lib.ptc_multislot_parse.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_longlong,
        ctypes.c_longlong, ctypes.POINTER(ctypes.c_longlong)]
    _lib = lib
    return lib


def multislot_parse(text, n_slots, slot_is_int):
    """Parse MultiSlot text in C (reference: data_feed.cc
    MultiSlotDataFeed) — returns (counts [n_rec, n_slots] int64,
    values_lanes [n_vals] 8-byte buffer). Float slots' lanes are
    doubles; int slots' lanes are int64 bit patterns (exact full-range
    ids). Raises ValueError on malformed input."""
    lib = get_lib()
    if isinstance(text, str):
        text = text.encode()
    # bounds: every value/count token is >= 1 char + separator, and a
    # record carries at least n_slots count tokens — so counts stays
    # ~len//2 total instead of scaling with n_slots
    max_vals = len(text) // 2 + 2
    max_recs = len(text) // (2 * max(n_slots, 1)) + 2
    vals = np.empty((max_vals,), np.float64)
    counts = np.empty((max_recs * n_slots,), np.int64)
    flags = (ctypes.c_int * n_slots)(*[int(b) for b in slot_is_int])
    n_vals = ctypes.c_longlong(0)
    rec = lib.ptc_multislot_parse(
        text, len(text), n_slots, flags,
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        max_vals, max_recs, ctypes.byref(n_vals))
    if rec < 0:
        raise ValueError("malformed MultiSlot text (native parser)")
    return (counts[:rec * n_slots].reshape(rec, n_slots).copy(),
            vals[:n_vals.value].copy())


class Arena:
    """Host staging arena (bump allocator with reset; reference:
    auto-growth allocator)."""

    def __init__(self, capacity_bytes):
        self._lib = get_lib()
        self._handle = self._lib.ptc_arena_create(capacity_bytes)
        if not self._handle:
            raise MemoryError("arena allocation failed")
        self.capacity = capacity_bytes

    def alloc_array(self, shape, dtype, align=64):
        """Allocate a numpy view into the arena (no per-step malloc)."""
        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize
        ptr = self._lib.ptc_arena_alloc(self._handle, nbytes, align)
        if not ptr:
            raise MemoryError(
                f"arena exhausted: {self.used}B used of {self.capacity}B")
        buf = (ctypes.c_char * nbytes).from_address(ptr)
        return np.frombuffer(buf, dtype=dtype).reshape(shape)

    @property
    def used(self):
        return self._lib.ptc_arena_used(self._handle)

    @property
    def peak(self):
        return self._lib.ptc_arena_peak(self._handle)

    def reset(self):
        self._lib.ptc_arena_reset(self._handle)

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.ptc_arena_destroy(self._handle)
            self._handle = None


def gather_rows(src, idx, out=None, n_threads=4):
    """Multithreaded dst[i] = src[idx[i]] for a C-contiguous 2D+ table."""
    lib = get_lib()
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:]))
    if out is None:
        out = np.empty((len(idx),) + src.shape[1:], src.dtype)
    lib.ptc_gather_rows(
        src.ctypes.data_as(ctypes.c_void_p), row_bytes,
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(idx),
        out.ctypes.data_as(ctypes.c_void_p), n_threads)
    return out


class NativeBatcher:
    """Background-thread shuffling batcher over contiguous feature arrays
    (reference: buffered_reader + data_feed)."""

    def __init__(self, arrays, batch_size=None, shuffle=False,
                 drop_last=False, seed=0, prefetch_slots=3):
        self._lib = get_lib()
        self.arrays = [np.ascontiguousarray(a) for a in arrays]
        self.n_rows = len(self.arrays[0])
        self.row_bytes = [a.dtype.itemsize * int(np.prod(a.shape[1:]))
                          for a in self.arrays]
        self.batch_size = batch_size
        self._handle = None
        self._cfg = (shuffle, drop_last, seed, prefetch_slots)
        if batch_size is not None:
            self._start()

    def _start(self):
        shuffle, drop_last, seed, slots = self._cfg
        n = len(self.arrays)
        ptrs = (ctypes.c_void_p * n)(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in self.arrays])
        rbs = (ctypes.c_size_t * n)(*self.row_bytes)
        self._handle = self._lib.ptc_batcher_create(
            ptrs, rbs, n, self.n_rows, self.batch_size,
            1 if shuffle else 0, 1 if drop_last else 0, seed, slots)
        if not self._handle:
            raise MemoryError("batcher allocation failed")

    def gather(self, idx):
        """Index-batch fast path used by DataLoader samplers."""
        return tuple(gather_rows(a, idx) for a in self.arrays)

    def __iter__(self):
        if self._handle is None and self.batch_size is None:
            raise RuntimeError("NativeBatcher built without batch_size")
        # A dirty iterator (previous epoch abandoned mid-way, e.g. a `break`
        # in the consumer loop) would otherwise resume with leftover
        # batches — rebuild the C++ batcher for a clean epoch.
        if getattr(self, "_mid_epoch", False):
            self._lib.ptc_batcher_destroy(self._handle)
            self._handle = None
            self._cfg = (self._cfg[0], self._cfg[1],
                         self._cfg[2] + 1, self._cfg[3])  # new shuffle seed
            self._start()
        self._mid_epoch = True
        n = len(self.arrays)
        out_ptrs = (ctypes.c_void_p * n)()
        rows = ctypes.c_size_t()
        try:
            while True:
                slot = self._lib.ptc_batcher_next(self._handle, out_ptrs,
                                                  ctypes.byref(rows))
                if slot < 0:
                    self._lib.ptc_batcher_new_epoch(self._handle)
                    self._mid_epoch = False
                    return
                r = rows.value
                batch = []
                for i, a in enumerate(self.arrays):
                    shape = (r,) + a.shape[1:]
                    nbytes = self.row_bytes[i] * r
                    buf = (ctypes.c_char * nbytes).from_address(out_ptrs[i])
                    # copy out: the slot is recycled after release
                    batch.append(np.frombuffer(buf, dtype=a.dtype)
                                 .reshape(shape).copy())
                self._lib.ptc_batcher_release(self._handle, slot)
                yield tuple(batch)
        except GeneratorExit:
            pass  # _mid_epoch stays True; next __iter__ rebuilds

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.ptc_batcher_destroy(self._handle)
            self._handle = None
