"""paddle_tpu.memory_plan — memory as a planned resource.

PR 12 made memory *observable*: ``monitor.memory.simulate()`` predicts
an executable's HBM peak pre-flight and attributes it by buffer class.
This package makes memory *managed* — three composable mechanisms plus
an auto-picker that turns the predicted-peak model into decisions:

* **Activation rematerialization** (``remat``): ``jax.checkpoint``
  around layer forwards / traced step bodies with named policies —
  ``"none"`` | ``"dots"`` (save dot outputs, recompute elementwise) |
  ``"full"`` (save only the inputs) — or MeshPlan-style per-layer
  regex rules ``((pattern, policy), ...)``, first match wins. Exact:
  the backward replays the identical ops, losses are bit-identical.
* **Optimizer-state host offload** (``offload``): pages the flat
  ``ParamArena`` Adam moments to host RAM after each apply and
  prefetches them back during the next step's forward/backward on a
  dedicated worker thread (the grad-sync comm-worker pattern), so the
  transfers sit on their own trace track (``offload.d2h`` /
  ``offload.h2d``) and only the un-hidden remainder shows up in
  ``mem.offload.exposed_wait_s``. Exact: paging is a bit-preserving
  round trip — and it implies the *split step* (fwd/bwd jitted
  separately from the eager fused apply) so the training executable
  never carries the optimizer state as an argument at all.
* **bf16 device-resident params over fp32 master weights**
  (``master_weights``): the arena keeps the fp32 flat buffer (the
  master — checkpoints stay exact fp32) and binds *bf16 views* inside
  traced steps while the step body runs under ``amp.auto_cast``;
  grads are cast back to fp32 by ``pack_grads`` and the update
  applies to the master. Tolerance-gated: not bit-identical.

``plan_memory(auto=True)`` closes the loop (ROADMAP item 4): simulate
the compiled baseline, derive the candidate ladder (none → dots →
full → full+offload), score each by predicted step-time overhead
(recompute flops on the roofline, offload bytes over the host link),
refuse offload when ``mem.host.headroom_bytes`` can't take the paged
state, pick the cheapest policy that fits ``device_hbm_limit()``, and
record the decision in the monitor ledger exactly like
``planner.plan(auto=True)`` does.
"""
from __future__ import annotations

import contextlib
import os
import re
import threading
import time

__all__ = [
    "MemoryPolicy", "resolve", "policy_key", "checkpoint_policy",
    "remat_scope", "current_remat", "policy_for_layer",
    "install_layer_hook",
    "host_mem_limit", "host_headroom_bytes", "host_link_bandwidth",
    "measure_host_bandwidth", "ArenaOffloader", "attach_offload",
    "detach_offload",
    "plan_memory", "candidate_table", "last_decision", "reset",
]

_REMAT_NAMES = ("none", "dots", "full")


def _canon_remat(pol):
    """Canonicalize a remat spec: None/"none" → None, a policy name →
    itself, anything iterable → a hashable ((pattern, name), ...) rule
    tuple (PR 11's MeshPlan rule idiom)."""
    if pol is None or pol == "none":
        return None
    if isinstance(pol, str):
        if pol not in _REMAT_NAMES:
            raise ValueError(
                f"unknown remat policy {pol!r}: expected one of "
                f"{_REMAT_NAMES} or ((pattern, policy), ...) rules")
        return pol
    rules = []
    for item in pol:
        pat, name = item
        name = None if name in (None, "none") else str(name)
        if name is not None and name not in ("dots", "full"):
            raise ValueError(f"unknown remat policy {name!r} in rule "
                             f"({pat!r}, {name!r})")
        rules.append((str(pat), name))
    return tuple(rules)


class MemoryPolicy:
    """One resolved memory policy: what to remat, whether to page the
    optimizer state to host, whether params go device-bf16 over an
    fp32 master. Hashable + stably keyed so it can join jit/Executor
    cache keys (a policy toggle is exactly one recompile)."""

    __slots__ = ("remat", "offload", "master_weights")

    def __init__(self, remat=None, offload=False, master_weights=False):
        object.__setattr__(self, "remat", _canon_remat(remat))
        object.__setattr__(self, "offload", bool(offload))
        object.__setattr__(self, "master_weights", bool(master_weights))

    def __setattr__(self, name, value):
        raise AttributeError("MemoryPolicy is immutable")

    def key(self):
        return policy_key(self)

    def __repr__(self):
        return (f"MemoryPolicy(remat={self.remat!r}, "
                f"offload={self.offload}, "
                f"master_weights={self.master_weights})")

    def __eq__(self, other):
        return (isinstance(other, MemoryPolicy)
                and self.remat == other.remat
                and self.offload == other.offload
                and self.master_weights == other.master_weights)

    def __hash__(self):
        return hash((self.remat, self.offload, self.master_weights))


def resolve(memory):
    """Coerce a user-facing ``memory=`` knob into a MemoryPolicy.

    Accepts None, ``"auto"`` (returned verbatim — the caller defers to
    :func:`plan_memory` after the baseline compile), a remat name
    (``"none"|"dots"|"full"``), ``"offload"``, a rule tuple, a dict of
    MemoryPolicy fields, or an already-built MemoryPolicy."""
    if memory is None:
        return None
    if isinstance(memory, MemoryPolicy):
        return memory
    if isinstance(memory, str):
        if memory == "auto":
            return "auto"
        if memory == "offload":
            return MemoryPolicy(offload=True)
        return MemoryPolicy(remat=memory)
    if isinstance(memory, dict):
        bad = set(memory) - {"remat", "offload", "master_weights"}
        if bad:
            raise ValueError(f"memory=: unknown fields {sorted(bad)}; "
                             "expected remat/offload/master_weights")
        return MemoryPolicy(**memory)
    return MemoryPolicy(remat=memory)   # rule tuple


def policy_key(pol):
    """Short stable string for cache keys and ledger rows."""
    if pol is None:
        return "none"
    if pol == "auto":
        return "auto"
    r = pol.remat
    if r is None:
        if not pol.offload and not pol.master_weights:
            return "none"  # all-defaults policy == no policy
        rk = "none"
    elif isinstance(r, str):
        rk = r
    else:
        rk = "rules:" + ";".join(f"{p}->{n or 'none'}" for p, n in r)
    parts = [f"remat={rk}"]
    if pol.offload:
        parts.append("offload")
    if pol.master_weights:
        parts.append("bf16master")
    return ",".join(parts)


def checkpoint_policy(name):
    """Map a remat policy name onto ``jax.checkpoint``'s ``policy=``:
    ``"full"`` → None (save nothing but the inputs), ``"dots"`` →
    ``jax.checkpoint_policies.checkpoint_dots`` (save matmul outputs,
    recompute the elementwise tail). Callers only reach here when a
    checkpoint is actually being placed — ``"none"`` means *no*
    ``jax.checkpoint`` at all, which is not this function's job."""
    if name in (None, "none", "full"):
        return None
    if name == "dots":
        import jax
        return jax.checkpoint_policies.checkpoint_dots
    raise ValueError(f"unknown remat policy {name!r}")


# ---------------------------------------------------------------------------
# ambient remat scope + the Layer.__call__ hook

_tls = threading.local()


@contextlib.contextmanager
def remat_scope(policy):
    """Ambient remat policy for every layer called inside — how
    ``to_static(remat=)`` reaches the layers of a traced step body.
    Nested scopes shadow; ``None`` disables."""
    pol = _canon_remat(policy)
    if pol is not None:
        install_layer_hook()
    prev = getattr(_tls, "remat", None)
    _tls.remat = pol
    try:
        yield
    finally:
        _tls.remat = prev


def current_remat():
    return getattr(_tls, "remat", None)


def policy_for_layer(layer, pol):
    """Effective checkpoint-policy name for one layer under ``pol``: a
    plain name applies to the outermost layer reached (the whole
    subtree lands in one checkpoint — nested calls are suppressed by
    the recompute guard), a rule tuple is matched with ``re.search``
    against ``name_scope:ClassName``, first match wins."""
    if pol is None:
        return None
    if isinstance(pol, str):
        return None if pol == "none" else pol
    hay = f"{getattr(layer, '_name_scope', '')}:{type(layer).__name__}"
    for pat, name in pol:
        if re.search(pat, hay):
            return name
    return None


def _layer_remat_hook(layer, args, kwargs):
    """Installed as ``nn.layer._remat_hook`` and consulted by
    ``Layer.__call__``. Returns NotImplemented to mean "no remat here,
    run the normal forward"."""
    pol = getattr(layer, "_remat", None)
    if pol is not None:
        name = policy_for_layer(layer, _canon_remat(pol))
    else:
        name = policy_for_layer(layer, current_remat())
    if name is None:
        return NotImplemented
    from ..tensor import Tensor
    for a in args:
        if a is not None and not isinstance(a, Tensor):
            return NotImplemented   # recompute threads Tensor args only
    for v in kwargs.values():
        if isinstance(v, Tensor):
            return NotImplemented
    from .. import jit as _jit
    return _jit.recompute(layer, *args, policy=name, **kwargs)


_hook_installed = False


def install_layer_hook():
    """Arm the Layer.__call__ remat hook (idempotent). Mirrors
    ``tensor._arena_hook``'s cost discipline: until the first remat
    feature is used the hook is None and layers pay nothing."""
    global _hook_installed
    if _hook_installed:
        return
    from ..nn import layer as _layer_mod
    _layer_mod._remat_hook = _layer_remat_hook
    _hook_installed = True


# ---------------------------------------------------------------------------
# host-side budget + host link bandwidth

def host_mem_limit():
    """Host-memory budget in bytes: $PADDLE_TPU_HOST_MEM_LIMIT_BYTES,
    else autodetected /proc/meminfo MemTotal, else None (no budget)."""
    env = os.environ.get("PADDLE_TPU_HOST_MEM_LIMIT_BYTES")
    if env:
        try:
            return int(float(env))
        except ValueError:
            pass
    try:
        with open("/proc/meminfo", encoding="ascii",
                  errors="replace") as fh:
            for line in fh:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except Exception:
        pass
    return None


def host_headroom_bytes():
    """limit − current RSS, or None when either side is unknown. The
    sampler publishes the same number as ``mem.host.headroom_bytes``;
    the auto-picker uses it to refuse offload the host can't hold."""
    limit = host_mem_limit()
    if limit is None:
        return None
    from ..monitor.sampler import _host_rss_bytes
    rss = _host_rss_bytes()
    if rss is None:
        return None
    return limit - rss


# PCIe-class defaults when nothing is measured or pinned (bytes/s)
_HOST_LINK_DEFAULT = {"tpu": 16e9, "gpu": 16e9, "cpu": 4e9}

_measured_bw = None


def host_link_bandwidth(gbps=None):
    """Host↔device link bandwidth (bytes/s) for the offload cost
    model: explicit arg → $PADDLE_TPU_HOST_LINK_GBPS → the cached
    :func:`measure_host_bandwidth` result → a PCIe-class default."""
    if gbps is not None:
        return float(gbps) * 1e9
    env = os.environ.get("PADDLE_TPU_HOST_LINK_GBPS")
    if env:
        return float(env) * 1e9
    if _measured_bw is not None:
        return _measured_bw
    try:
        import jax
        plat = str(jax.local_devices()[0].platform)
    except Exception:
        plat = "cpu"
    return _HOST_LINK_DEFAULT.get(plat, 4e9)


def measure_host_bandwidth(n_bytes=1 << 24, repeats=3):
    """Measured D2H+H2D round-trip bandwidth (bytes/s), cached so
    :func:`host_link_bandwidth` serves it from then on. Best-of-N
    (the first lap doubles as warmup)."""
    global _measured_bw
    import jax
    import jax.numpy as jnp
    import numpy as np
    n = max(1, int(n_bytes) // 4)
    dev = jax.device_put(jnp.zeros((n,), jnp.float32))
    dev.block_until_ready()
    best = None
    for _ in range(int(repeats) + 1):
        t0 = time.perf_counter()
        host = np.asarray(jax.device_get(dev))
        back = jax.device_put(host)
        back.block_until_ready()
        dt = time.perf_counter() - t0
        if best is None or dt < best:
            best = dt
    _measured_bw = (2.0 * n * 4) / max(best, 1e-9)
    return _measured_bw


# ---------------------------------------------------------------------------
# optimizer-state host offload

class ArenaOffloader:
    """Double-buffered host offload of the arena's Adam moments.

    Mirrors the grad-sync comm worker (``parallel/overlap.py``): one
    worker thread owns the transfers, so the ``offload.d2h`` /
    ``offload.h2d`` spans land on their own trace track and overlap
    the main thread's forward/backward dispatch. Per-step protocol,
    driven from ``Optimizer._apply_update``'s arena branch:

    * :meth:`collect` — before the fused apply: wait for the pending
      prefetch (exposed remainder → ``mem.offload.exposed_wait_s``)
      and rebind the slot tensors to the prefetched device arrays.
    * :meth:`page_out` — after ``arena.finish_step()``: enqueue D2H of
      the just-updated moments, drop the device references (the HBM
      saving — the split fwd/bwd executable never carries them as
      arguments), then start the H2D prefetch for the next apply.

    Only ``grp.slots`` (moment1/moment2 — 2× param bytes, the dominant
    state) page; the fp32 master ``flat`` stays resident (the forward
    reads it) and the beta-pow scalars are not worth a transfer.
    Paging is bit-exact: device_get/device_put round-trip the payload
    untouched, and checkpoints see device state again because
    ``state_dict``/``set_state_dict`` call :meth:`materialize` first.
    """

    def __init__(self):
        self._pool = None
        self._pending = None   # Future -> [(slot_tensor, device_array)]
        self.steps = 0
        self.exposed_wait_s = 0.0
        self.transfer_s = 0.0     # blocking D2H+H2D time in the worker
        self.bytes_out = 0
        self.bytes_in = 0

    def _worker(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="offload-worker")
        return self._pool

    def collect(self, arena, count_exposed=True):
        """Wait for the in-flight page-out/prefetch and rebind the slot
        tensors to the returned device arrays. No-op when idle."""
        fut, self._pending = self._pending, None
        if fut is None:
            return
        from ..monitor import trace as _trace
        from .. import monitor as _mon
        t0 = time.perf_counter()
        with _trace.span("offload.wait"):
            prefetched = fut.result()
        dt = time.perf_counter() - t0
        if count_exposed:
            self.exposed_wait_s += dt
            if _mon.enabled():
                _mon.histogram("mem.offload.exposed_wait_s").observe(dt)
                _mon.counter("mem.offload.exposed_wait_s_total").inc(dt)
        for t, dev in prefetched:
            t.data = dev
        self.steps += 1

    def page_out(self, arena):
        """Asynchronously page the arena's slot buffers to host and
        start the H2D prefetch for the next apply."""
        if self._pending is not None:      # lag-1 safety: never stack
            self.collect(arena, count_exposed=False)
        slots = tuple(t for grp in arena.groups
                      for t in grp.slots.values())
        if not slots:
            return
        offloader = self

        def task():
            import jax
            import numpy as np
            from ..monitor import trace as _trace
            t0 = time.perf_counter()
            nbytes = 0
            hosts = []
            with _trace.span("offload.d2h", n=len(slots)):
                for t in slots:
                    h = np.asarray(jax.device_get(t.data))
                    nbytes += h.nbytes
                    hosts.append(h)
            for t, h in zip(slots, hosts):
                t.data = h        # drop the device reference: HBM freed
            with _trace.span("offload.h2d", n=len(slots),
                             bytes=nbytes):
                devs = [jax.device_put(h) for h in hosts]
                for d in devs:
                    d.block_until_ready()
            offloader.transfer_s += time.perf_counter() - t0
            offloader.bytes_out += nbytes
            offloader.bytes_in += nbytes
            return list(zip(slots, devs))

        self._pending = self._worker().submit(task)

    def materialize(self, arena):
        """Force the optimizer state device-resident (checkpoint
        save/restore slices the slot buffers; exactness requires the
        round trip to have landed)."""
        self.collect(arena, count_exposed=False)

    def shutdown(self):
        pool, self._pool = self._pool, None
        self._pending = None
        if pool is not None:
            pool.shutdown(wait=True)


def attach_offload(opt):
    """Arm optimizer-state host offload on ``opt`` (forces the flat
    arena on — offload pages the arena's flat slot buffers, nothing
    else). Returns the (possibly pre-existing) ArenaOffloader."""
    off = getattr(opt, "_offloader", None)
    if off is None:
        opt.set_flat_arena(True)
        off = ArenaOffloader()
        opt._offloader = off
    return off


def detach_offload(opt):
    """Disarm offload on ``opt``: bring any paged-out slot buffers back
    on device, stop the worker thread, and drop the offloader. The
    optimizer keeps training exactly as before — the arena never left."""
    off = getattr(opt, "_offloader", None)
    if off is None:
        return
    if getattr(opt, "_arena", None) is not None:
        off.materialize(opt._arena)
    off.shutdown()
    opt._offloader = None


# ---------------------------------------------------------------------------
# the auto-picker

_last_decision = None


def _by_class_bytes(rep):
    bc = rep.get("by_class") or {}
    act = float(bc.get("activation", 0.0)) + float(bc.get("remat", 0.0))
    opt = float(bc.get("opt_state", 0.0))
    return act, opt


def candidate_table(rep, limit=None, host_headroom=None,
                    step_flops=None, ceilings=None, link_bps=None):
    """The candidate-policy ladder with predicted peaks and step-time
    overheads, derived from one baseline (no-remat) memory report.

    Peak model (docs/performance.md §8): remat removes a fraction of
    the live-at-peak *activation* class — dots ≈ 50% (the elementwise
    intermediates between saved matmul outputs), full ≈ 90%
    (everything but the checkpointed inputs); offload removes the
    *opt_state* class entirely (the split step's fwd/bwd executable no
    longer carries it). Cost model: "full" recomputes ~one forward
    (fwd ≈ step_flops/3 of the fwd+bwd+apply 6N split) on the roofline
    flops ceiling, "dots" ~25% of a forward; offload moves 2× the
    paged bytes (D2H + H2D) over the host link — assumed hidden behind
    compute, with the un-hidden remainder gated by the smoke's
    exposed-wait check, and refused outright when the host headroom
    can't take the paged state."""
    peak = float(rep["predicted_peak_bytes"])
    act, opt = _by_class_bytes(rep)
    if ceilings is None:
        from ..monitor import profile as _prof
        ceilings = _prof.roofline_ceilings()
    fwd_s = (float(step_flops) / 3.0 / float(ceilings["peak_flops"])
             if step_flops else 0.0)
    link = link_bps if link_bps is not None else host_link_bandwidth()
    offload_s = 2.0 * opt / link
    cands = [
        {"policy": MemoryPolicy(), "name": "none",
         "predicted_peak_bytes": peak, "overhead_s": 0.0},
        {"policy": MemoryPolicy(remat="dots"), "name": "dots",
         "predicted_peak_bytes": peak - 0.5 * act,
         "overhead_s": 0.25 * fwd_s},
        {"policy": MemoryPolicy(remat="full"), "name": "full",
         "predicted_peak_bytes": peak - 0.9 * act,
         "overhead_s": fwd_s},
        {"policy": MemoryPolicy(remat="full", offload=True),
         "name": "full+offload",
         "predicted_peak_bytes": peak - 0.9 * act - opt,
         "overhead_s": fwd_s + offload_s},
    ]
    for c in cands:
        c["feasible"] = (limit is None
                         or c["predicted_peak_bytes"] <= float(limit))
        c["offload_bytes"] = opt if c["policy"].offload else 0.0
        c["host_ok"] = not (c["policy"].offload
                            and host_headroom is not None
                            and opt > host_headroom)
    return cands


def plan_memory(auto=True, label=None, hlo=None, limit=None,
                step_flops=None, link_gbps=None, record=True):
    """Pick the cheapest memory policy whose predicted peak fits.

    Consumes PR 12's predicted-peak model: simulate the captured
    baseline executable (``label`` picks a ``monitor.xla`` capture,
    default newest; ``hlo=`` simulates raw HLO text instead), build
    the candidate ladder via :func:`candidate_table`, drop candidates
    over ``limit`` (default :func:`monitor.memory.device_hbm_limit`)
    or over the host budget, pick the lowest-overhead survivor, and
    record the decision in the monitor ledger exactly like
    ``planner.plan(auto=True)`` (counters ``memory_plan.plan`` /
    ``memory_plan.auto_pick``, gauges, one ``kind="memory_plan"``
    JSONL record, :func:`last_decision`). Raises ValueError when no
    candidate fits — the planner's all-infeasible refusal, not a
    silent OOM. ``auto=False`` builds and records the table but
    returns the baseline policy regardless of fit."""
    global _last_decision
    from ..monitor import memory as _mem
    from ..monitor import xla as _xla
    rep = _mem.report(label=label, hlo=hlo, emit_records=False)
    if rep is None:
        raise ValueError(
            "plan_memory: nothing to simulate — enable the monitor and "
            "compile a baseline step first (the aot capture feeds the "
            "predicted-peak model), or pass hlo=")
    if limit is None:
        limit = _mem.device_hbm_limit()
    if step_flops is None:
        try:
            step_flops = _xla.flops(rep.get("label"))
        except Exception:
            step_flops = None
    headroom = host_headroom_bytes()
    link = (float(link_gbps) * 1e9 if link_gbps
            else host_link_bandwidth())
    cands = candidate_table(rep, limit=limit, host_headroom=headroom,
                            step_flops=step_flops, link_bps=link)
    eligible = [c for c in cands if c["feasible"] and c["host_ok"]]
    if auto:
        if not eligible:
            best = min(c["predicted_peak_bytes"] for c in cands)
            raise ValueError(
                "plan_memory: every memory policy exceeds the budget "
                f"(hbm_limit={limit}, best predicted peak={best:.0f}, "
                f"host_headroom={headroom}) — shard the model "
                "(planner.advise) or raise PADDLE_TPU_HBM_LIMIT_BYTES")
        pick = min(eligible, key=lambda c: (c["overhead_s"],
                                            c["predicted_peak_bytes"]))
    else:
        pick = cands[0]
    decision = {
        "kind": "memory_plan",
        "ts": time.time(),
        "auto": bool(auto),
        "label": rep.get("label"),
        "policy": pick["policy"],
        "picked": pick["name"],
        "policy_key": policy_key(pick["policy"]),
        "predicted_peak_bytes": pick["predicted_peak_bytes"],
        "baseline_peak_bytes": rep["predicted_peak_bytes"],
        "overhead_s": pick["overhead_s"],
        "hbm_limit_bytes": limit,
        "host_headroom_bytes": headroom,
        "host_link_bytes_per_s": link,
        "step_flops": step_flops,
        "table": [{k: v for k, v in c.items() if k != "policy"}
                  for c in cands],
    }
    _last_decision = decision
    if record:
        _record(decision)
    return decision


def _record(decision):
    from .. import monitor as _monitor
    if not _monitor.enabled():
        return
    _monitor.counter("memory_plan.plan").inc()
    if decision["auto"]:
        _monitor.counter("memory_plan.auto_pick").inc()
    _monitor.gauge("memory_plan.candidates").set(
        len(decision["table"]))
    _monitor.gauge("memory_plan.predicted_peak_bytes").set(
        decision["predicted_peak_bytes"])
    _monitor.gauge("memory_plan.overhead_s").set(
        decision["overhead_s"])
    _monitor.emit(kind="memory_plan", auto=decision["auto"],
                  picked=decision["picked"],
                  policy_key=decision["policy_key"],
                  label=decision["label"],
                  predicted_peak_bytes=decision["predicted_peak_bytes"],
                  baseline_peak_bytes=decision["baseline_peak_bytes"],
                  overhead_s=decision["overhead_s"],
                  hbm_limit_bytes=decision["hbm_limit_bytes"],
                  host_headroom_bytes=decision["host_headroom_bytes"],
                  table=decision["table"])


def last_decision():
    """The most recent plan_memory() decision dict (None before the
    first call) — same contract as planner.last_decision()."""
    return _last_decision


def reset():
    global _last_decision, _measured_bw
    _last_decision = None
    _measured_bw = None
