"""paddle_tpu.device — device/place management.

TPU-native rebuild of the reference's Place abstraction
(reference: python/paddle/fluid/framework.py CPUPlace/CUDAPlace +
paddle/fluid/platform/place.h). CUDAPlace becomes TPUPlace; a Place wraps a
jax.Device. `set_device` steers default placement via jax.default_device.
"""
from __future__ import annotations

import jax


class Place:
    def __init__(self, device):
        self.device = device

    def __repr__(self):
        return f"Place({self.device})"

    def __eq__(self, other):
        return isinstance(other, Place) and self.device == other.device


class CPUPlace(Place):
    def __init__(self, idx=0):
        super().__init__(jax.devices("cpu")[idx]
                         if _has_platform("cpu") else jax.devices()[0])


class TPUPlace(Place):
    def __init__(self, idx=0):
        devs = jax.devices()
        super().__init__(devs[idx % len(devs)])


class CUDAPinnedPlace(Place):
    """reference place.h:CUDAPinnedPlace — page-locked host staging
    memory. Host-side staging here is the csrc arena; the place object
    exists so device-placement code ports, and resolves to host CPU."""

    def __init__(self):
        super().__init__(jax.devices("cpu")[0]
                         if _has_platform("cpu") else jax.devices()[0])


# parity alias: code written against the reference uses CUDAPlace for the
# accelerator
CUDAPlace = TPUPlace


def _has_platform(name):
    try:
        jax.devices(name)
        return True
    except RuntimeError:
        return False


_current = None


def set_device(device):
    """paddle.set_device('tpu'/'cpu'/'tpu:0')."""
    global _current
    name = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    if name in ("tpu", "gpu", "xpu", "cuda"):
        place = TPUPlace(idx)
    else:
        place = CPUPlace(idx)
    _current = place
    jax.config.update("jax_default_device", place.device)
    return place


def get_device():
    if _current is None:
        return f"{jax.devices()[0].platform}:0"
    return f"{_current.device.platform}:{_current.device.id}"


def is_compiled_with_cuda():
    """Parity shim — reports accelerator availability (TPU here)."""
    return any(d.platform != "cpu" for d in jax.devices())


def is_compiled_with_tpu():
    return any(d.platform in ("tpu", "axon") for d in jax.devices())


def device_count():
    return jax.device_count()


def enable_compilation_cache(path=None, min_compile_time_secs=1.0):
    """Point XLA's persistent compilation cache at ``path`` so executables
    survive process restarts — the second run of a job skips its multi-
    minute compile entirely.

    ``path`` defaults to ``$PADDLE_TPU_COMPILE_CACHE_DIR`` or
    ``~/.cache/paddle_tpu/xla_cache``. Programs that compile faster than
    ``min_compile_time_secs`` are not persisted (tiny shapes would churn
    the cache for no win). Returns the cache path, or ``None`` if this
    jax build does not support a persistent cache.
    """
    import os
    if path is None:
        path = os.environ.get(
            "PADDLE_TPU_COMPILE_CACHE_DIR",
            os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                         "xla_cache"))
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
    except Exception:
        return None
    return path
