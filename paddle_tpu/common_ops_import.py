"""paddle_tpu.common_ops_import — the op-author import aggregator.

Reference: python/paddle/common_ops_import.py, an internal shim that
re-exported the names op-definition modules need (LayerHelper,
ParamAttr, Variable, dygraph-mode checks, ...). Provided for source
compatibility with reference op code being ported onto this framework.

Intentionally absent (their mechanism has no TPU-side equivalent —
ops here are jax functions, not OpDesc emissions): OpProtoHolder,
LayerHelper, _varbase_creator, _dygraph_tracer. Porting guidance:
where reference op code used LayerHelper.create_variable_for_type_
inference + append_op, write the computation directly with jax/lax and
register it via paddle_tpu.dispatch.apply (see docs/porting_guide.md).
"""
from functools import reduce  # noqa: F401  (reference used six.moves)

from .fluid.framework import (  # noqa: F401
    Variable, in_dygraph_mode, default_main_program, device_guard,
)
from .fluid.param_attr import ParamAttr  # noqa: F401
from .initializer import Constant  # noqa: F401
from . import fluid  # noqa: F401
from .fluid import layers  # noqa: F401
from .tensor import convert_dtype as convert_np_dtype_to_dtype_  # noqa: F401
import numpy as np  # noqa: F401

__all__ = [
    "reduce", "Variable", "in_dygraph_mode", "default_main_program",
    "device_guard", "ParamAttr", "Constant", "fluid", "layers",
    "convert_np_dtype_to_dtype_", "np",
]
