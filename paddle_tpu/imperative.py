"""paddle.imperative parity package (reference:
python/paddle/imperative/__init__.py)."""
from .fluid.dygraph import (enabled, guard, to_variable,  # noqa: F401
                            TracedLayer, BackwardStrategy)
from .autograd import no_grad, grad  # noqa: F401
from .nn import LayerList, ParameterList, Sequential  # noqa: F401
from .io import save_dygraph as save  # noqa: F401
from .io import load_dygraph as load  # noqa: F401
from .parallel.env import prepare_context  # noqa: F401


