"""paddle_tpu.resilience.preempt — SIGTERM/SIGINT-safe training.

TPU pools preempt: the scheduler sends SIGTERM and the process has
seconds to persist state. The handler here converts that signal into a
*cooperative* flag the training loop polls at step boundaries — the
loop (``hapi.Model.fit`` / ``Executor.train_from_dataset``) then writes
one atomic final checkpoint (``resilience.preempt_save``) and stops
cleanly, so the next invocation's ``auto_resume=True`` continues at the
right step.

With a :class:`~paddle_tpu.io.CheckpointManager` *attached*
(:meth:`PreemptionHandler.attach` — the train loops do this when given
one), a real SIGTERM additionally flushes a final save *inside*
:meth:`request`, before the prior handler chains: if the scheduler's
grace window is too short for the loop to reach its next step boundary,
state has already landed on disk (``resilience.preempt_save`` with the
saved step). The loop then sees ``flushed_step`` set and skips its own
boundary save. Step boundaries remain the preferred save point — the
loop calls :meth:`notify_step` so the flush never captures
mid-step state: the flush saves the last *completed* step.

Signal handlers are process-global and main-thread-only; installation
from a worker thread is a silent no-op (the flag can still be set by
:func:`request` — how simulated preemption and tests drive it).
"""
from __future__ import annotations

import signal
import threading
import warnings

from ._common import record


class PreemptionHandler:
    """Install with ``with PreemptionHandler() as p:`` (or
    ``install()``/``uninstall()``); poll ``p.triggered`` at step
    boundaries. Previous handlers are chained — an outer framework's
    SIGTERM logic still runs — and restored on uninstall."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 on_preempt=None):
        self.signals = tuple(signals)
        self.on_preempt = on_preempt
        self._event = threading.Event()
        self._previous = {}
        self._installed = False
        self._save_fn = None
        self._ckpt = None
        self._last_step = None
        self.flushed_step = None  # set when request() flushed a save

    def attach(self, checkpoint_manager=None, save_fn=None):
        """Arm the final-save flush: on a real signal, :meth:`request`
        calls ``save_fn(step)`` (default:
        ``checkpoint_manager.save(step)``) with the last step reported
        via :meth:`notify_step`. Train loops attach a save_fn that
        captures their model/optimizer."""
        self._ckpt = checkpoint_manager
        if save_fn is not None:
            self._save_fn = save_fn
        elif checkpoint_manager is not None:
            self._save_fn = checkpoint_manager.save
        else:
            self._save_fn = None
        return self

    def notify_step(self, step):
        """Record the last *completed* step — what a flush would save."""
        self._last_step = step

    @property
    def triggered(self):
        return self._event.is_set()

    def _flush_save(self, signum):
        if self._save_fn is None or self._last_step is None:
            return
        step = self._last_step
        try:
            self._save_fn(step)
        except Exception as e:  # the signal path must never die saving
            warnings.warn(
                f"PreemptionHandler: final save at step {step} failed "
                f"({e!r}); relying on the last periodic checkpoint")
            return
        self.flushed_step = step
        record("preempt_save", step=step, where="signal_flush",
               signum=signum)

    def request(self, signum=None):
        """Mark preemption requested (the signal handler body; also the
        entry point for simulated preemption)."""
        first = not self._event.is_set()
        self._event.set()
        if first:
            record("preempt_signal", signum=signum)
            if signum is not None:
                self._flush_save(signum)
            if self.on_preempt is not None:
                self.on_preempt(signum)

    def _handle(self, signum, frame):
        self.request(signum)
        prev = self._previous.get(signum)
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL,
                                           signal.default_int_handler):
            prev(signum, frame)

    def install(self):
        if self._installed:
            return self
        try:
            for s in self.signals:
                self._previous[s] = signal.signal(s, self._handle)
            self._installed = True
        except ValueError:
            # not the main thread: signals can't be installed here; the
            # cooperative flag still works via request()
            self._previous.clear()
        return self

    def uninstall(self):
        if not self._installed:
            return
        for s, prev in self._previous.items():
            try:
                signal.signal(s, prev)
            except ValueError:
                pass
        self._previous.clear()
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
