"""paddle_tpu.resilience.preempt — SIGTERM/SIGINT-safe training.

TPU pools preempt: the scheduler sends SIGTERM and the process has
seconds to persist state. The handler here converts that signal into a
*cooperative* flag the training loop polls at step boundaries — the
loop (``hapi.Model.fit`` / ``Executor.train_from_dataset``) then writes
one atomic final checkpoint (``resilience.preempt_save``) and stops
cleanly, so the next invocation's ``auto_resume=True`` continues at the
right step.

With a :class:`~paddle_tpu.io.CheckpointManager` *attached*
(:meth:`PreemptionHandler.attach` — the train loops do this when given
one), a real SIGTERM additionally flushes a final save *inside*
:meth:`request`, before the prior handler chains: if the scheduler's
grace window is too short for the loop to reach its next step boundary,
state has already landed on disk (``resilience.preempt_save`` with the
saved step). The loop then sees ``flushed_step`` set and skips its own
boundary save. Step boundaries remain the preferred save point — the
loop calls :meth:`notify_step` so the flush never captures
mid-step state: the flush saves the last *completed* step.

Signal handlers are process-global and main-thread-only; installation
from a worker thread is a silent no-op (the flag can still be set by
:func:`request` — how simulated preemption and tests drive it).

Beyond training, preemption is a *process lifecycle* event any
subsystem may need to hear: :func:`subscribe` registers a process-level
listener that every :class:`PreemptionHandler` broadcasts to on its
first trigger (real signal or simulated). The serving tier subscribes
its replica fleets here — on SIGTERM a fleet flips to ``draining``,
stops admitting, and finishes (or migrates) its in-flight work instead
of dying mid-stream (see docs/robustness.md, "Serving lifecycle").
Every notice increments ``resilience.preempt.notice``.
"""
from __future__ import annotations

import signal
import threading
import warnings

from ._common import record

# -- process-level lifecycle broadcast --------------------------------------

_sub_lock = threading.Lock()
_subscribers = []

#: handlers in install order — uninstalling out of LIFO order splices
#: the chain instead of clobbering a later handler's registration
_install_stack = []


def subscribe(callback):
    """Register a process-level preemption listener: ``callback(signum)``
    runs on the FIRST trigger of any :class:`PreemptionHandler` (real
    signal or simulated :meth:`~PreemptionHandler.request`). Returns the
    callback, which doubles as the :func:`unsubscribe` handle. Callbacks
    must be fast and must not raise — failures are warned and
    swallowed; the signal path must never die notifying."""
    with _sub_lock:
        _subscribers.append(callback)
    return callback


def unsubscribe(callback):
    """Remove a listener registered with :func:`subscribe` (idempotent)."""
    with _sub_lock:
        try:
            _subscribers.remove(callback)
        except ValueError:
            pass


def notify(signum=None):
    """Broadcast one preemption notice to every subscriber and count it
    (``resilience.preempt.notice``). Handlers call this on their first
    trigger; tests and simulated preemption may call it directly."""
    record("preempt.notice", signum=signum)
    with _sub_lock:
        subs = list(_subscribers)
    for cb in subs:
        try:
            cb(signum)
        except Exception as e:   # noqa: BLE001 - never die notifying
            warnings.warn(
                f"preempt subscriber {cb!r} failed: {e!r}")


class PreemptionHandler:
    """Install with ``with PreemptionHandler() as p:`` (or
    ``install()``/``uninstall()``); poll ``p.triggered`` at step
    boundaries. Previous handlers are chained — an outer framework's
    SIGTERM logic still runs — and restored on uninstall."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 on_preempt=None):
        self.signals = tuple(signals)
        self.on_preempt = on_preempt
        self._event = threading.Event()
        self._previous = {}
        self._installed = False
        self._save_fns = []
        self._ckpt = None
        self._last_step = None
        self.flushed_step = None  # set when request() flushed a save

    def attach(self, checkpoint_manager=None, save_fn=None):
        """Arm the final-save flush: on a real signal, :meth:`request`
        calls each attached ``save_fn(step)`` (default:
        ``checkpoint_manager.save(step)``) with the last step reported
        via :meth:`notify_step`. Train loops attach a save_fn that
        captures their model/optimizer. Repeated calls *accumulate*
        callbacks — several subsystems can each arm their own flush;
        they run in attach order."""
        if checkpoint_manager is not None:
            self._ckpt = checkpoint_manager
        fn = save_fn if save_fn is not None else (
            checkpoint_manager.save if checkpoint_manager is not None
            else None)
        if fn is not None and fn not in self._save_fns:
            self._save_fns.append(fn)
        return self

    def detach(self, save_fn=None):
        """Drop one attached callback (or all, when ``save_fn=None``)."""
        if save_fn is None:
            self._save_fns.clear()
            self._ckpt = None
        else:
            try:
                self._save_fns.remove(save_fn)
            except ValueError:
                pass
        return self

    def notify_step(self, step):
        """Record the last *completed* step — what a flush would save."""
        self._last_step = step

    @property
    def triggered(self):
        return self._event.is_set()

    def _flush_save(self, signum):
        if not self._save_fns or self._last_step is None:
            return
        step = self._last_step
        any_ok = False
        for fn in list(self._save_fns):
            try:
                fn(step)
                any_ok = True
            except Exception as e:  # the signal path must never die saving
                warnings.warn(
                    f"PreemptionHandler: final save at step {step} failed "
                    f"({e!r}); relying on the last periodic checkpoint")
        if not any_ok:
            return
        self.flushed_step = step
        record("preempt_save", step=step, where="signal_flush",
               signum=signum)

    def request(self, signum=None):
        """Mark preemption requested (the signal handler body; also the
        entry point for simulated preemption)."""
        first = not self._event.is_set()
        self._event.set()
        if first:
            record("preempt_signal", signum=signum)
            if signum is not None:
                self._flush_save(signum)
            if self.on_preempt is not None:
                self.on_preempt(signum)
            notify(signum)

    def _handle(self, signum, frame):
        self.request(signum)
        prev = self._previous.get(signum)
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL,
                                           signal.default_int_handler):
            prev(signum, frame)

    def install(self):
        if self._installed:
            return self
        try:
            for s in self.signals:
                self._previous[s] = signal.signal(s, self._handle)
            self._installed = True
            _install_stack.append(self)
        except ValueError:
            # not the main thread: signals can't be installed here; the
            # cooperative flag still works via request()
            self._previous.clear()
        return self

    def uninstall(self):
        """Remove this handler; safe in any order. The most recently
        installed handler restores the OS registration it replaced
        (LIFO); a handler buried beneath later installs is *spliced out*
        instead — the nearest handler above it that chains to this one
        is repointed at this handler's predecessor, so no later
        handler's registration is clobbered."""
        if not self._installed:
            return
        try:
            idx = _install_stack.index(self)
        except ValueError:
            idx = -1
        above = _install_stack[idx + 1:] if idx >= 0 else []
        for s, prev in self._previous.items():
            spliced = False
            for h in above:
                if h._previous.get(s) == self._handle:
                    h._previous[s] = prev
                    spliced = True
                    break
            if not spliced:
                try:
                    if signal.getsignal(s) == self._handle:
                        signal.signal(s, prev)
                except ValueError:
                    pass
        if idx >= 0:
            _install_stack.pop(idx)
        self._previous.clear()
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
