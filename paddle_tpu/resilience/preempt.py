"""paddle_tpu.resilience.preempt — SIGTERM/SIGINT-safe training.

TPU pools preempt: the scheduler sends SIGTERM and the process has
seconds to persist state. The handler here converts that signal into a
*cooperative* flag the training loop polls at step boundaries — the
loop (``hapi.Model.fit`` / ``Executor.train_from_dataset``) then writes
one atomic final checkpoint (``resilience.preempt_save``) and stops
cleanly, so the next invocation's ``auto_resume=True`` continues at the
right step. Doing the save at a step boundary rather than inside the
signal handler keeps it off the async-signal path (no half-updated
optimizer state, no reentrant pickling).

Signal handlers are process-global and main-thread-only; installation
from a worker thread is a silent no-op (the flag can still be set by
:func:`request` — how simulated preemption and tests drive it).
"""
from __future__ import annotations

import signal
import threading

from ._common import record


class PreemptionHandler:
    """Install with ``with PreemptionHandler() as p:`` (or
    ``install()``/``uninstall()``); poll ``p.triggered`` at step
    boundaries. Previous handlers are chained — an outer framework's
    SIGTERM logic still runs — and restored on uninstall."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 on_preempt=None):
        self.signals = tuple(signals)
        self.on_preempt = on_preempt
        self._event = threading.Event()
        self._previous = {}
        self._installed = False

    @property
    def triggered(self):
        return self._event.is_set()

    def request(self, signum=None):
        """Mark preemption requested (the signal handler body; also the
        entry point for simulated preemption)."""
        first = not self._event.is_set()
        self._event.set()
        if first:
            record("preempt_signal", signum=signum)
            if self.on_preempt is not None:
                self.on_preempt(signum)

    def _handle(self, signum, frame):
        self.request(signum)
        prev = self._previous.get(signum)
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL,
                                           signal.default_int_handler):
            prev(signum, frame)

    def install(self):
        if self._installed:
            return self
        try:
            for s in self.signals:
                self._previous[s] = signal.signal(s, self._handle)
            self._installed = True
        except ValueError:
            # not the main thread: signals can't be installed here; the
            # cooperative flag still works via request()
            self._previous.clear()
        return self

    def uninstall(self):
        if not self._installed:
            return
        for s, prev in self._previous.items():
            try:
                signal.signal(s, prev)
            except ValueError:
                pass
        self._previous.clear()
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
