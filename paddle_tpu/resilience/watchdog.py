"""paddle_tpu.resilience.watchdog — hung-step detection.

A deadlocked collective, a stuck host callback, or an input pipeline
wedge all look the same from outside: the step just never ends. The
watchdog is a daemon thread that knows when each step started and flags
any step exceeding a rolling deadline — ``factor`` × the p99 of recent
step times once enough history exists, never below ``min_deadline``.
On a stall it emits ``resilience.watchdog_stall`` plus a one-shot
monitor state dump (every counter/gauge, so the post-mortem shows what
the run was doing when it wedged) and calls the optional ``on_stall``
hook. It never kills the step itself — detection and evidence, not
preemption.

Usage::

    wd = Watchdog(min_deadline=30.0).start()
    for i, batch in enumerate(loader):
        with wd.step(i):
            train_step(batch)
    wd.stop()

``hapi.Model.fit(watchdog=True)`` wires this around its train loop.
"""
from __future__ import annotations

import collections
import threading
import time
import weakref

from .. import monitor as _monitor
from ._common import record

# every started watchdog, for the /healthz endpoint — weak so an
# abandoned watchdog never outlives its owner through this set
_ACTIVE = weakref.WeakSet()


def health():
    """Health snapshots of every running watchdog (the monitor export
    server's /healthz feed): a list of :meth:`Watchdog.health` dicts.
    Empty list = no watchdog armed (liveness only, no stall signal)."""
    return [wd.health() for wd in list(_ACTIVE)]


class Watchdog:
    """See module docstring.

    min_deadline — floor (and the deadline until ``warmup`` steps of
    history exist); factor × rolling p99 takes over after warmup.
    """

    def __init__(self, min_deadline=30.0, factor=4.0, warmup=5,
                 poll=0.05, history=256, on_stall=None):
        self.min_deadline = float(min_deadline)
        self.factor = float(factor)
        self.warmup = int(warmup)
        self.poll = float(poll)
        self.on_stall = on_stall
        self._durations = collections.deque(maxlen=history)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._current = None      # (step_id, t0) while a step runs
        self._flagged = None      # step_id already reported this pass
        self.stall_count = 0

    # -- deadline -----------------------------------------------------------

    def deadline(self):
        with self._lock:
            if len(self._durations) < self.warmup:
                return self.min_deadline
            ordered = sorted(self._durations)
            p99 = ordered[min(len(ordered) - 1,
                              int(0.99 * (len(ordered) - 1) + 0.999))]
        return max(self.min_deadline, self.factor * p99)

    # -- step bracketing ------------------------------------------------------

    class _StepScope:
        def __init__(self, wd, step_id):
            self._wd = wd
            self._step_id = step_id

        def __enter__(self):
            wd = self._wd
            with wd._lock:
                wd._current = (self._step_id, time.monotonic())
            return self

        def __exit__(self, *exc):
            wd = self._wd
            with wd._lock:
                cur = wd._current
                wd._current = None
                if cur is not None:
                    wd._durations.append(time.monotonic() - cur[1])
            return False

    def step(self, step_id=None):
        """Context manager bracketing one training step."""
        return Watchdog._StepScope(self, step_id)

    # -- health introspection -------------------------------------------------

    def health(self):
        """Point-in-time health: whether a step is in flight, how long
        it has run vs the current deadline, and the cumulative stall
        count. ``stalled`` is live (the in-flight step is past deadline
        RIGHT NOW), independent of whether the watcher thread has
        flagged it yet — /healthz must flip the moment the SLA is
        blown, not a poll interval later."""
        with self._lock:
            cur = self._current
        deadline = self.deadline()
        out = {"running": self._thread is not None
               and self._thread.is_alive(),
               "stall_count": self.stall_count,
               "deadline_s": deadline,
               "in_step": cur is not None,
               "stalled": False}
        if cur is not None:
            step_id, t0 = cur
            elapsed = time.monotonic() - t0
            out.update(step=step_id, elapsed_s=elapsed,
                       stalled=elapsed > deadline)
        return out

    # -- the watcher thread ---------------------------------------------------

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._watch, name="paddle_tpu-watchdog", daemon=True)
            self._thread.start()
        _ACTIVE.add(self)
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        _ACTIVE.discard(self)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _watch(self):
        while not self._stop.wait(self.poll):
            with self._lock:
                cur = self._current
            if cur is None:
                continue
            step_id, t0 = cur
            if self._flagged == (step_id, t0):
                continue  # one report per hung step
            elapsed = time.monotonic() - t0
            deadline = self.deadline()
            if elapsed > deadline:
                self._flagged = (step_id, t0)
                self.stall_count += 1
                record("watchdog_stall", step=step_id, elapsed=elapsed,
                       deadline=deadline)
                if _monitor.enabled():
                    # the post-mortem payload: everything the run was
                    # doing — counters inline, plus a flight-recorder
                    # directory (spans + counters + active HLO) whose
                    # path rides in the same JSONL record
                    flight = _monitor.trace.flight_record(
                        "watchdog_stall", step=step_id,
                        extra={"elapsed": elapsed, "deadline": deadline})
                    _monitor.emit(kind="watchdog_dump", step=step_id,
                                  elapsed=elapsed, deadline=deadline,
                                  flight_dir=flight,
                                  counters=_monitor.snapshot())
                if self.on_stall is not None:
                    try:
                        self.on_stall(step_id, elapsed, deadline)
                    except Exception:
                        pass  # a broken hook must not kill the watcher
