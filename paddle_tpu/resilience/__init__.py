"""paddle_tpu.resilience — the fault-tolerant training runtime.

Long multi-chip runs die three ways: transient I/O kills the input
pipeline, a NaN step silently poisons every parameter, and a scheduler
preemption lands mid-checkpoint and leaves garbage on disk. This
subsystem turns each into a bounded, observable recovery:

* :mod:`~paddle_tpu.resilience.retry`    — exponential backoff with
  deterministic jitter + max-attempt budgets (prefetch producer,
  DataLoader assembly, checkpoint I/O)
* :mod:`~paddle_tpu.resilience.guard`    — step-level NaN/Inf guard
  (``skip`` / ``rollback_to_last_ckpt`` / ``raise``) built on the AMP
  scaler's fused finite-check, jit-safe
* :mod:`~paddle_tpu.resilience.watchdog` — hung-step detection against
  a rolling p99 deadline, with a monitor state dump per stall
* :mod:`~paddle_tpu.resilience.preempt`  — SIGTERM/SIGINT → one atomic
  final checkpoint + clean stop; ``fit(auto_resume=True)`` /
  ``train_from_dataset(auto_resume=True)`` continue at the right step
* :mod:`~paddle_tpu.resilience.faults`   — deterministic fault
  injection (the tests' and chaos CI gate's chaos source)
* :mod:`~paddle_tpu.resilience.deadline` — monotonic wall-time budgets
  (:class:`Deadline`); the serving tier's admission controller drops
  expired requests at dequeue so they never occupy a batch slot
* :mod:`~paddle_tpu.resilience.elastic`  — the elastic recovery loop:
  restart after :class:`HostLossError` on a mesh shrunk to the
  surviving devices, resuming from the last complete sharded
  checkpoint at the exact next step

Checkpoint hardening itself (tmp-file + ``os.replace``, sha256
sidecars, corrupt-file quarantine) lives in
:class:`paddle_tpu.io.CheckpointManager`.

Every recovery emits a ``resilience.*`` monitor counter and JSONL
event: ``retry``, ``drop``, ``nan_skip``, ``rollback``, ``nan_raise``,
``watchdog_stall``, ``preempt_signal``, ``preempt_save``,
``auto_resume``, ``ckpt_quarantine``, ``fault_injected``.

See docs/robustness.md for the workflow guide.
"""
from __future__ import annotations

from . import faults  # noqa: F401
from . import retry  # noqa: F401
from . import guard  # noqa: F401
from . import watchdog  # noqa: F401
from . import preempt  # noqa: F401
from . import deadline  # noqa: F401
from . import elastic  # noqa: F401
from ._common import record  # noqa: F401
from .deadline import Deadline  # noqa: F401
from .retry import (RetryPolicy, RetryExhausted, TransientError,  # noqa: F401
                    retry_call, retrying, is_transient)
from .guard import NaNGuard, NonFiniteError  # noqa: F401
from .watchdog import Watchdog  # noqa: F401
from .preempt import PreemptionHandler  # noqa: F401
from .preempt import subscribe, unsubscribe  # noqa: F401
from .faults import HostLossError  # noqa: F401
from .elastic import ElasticSupervisor  # noqa: F401

__all__ = [
    "faults", "retry", "guard", "watchdog", "preempt", "deadline",
    "elastic", "RetryPolicy", "RetryExhausted", "TransientError",
    "retry_call", "retrying", "is_transient", "NaNGuard",
    "NonFiniteError", "Watchdog", "PreemptionHandler", "HostLossError",
    "ElasticSupervisor", "Deadline", "record", "subscribe",
    "unsubscribe",
]

# PADDLE_TPU_FAULTS='[{"kind":"loader","step":3}]' registers faults at
# import time — chaos runs with zero code changes.
import os as _os
if _os.environ.get("PADDLE_TPU_FAULTS"):
    faults.load_env()
