"""paddle_tpu.resilience.elastic — the elastic recovery loop.

A pod-scale run must survive two distinct ends-of-the-world:

* **preemption** — the scheduler says *stop*: SIGTERM lands, the
  :mod:`~paddle_tpu.resilience.preempt` handler flushes a final sharded
  save, and the job should NOT restart (the scheduler owns the next
  incarnation).
* **host loss** — a worker (and its devices) silently drops out:
  the run dies mid-step with :class:`~paddle_tpu.resilience.faults.
  HostLossError` (or, on real hardware, a device error the caller maps
  to one), and the job SHOULD restart — on a smaller mesh, from the
  last complete checkpoint, at the exact next step.

:class:`ElasticSupervisor` is the control loop gluing those together.
Each *attempt* plans a mesh over the devices still available
(:meth:`plan_mesh` shrinks the data axis first, so model-parallel
groups stay intact), registers it as the global mesh, and calls the
user's ``train_fn(attempt)`` — which runs ``hapi.Model.fit(...,
auto_resume=True)`` or ``Executor.train_from_dataset`` against
``attempt.mesh``. Sharded checkpoints written on the old topology
restore onto the new one through
:meth:`paddle_tpu.io.CheckpointManager.restore`'s reshard-on-load
path, so resuming after a resize is the same code path as resuming
after a clean stop. Worker liveness is observable through
:meth:`liveness` (reusing :func:`paddle_tpu.resilience.watchdog.
health` — the same feed the monitor's /healthz serves).

Every transition is recorded: ``resilience.elastic_attempt``,
``elastic_restart`` (a host died; restarting), ``elastic_resize``
(the planned mesh differs from the previous attempt's),
``elastic_preempt_stop`` and ``elastic_done``.
"""
from __future__ import annotations

import numpy as np

from ._common import record
from .faults import HostLossError
from . import watchdog as _watchdog


class Attempt:
    """One incarnation of the run: the mesh it trains on, which devices
    back it, and whether it should auto-resume from the checkpoint."""

    def __init__(self, number, mesh, axes, devices, checkpoint,
                 auto_resume):
        self.number = number
        self.mesh = mesh
        self.axes = axes
        self.devices = devices
        self.checkpoint = checkpoint
        self.auto_resume = auto_resume

    def __repr__(self):
        return (f"Attempt(number={self.number}, axes={self.axes}, "
                f"devices={len(self.devices)}, "
                f"auto_resume={self.auto_resume})")


class ElasticSupervisor:
    """Restart-on-host-loss supervisor around a training function.

    checkpoint   — the run's :class:`~paddle_tpu.io.CheckpointManager`
                   (``sharded=True`` for topology-elastic restores).
    mesh_axes    — the full-strength mesh, e.g. ``{"dp": 4, "tp": 2}``;
                   None trains unmeshed (single device).
    shrink_axis  — which axis absorbs lost devices (default: the first,
                   conventionally the data axis).
    max_restarts — restart budget; one more :class:`HostLossError`
                   re-raises to the caller.
    """

    def __init__(self, checkpoint=None, mesh_axes=None, shrink_axis=None,
                 max_restarts=3):
        self.checkpoint = checkpoint
        self.mesh_axes = dict(mesh_axes) if mesh_axes else None
        if shrink_axis is None and self.mesh_axes:
            shrink_axis = next(iter(self.mesh_axes))
        self.shrink_axis = shrink_axis
        self.max_restarts = int(max_restarts)
        self.lost_devices = 0
        self.attempts = []

    # -- observability ----------------------------------------------------

    def liveness(self):
        """Watchdog health snapshots (the /healthz feed): stalled or
        dead step-loops show up here before they show up as losses."""
        return _watchdog.health()

    def available_devices(self):
        """Devices this incarnation may use: the visible set minus the
        ones reported lost (simulated loss keeps the jax client's
        device list intact, so the supervisor does the subtraction)."""
        import jax
        devs = jax.devices()
        n = max(1, len(devs) - self.lost_devices)
        return devs[:n]

    # -- topology planning ------------------------------------------------

    def plan_mesh(self, n_devices):
        """Shrink ``mesh_axes`` to fit `n_devices`, data axis first.

        The shrink axis takes ``n // prod(other axes)``; if even the
        other axes alone no longer fit, the largest of them is halved
        until they do. Axis names and order are preserved, so saved
        PartitionSpecs stay meaningful across the resize."""
        if not self.mesh_axes:
            return None
        axes = dict(self.mesh_axes)
        shrink = self.shrink_axis
        n = max(1, int(n_devices))

        def _others():
            return int(np.prod([s for k, s in axes.items()
                                if k != shrink] or [1]))

        while _others() > n:
            candidates = [k for k in axes if k != shrink and axes[k] > 1]
            if not candidates:
                break
            k = max(candidates, key=lambda k: axes[k])
            axes[k] = max(1, axes[k] // 2)
        axes[shrink] = max(1, n // _others())
        return axes

    # -- the loop ---------------------------------------------------------

    def run(self, train_fn):
        """Run ``train_fn(attempt)`` until it finishes, is preempted, or
        the restart budget is spent. Returns the last attempt's result.
        """
        from ..parallel import collective as _collective
        from .preempt import PreemptionHandler
        result = None
        attempt_no = 0
        prev_axes = None
        with PreemptionHandler() as handler:
            while True:
                devices = self.available_devices()
                axes = self.plan_mesh(len(devices))
                mesh = (_collective.make_mesh(axes, devices=devices)
                        if axes else None)
                if prev_axes is not None and axes != prev_axes:
                    record("elastic_resize", previous=prev_axes,
                           planned=axes, devices=len(devices))
                prev_axes = axes
                attempt = Attempt(attempt_no, mesh, axes, devices,
                                  self.checkpoint,
                                  auto_resume=attempt_no > 0 or (
                                      self.checkpoint is not None and
                                      self.checkpoint.latest_step()
                                      is not None))
                self.attempts.append(attempt)
                record("elastic_attempt", attempt=attempt_no, axes=axes,
                       devices=len(devices),
                       auto_resume=attempt.auto_resume)
                try:
                    result = train_fn(attempt)
                except HostLossError as e:
                    self.lost_devices += max(1, int(
                        getattr(e, "lost", 1)))
                    if attempt_no >= self.max_restarts:
                        record("elastic_exhausted", attempt=attempt_no,
                               lost_devices=self.lost_devices)
                        raise
                    record("elastic_restart", attempt=attempt_no,
                           lost=getattr(e, "lost", 1),
                           lost_total=self.lost_devices, error=str(e))
                    attempt_no += 1
                    continue
                if handler.triggered:
                    record("elastic_preempt_stop", attempt=attempt_no)
                else:
                    record("elastic_done", attempt=attempt_no)
                return result
