"""paddle_tpu.resilience.deadline — wall-time budgets for online work.

Training tolerates slow steps; serving does not. A request that has
already blown its SLA is pure waste: executing it burns a batch slot
that a live request could have used, and the caller gave up long ago.
:class:`Deadline` is the one representation of "this work is worthless
after T" shared by the serving tier (``paddle_tpu.serving.admission``
drops expired requests at dequeue, before they occupy a batch slot)
and available to any queue consumer with the same problem.

Monotonic by default (``time.monotonic`` — wall-clock jumps must not
expire a request), with an injectable clock so tests replay exact
expiry schedules without sleeping.
"""
from __future__ import annotations

import time


class Deadline:
    """An absolute expiry instant, built from a relative budget.

    ``Deadline(0.5)`` expires half a second from construction. A zero
    or negative budget is already expired — useful for "drop if any
    queueing at all" requests.
    """

    __slots__ = ("expires_at", "_clock")

    def __init__(self, timeout_s, clock=time.monotonic):
        self._clock = clock
        self.expires_at = clock() + float(timeout_s)

    @classmethod
    def after_ms(cls, ms, clock=time.monotonic):
        return cls(float(ms) / 1e3, clock=clock)

    def remaining(self, now=None):
        """Seconds until expiry (negative once past it)."""
        if now is None:
            now = self._clock()
        return self.expires_at - now

    def expired(self, now=None):
        return self.remaining(now) <= 0.0

    def __repr__(self):
        r = self.remaining()
        state = f"{r * 1e3:.1f}ms left" if r > 0 else "expired"
        return f"Deadline({state})"
