"""paddle_tpu.resilience.guard — step-level NaN/Inf protection.

One NaN step silently poisons every parameter; this guard makes the
blast radius one *skipped* step instead. Three policies:

* ``skip``                  — drop the poisoned update, keep training
* ``rollback_to_last_ckpt`` — restore model+optimizer from the guard's
                              CheckpointManager, keep training
* ``raise``                 — fail fast with :class:`NonFiniteError`

Two enforcement layers share the AMP scaler's finite-check machinery
(``amp.tree_all_finite`` — ONE fused all-finite reduction, jit-safe):

1. **Optimizer level** (`guarded_apply`, called by ``Optimizer.step``
   while a guard is installed): snapshot params+slots, apply the
   update, then ``jnp.where``-select the old state back when any grad
   is non-finite. Pure device selects — it composes with
   ``jit.to_static`` exactly like ``amp.GradScaler.step`` does, so the
   fused hapi train step gets skip protection *inside* the compiled
   computation.
2. **Host level** (`check_host`, called by ``hapi.Model.fit`` /
   ``Executor.run`` on the materialized loss): counts
   ``resilience.nan_skip``, and applies the rollback / raise policies
   that need host control flow.

Install a guard for the optimizer layer with ``with guard:`` (or
``guard.install()``); ``fit(nan_guard=...)`` does this for you.
"""
from __future__ import annotations

import math
import threading

import numpy as np

from ._common import record

POLICIES = ("skip", "rollback_to_last_ckpt", "raise")


class NonFiniteError(FloatingPointError):
    """Raised by policy="raise" (and by skip/rollback guards when
    ``max_consecutive`` poisoned steps arrive back to back)."""


_state = threading.local()

# process-wide non-finite trips across every guard instance (guards are
# per-loop and thread-local; /healthz needs the whole process's count)
_trips_lock = threading.Lock()
_total_trips = 0


def total_trips():
    """Total non-finite events any NaNGuard in this process has seen
    (skip + rollback + raise), for the /healthz endpoint."""
    return _total_trips


def active():
    """The innermost installed guard, or None (checked by
    Optimizer.step; one attribute read when no guard is in play)."""
    stack = getattr(_state, "stack", None)
    return stack[-1] if stack else None


class NaNGuard:
    """See module docstring. ``checkpoint_manager`` is required for the
    rollback policy; ``max_consecutive`` (default 10) bounds how many
    poisoned steps in a row skip/rollback will absorb before raising —
    a permanently-NaN model should fail, not spin forever."""

    def __init__(self, policy="skip", checkpoint_manager=None,
                 max_consecutive=10):
        if policy not in POLICIES:
            raise ValueError(
                f"NaNGuard policy must be one of {POLICIES}, got {policy!r}")
        self.policy = policy
        self.checkpoint_manager = checkpoint_manager
        self.max_consecutive = max_consecutive
        self.consecutive = 0
        self.total_nonfinite = 0

    # -- install / uninstall (optimizer-level enforcement) -----------------

    def install(self):
        stack = getattr(_state, "stack", None)
        if stack is None:
            stack = _state.stack = []
        stack.append(self)
        return self

    def uninstall(self):
        stack = getattr(_state, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        elif stack and self in stack:
            stack.remove(self)

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- host-level enforcement ---------------------------------------------

    def check_host(self, value, step=None, model=None, optimizer=None,
                   program=None, where="train"):
        """Check a materialized loss/flag on the host. Returns True when
        finite; on non-finite applies the policy and returns False (the
        caller drops the step from its averages)."""
        if value is None:
            return True
        v = float(np.asarray(value).ravel()[0]) if not isinstance(
            value, float) else value
        if math.isfinite(v):
            self.consecutive = 0
            return True
        self._on_nonfinite(step=step, value=v, model=model,
                           optimizer=optimizer, program=program, where=where)
        return False

    def note_device_flag(self, finite, step=None, model=None,
                         optimizer=None, program=None, where="optimizer"):
        """Host-sync a device finite flag when possible and apply the
        policy. Under a jit trace the flag is a tracer — the select
        machinery already handled skip semantics, so this quietly
        returns None there; rollback/raise then happen at the host
        level via check_host on the materialized loss."""
        try:
            ok = bool(finite)
        except Exception:  # tracer: inside jit.to_static / Executor jit
            return None
        if ok:
            self.consecutive = 0
            return True
        self._on_nonfinite(step=step, model=model, optimizer=optimizer,
                           program=program, where=where)
        return False

    def _on_nonfinite(self, step=None, value=None, model=None,
                      optimizer=None, program=None, where="train"):
        self.consecutive += 1
        self.total_nonfinite += 1
        global _total_trips
        with _trips_lock:
            _total_trips += 1
        if self.policy == "raise":
            record("nan_raise", step=step, where=where)
            raise NonFiniteError(
                f"non-finite loss/gradients at step {step} ({where}); "
                "policy='raise'")
        if self.max_consecutive and self.consecutive > self.max_consecutive:
            raise NonFiniteError(
                f"{self.consecutive} consecutive non-finite steps at step "
                f"{step} ({where}) — model state is unrecoverable under "
                f"policy={self.policy!r}")
        if self.policy == "rollback_to_last_ckpt":
            if self.checkpoint_manager is None:
                raise ValueError(
                    "NaNGuard(policy='rollback_to_last_ckpt') needs a "
                    "checkpoint_manager")
            # evidence BEFORE the restore overwrites live state: which
            # spans/counters led into the poisoned step
            from .. import monitor as _monitor
            if _monitor.enabled():
                _monitor.trace.flight_record(
                    "nan_rollback", step=step,
                    extra={"where": where, "value": value})
            state = self.checkpoint_manager.restore(
                model=model, optimizer=optimizer, program=program)
            record("rollback", step=step,
                   restored_step=None if state is None else state.get("step"),
                   where=where)
            return
        # skip: the poisoned update was already dropped (optimizer-level
        # where-select, or never applied); just account for it
        record("nan_skip", step=step, where=where, value=value)


def guarded_apply(optimizer, params_grads, apply_fn):
    """jit-safe skip enforcement for one optimizer update (the AMP
    scaler's snapshot / apply / where-select scheme): run ``apply_fn()``
    then select every param and slot back to its pre-step value when any
    grad is non-finite. Returns the device finite flag."""
    import jax.numpy as jnp
    from ..amp import tree_all_finite

    finite = tree_all_finite([g for _, g in params_grads if g is not None])
    # slots must exist BEFORE the snapshot or a rolled-back step would
    # leave lazily-created accumulators holding the poisoned update
    optimizer._ensure_all_slots()
    params = [p for p, g in params_grads if g is not None]
    old_params = [p.data for p in params]
    old_slots = [(t, t.data)
                 for slots in optimizer._accumulators.values()
                 for t in slots.values()]
    apply_fn()
    for p, old in zip(params, old_params):
        p.data = jnp.where(finite, p.data, old)
    for t, old in old_slots:
        t.data = jnp.where(finite, t.data, old)
    return finite
