"""Shared event plumbing for paddle_tpu.resilience.

Every recovery action funnels through :func:`record` so one grep over
the monitor output answers "what did the runtime survive": a counter
``resilience.<event>`` plus a JSONL record ``{"kind": "resilience",
"event": <event>, ...}`` when the monitor sink is active.
"""
from __future__ import annotations

from .. import monitor as _monitor


def record(event, **fields):
    """Count + emit one resilience event (no-op while the monitor is
    disabled, matching the framework's zero-cost-when-off discipline)."""
    if _monitor.enabled():
        _monitor.counter(f"resilience.{event}").inc()
        _monitor.emit(kind="resilience", event=event, **fields)
