"""paddle_tpu.resilience.faults — deterministic fault injection.

The chaos layer the resilience tests and `scripts/chaos_smoke.sh` drive:
a process-global registry of fault specs, each firing either at exact
step numbers or with a seeded per-spec probability, with a bounded fire
count. Injection sites sit inside the code paths the faults simulate
(DataLoader/prefetch producers for ``loader``, the hapi/executor train
loops for ``nan_grad`` / ``slow_step`` / ``preempt``) so recovery is
exercised end-to-end, not unit-mocked.

Well-known kinds (the registry itself is string-keyed and open):

* ``loader``          — raise inside the batch producer (default: a
                        :class:`~paddle_tpu.resilience.retry.TransientError`)
* ``nan_grad``        — poison one training batch so loss/grads go NaN
* ``slow_step``       — sleep ``delay`` seconds inside a step
                        (watchdog food)
* ``preempt``         — simulated SIGTERM: save-and-stop mid-run
* ``shard_corrupt``   — garble bytes of one committed checkpoint shard
                        (fires inside ``io.sharded.save_state``; the
                        quorum rule must then reject that step)
* ``shard_slow_write``— sleep ``delay`` inside a shard write (retry /
                        ``ckpt.shard_seconds`` food)
* ``host_loss``       — raise :class:`HostLossError` in the train loop:
                        ``lost`` devices vanish and the elastic
                        supervisor must resize the mesh and resume
* ``replica_error``   — raise inside one serving replica's batch
                        execution (default: a transient error; the
                        breaker must absorb it)
* ``replica_hang``    — sleep ``delay`` (default 30s) inside a serving
                        replica's batch execution — the supervisor must
                        trip the breaker and fail the batch over
* ``replica_slow``    — sleep ``delay`` inside a replica's batch
                        execution (straggler; hedged-request food)
* ``preempt_replica`` — simulated scheduler preemption notice for one
                        serving replica: the supervisor must flip it to
                        ``draining`` and migrate its queued + in-flight
                        work (zero lost requests; fires in the
                        supervisor tick, replica-targeted)
* ``publish_corrupt`` — garble one shard of a published checkpoint just
                        before a live weight hot-swap reads it; the
                        quorum ``validate()`` must refuse the swap and
                        quarantine the publish

Serving faults target replicas, not steps: pass ``replica=1`` (or a
list) to :func:`inject` and the spec only fires for that replica id —
this is how the chaos gate hangs exactly one of four replicas.

Every injection site is behind :func:`enabled` — an empty registry
costs one truthiness check.

Specs can also come from the environment for no-code chaos runs:
``PADDLE_TPU_FAULTS='[{"kind":"loader","step":3}]'`` (a JSON list of
:func:`inject` keyword dicts) is loaded on first import of
``paddle_tpu.resilience``.
"""
from __future__ import annotations

import json
import os
import random
import threading
import time

from ._common import record
from .retry import TransientError


class HostLossError(RuntimeError):
    """A (simulated) host dropped out of the slice mid-run. ``lost`` is
    how many devices went with it — the elastic supervisor shrinks the
    mesh by that many and resumes from the last complete checkpoint."""

    def __init__(self, msg="host lost", lost=1):
        super().__init__(msg)
        self.lost = int(lost)


class FaultSpec:
    """One injected fault: where it fires (exact steps and/or seeded
    probability), how often (``times`` budget), and what it does
    (raise ``exc``, sleep ``delay`` for slow faults, or drop ``lost``
    devices for ``host_loss``)."""

    def __init__(self, kind, step=None, probability=1.0, times=1,
                 exc=None, delay=0.0, seed=0, lost=1, replica=None,
                 site=None):
        self.kind = kind
        self.lost = int(lost)
        # a disaggregated topology reuses replica ids across pools:
        # "replica 0" alone is ambiguous, so a spec may also require
        # the injection site's pool label ("prefill", ...)
        self.site = site
        if step is None:
            self.steps = None
        elif isinstance(step, (list, tuple, set, frozenset)):
            self.steps = frozenset(int(s) for s in step)
        else:
            self.steps = frozenset((int(step),))
        if replica is None:
            self.replicas = None
        elif isinstance(replica, (list, tuple, set, frozenset)):
            self.replicas = frozenset(int(r) for r in replica)
        else:
            self.replicas = frozenset((int(replica),))
        self.probability = float(probability)
        self.times = None if times is None else int(times)
        self.exc = exc
        self.delay = float(delay)
        self._rng = random.Random(seed)
        self.fired = 0

    def should_fire(self, step, replica=None, site=None):
        if self.times is not None and self.fired >= self.times:
            return False
        if self.steps is not None and (
                step is None or int(step) not in self.steps):
            return False
        if self.replicas is not None and (
                replica is None or int(replica) not in self.replicas):
            return False
        if self.site is not None and site != self.site:
            return False
        if self.probability >= 1.0:
            return True
        return self._rng.random() < self.probability

    def make_exc(self):
        e = self.exc
        if e is None:
            if self.kind == "host_loss":
                return HostLossError(
                    f"injected host_loss fault (fire #{self.fired}, "
                    f"lost={self.lost})", lost=self.lost)
            return TransientError(
                f"injected {self.kind} fault (fire #{self.fired})")
        if isinstance(e, type):
            return e(f"injected {self.kind} fault")
        if callable(e):
            return e()
        return e


_lock = threading.Lock()
_specs = {}   # kind -> [FaultSpec]


def inject(kind, step=None, probability=1.0, times=1, exc=None,
           delay=0.0, seed=0, lost=1, replica=None, site=None):
    """Register a fault. Returns the spec (its ``.fired`` counter is the
    test-side evidence the injection actually happened)."""
    spec = FaultSpec(kind, step=step, probability=probability, times=times,
                     exc=exc, delay=delay, seed=seed, lost=lost,
                     replica=replica, site=site)
    with _lock:
        _specs.setdefault(kind, []).append(spec)
    return spec


def clear(kind=None):
    """Drop all specs (or just one kind). Tests call this in teardown so
    faults never leak across cases."""
    with _lock:
        if kind is None:
            _specs.clear()
        else:
            _specs.pop(kind, None)


def enabled():
    """True when any fault is registered — the one check hot paths pay."""
    return bool(_specs)


def fire(kind, step=None, replica=None, site=None):
    """Consume one firing of `kind` at `step` if a spec matches.
    Returns the spec (or None). Emits ``resilience.fault_injected``."""
    specs = _specs.get(kind)
    if not specs:
        return None
    with _lock:
        for spec in specs:
            if spec.should_fire(step, replica=replica, site=site):
                spec.fired += 1
                record("fault_injected", fault=kind, step=step,
                       replica=replica, fire=spec.fired)
                return spec
    return None


def maybe_raise(kind, step=None, replica=None):
    """Raise the spec's exception if a `kind` fault fires at `step`."""
    spec = fire(kind, step, replica=replica)
    if spec is not None:
        raise spec.make_exc()


def maybe_sleep(kind, step=None, replica=None):
    """Sleep the spec's ``delay`` if a `kind` fault fires at `step`
    (slow-step simulation). Returns True when it slept."""
    spec = fire(kind, step, replica=replica)
    if spec is not None and spec.delay > 0:
        time.sleep(spec.delay)
        return True
    return spec is not None


def maybe_serving_fault(replica, step=None, site=None):
    """The one injection site inside a serving replica's batch
    execution: ``replica_error`` raises, ``replica_hang`` sleeps a long
    default (30s — long enough that only supervision, never patience,
    resolves it), ``replica_slow`` sleeps its ``delay`` (straggler).
    ``site`` names the pool in a disaggregated topology (``"prefill"``)
    so a spec can target one pool's replica 0 and not the other's."""
    spec = fire("replica_error", step, replica=replica, site=site)
    if spec is not None:
        raise spec.make_exc()
    spec = fire("replica_hang", step, replica=replica, site=site)
    if spec is not None:
        time.sleep(spec.delay if spec.delay > 0 else 30.0)
    spec = fire("replica_slow", step, replica=replica, site=site)
    if spec is not None and spec.delay > 0:
        time.sleep(spec.delay)


def garble_file(path, nbytes=16, seed=0):
    """Deterministically corrupt `nbytes` of `path` in place (XOR with a
    seeded byte stream at a seeded offset) — the shard-corruption
    primitive behind the ``shard_corrupt`` fault and the chaos gates.
    The file's size never changes, so only checksums can catch it."""
    size = os.path.getsize(path)
    if size == 0:
        with open(path, "wb") as f:
            f.write(b"\xff")
        return
    rng = random.Random(seed)
    n = min(int(nbytes), size)
    off = rng.randrange(0, size - n + 1)
    with open(path, "r+b") as f:
        f.seek(off)
        chunk = f.read(n)
        garbled = bytes(b ^ (rng.randrange(1, 256)) for b in chunk)
        f.seek(off)
        f.write(garbled)
        f.flush()
        os.fsync(f.fileno())


def load_env(var="PADDLE_TPU_FAULTS"):
    """Load a JSON list of inject() kwarg dicts from the environment
    (no-code chaos runs). Returns the created specs."""
    raw = os.environ.get(var, "")
    if not raw:
        return []
    out = []
    for entry in json.loads(raw):
        kw = dict(entry)
        out.append(inject(kw.pop("kind"), **kw))
    return out
