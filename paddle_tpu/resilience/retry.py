"""paddle_tpu.resilience.retry — bounded exponential backoff with jitter.

The policy layer every recoverable I/O path shares: the device-prefetch
producer (io/prefetch.py), DataLoader batch assembly, and checkpoint
reads/writes (io.CheckpointManager). A *transient* failure (I/O hiccup,
injected fault, anything raising :class:`TransientError` or carrying a
truthy ``.transient`` attribute) is retried up to a max-attempt budget
with exponentially growing, jittered sleeps; a *terminal* failure (a
bug: TypeError, ValueError, pickling garbage) propagates immediately —
retrying it would only hide the stack trace.

Jitter is deterministic per policy (seeded ``random.Random``) so tests
and the chaos CI gate replay identical schedules.

Every retry increments ``resilience.retry`` and emits a
``{"kind": "resilience", "event": "retry"}`` JSONL record when the
monitor is enabled.
"""
from __future__ import annotations

import functools
import random
import time

from ._common import record


class TransientError(Exception):
    """A failure the caller expects to succeed on retry (used as the
    marker class by fault injection and as a base for user loaders)."""

    transient = True


class RetryExhausted(RuntimeError):
    """Raised (from the last transient error) when the attempt budget is
    spent. ``__cause__`` carries the final underlying exception."""


# Conservative default classification: network/filesystem flakiness is
# retryable, programming errors are not.
_TRANSIENT_TYPES = (TransientError, OSError, ConnectionError, TimeoutError)
_NEVER_RETRY = (KeyboardInterrupt, SystemExit, MemoryError)


def is_transient(exc, extra_types=()):
    """Transient/terminal classification used by every retry site."""
    if isinstance(exc, _NEVER_RETRY):
        return False
    if getattr(exc, "transient", False):
        return True
    return isinstance(exc, _TRANSIENT_TYPES + tuple(extra_types))


class RetryPolicy:
    """max-attempt budget + exponential backoff schedule.

    delay(attempt) = min(max_delay, base_delay * multiplier**attempt),
    scaled by a uniform jitter in [1-jitter, 1+jitter] drawn from a
    per-policy seeded RNG (deterministic replay).
    """

    def __init__(self, max_attempts=3, base_delay=0.05, max_delay=2.0,
                 multiplier=2.0, jitter=0.5, retryable=(), seed=0):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.retryable = tuple(retryable)
        self._rng = random.Random(seed)

    def is_transient(self, exc):
        return is_transient(exc, self.retryable)

    def delay(self, attempt):
        d = min(self.max_delay, self.base_delay * self.multiplier ** attempt)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, d)


#: Cheap defaults for in-process producers (tests and loaders want fast
#: recovery, not seconds-long sleeps).
DEFAULT_POLICY_ARGS = dict(max_attempts=3, base_delay=0.02, max_delay=1.0)


def default_policy():
    return RetryPolicy(**DEFAULT_POLICY_ARGS)


def retry_call(fn, *args, policy=None, label="", on_retry=None, **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying transient failures under
    ``policy``. Terminal failures propagate untouched; a spent budget
    raises :class:`RetryExhausted` from the last transient error."""
    policy = policy or default_policy()
    last = None
    for attempt in range(policy.max_attempts):
        try:
            return fn(*args, **kwargs)
        except BaseException as e:
            if not policy.is_transient(e):
                raise
            last = e
            if attempt + 1 >= policy.max_attempts:
                break
            record("retry", where=label or getattr(fn, "__name__", "call"),
                   attempt=attempt + 1, error=repr(e))
            if on_retry is not None:
                on_retry(e, attempt)
            d = policy.delay(attempt)
            # a server-side shed hint (serving.ShedError.retry_after_s)
            # floors the backoff: the endpoint told us when it expects
            # capacity, sleeping less just feeds the ladder
            ra = getattr(e, "retry_after_s", None)
            if ra is not None:
                d = max(d, float(ra))
            from .. import monitor as _monitor
            with _monitor.trace.span(
                    "resilience.backoff",
                    where=label or getattr(fn, "__name__", "call"),
                    attempt=attempt + 1):
                time.sleep(d)
    raise RetryExhausted(
        f"{label or getattr(fn, '__name__', 'call')}: "
        f"{policy.max_attempts} attempts exhausted (last: {last!r})"
    ) from last


def retrying(policy=None, label=""):
    """Decorator form of :func:`retry_call`."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return retry_call(fn, *args, policy=policy,
                              label=label or fn.__name__, **kwargs)
        return wrapped
    return deco
