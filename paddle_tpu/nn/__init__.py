"""paddle_tpu.nn — Layers, containers, losses, functional.

TPU-native rebuild of the reference's paddle.fluid.dygraph layer API
(reference: python/paddle/fluid/dygraph/{layers,nn,container}.py).
"""
from .layer import Layer, functional_call, state_pytree, bind_state
from .container import Sequential, LayerList, ParameterList
from .layers import (
    Linear, Conv2D, Conv2DTranspose, Conv3D, MaxPool2D, AvgPool2D,
    AdaptiveAvgPool2D, Pool2D, BatchNorm, BatchNorm1D, BatchNorm2D,
    BatchNorm3D, SyncBatchNorm, LayerNorm, GroupNorm, InstanceNorm2D,
    SpectralNorm, Embedding, Dropout, PRelu, BilinearTensorProduct, GRUUnit,
    Flatten, Upsample, Pad2D,
    ReLU, ReLU6, LeakyReLU, GELU, Sigmoid, Tanh, Softmax, LogSoftmax,
    Softplus, Hardswish, Hardsigmoid, Swish, Silu, Mish, ELU, SELU, Hardtanh,
)
from .loss import (CrossEntropyLoss, MSELoss, L1Loss, SmoothL1Loss, BCELoss,
                   BCEWithLogitsLoss, KLDivLoss, NLLLoss, MarginRankingLoss)
from .decode import (Decoder, BeamSearchDecoder, dynamic_decode,
                     gather_tree, DecodeHelper, TrainingHelper,
                     GreedyEmbeddingHelper, SamplingEmbeddingHelper,
                     BasicDecoder, basic_decode)
from .rnn import (RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, LSTM,
                  GRU, SimpleRNN, StaticRNN)
from . import functional
from . import functional as F
from .layers import NCE
from .layers import Conv3DTranspose, InstanceNorm, TreeConv

# paddle.nn 2.0-alpha alias tail (reference: python/paddle/nn/__init__.py)
from ..clip import (ClipGradByGlobalNorm as GradientClipByGlobalNorm,  # noqa
                    ClipGradByNorm as GradientClipByNorm,
                    ClipGradByValue as GradientClipByValue)
from ..fluid.layers_rnn import beam_search, beam_search_decode  # noqa: F401
from ..static import data  # noqa: F401
from ..ops import nn_ops as conv  # reference exports its conv module
from .layers import Upsample as UpSample  # noqa: F401 (2.0-alpha name)
from .layers import HSigmoid  # noqa: F401
from .moe import MoEFFN, moe_aux_loss  # noqa: F401
from ..fluid.dygraph import RowConv  # noqa: F401

# paddle.nn 1.x functional tails (reference: python/paddle/nn/
# {clip,control_flow}.py re-export the fluid twins at paddle.nn level)
from ..ops.math import clip  # noqa: F401,E402
from ..ops.control_flow import case, cond, while_loop  # noqa: F401,E402
