"""paddle_tpu.nn.container — Sequential / LayerList / ParameterList.

TPU-native rebuild of reference python/paddle/fluid/dygraph/container.py.
"""
from __future__ import annotations

from .layer import Layer
from ..tensor import Parameter


class Sequential(Layer):
    """reference: container.py:Sequential — accepts layers or (name, layer)
    tuples."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], (list, tuple)):
            layers = layers[0]
        for i, item in enumerate(layers):
            if isinstance(item, (list, tuple)):
                name, layer = item
            else:
                name, layer = str(i), item
            self.add_sublayer(name, layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    """reference: container.py:LayerList."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, layer in enumerate(sublayers):
                self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __setitem__(self, idx, layer):
        self.add_sublayer(str(idx), layer)

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def extend(self, layers):
        for layer in layers:
            self.append(layer)
        return self

    def insert(self, index, layer):
        items = list(self._sub_layers.values())
        items.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(items):
            self.add_sublayer(str(i), l)


class ParameterList(Layer):
    """reference: container.py:ParameterList."""

    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return list(self._parameters.values())[idx]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self
