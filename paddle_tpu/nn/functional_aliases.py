"""1.x paddle.nn.functional spellings whose canonical names differ
(reference: python/paddle/nn/functional/activation.py aliases)."""
from ..ops.nn_ops import log_sigmoid as logsigmoid  # noqa: F401
from ..ops.nn_ops import tanhshrink as tanh_shrink  # noqa: F401
from ..ops.manip import diag_embed  # noqa: F401
