"""paddle_tpu.nn.functional — functional NN API surface.

Mirrors paddle.nn.functional by re-exporting the op library
(reference: python/paddle/fluid/layers/nn.py + loss.py functional surface).
"""
from ..ops.nn_ops import *  # noqa: F401,F403
from ..ops.loss import *  # noqa: F401,F403
from ..ops.manip import one_hot, pad  # noqa: F401

# --- paddle.nn.functional 1.x surface (reference: python/paddle/nn/
# functional/*.py re-exported the fluid.layers twins under paddle.nn.
# functional; same here, so `from paddle.nn import functional as F`
# code ports verbatim) ---------------------------------------------------
from ..fluid.layers import (  # noqa: F401,E402
    # activation.py
    brelu, hsigmoid, soft_relu,
    # common.py / conv.py
    pad2d, conv3d_transpose, assign,
    # extension.py
    add_position_encoding, multiclass_nms, row_conv, target_assign,
    temporal_shift,
    # learning_rate.py
    cosine_decay, exponential_decay, inverse_time_decay,
    natural_exp_decay, noam_decay, piecewise_decay, polynomial_decay,
    linear_lr_warmup,
    # lod.py
    hash,
    # loss.py
    center_loss, dice_loss, iou_similarity, kldiv_loss, npair_loss,
    sigmoid_focal_loss, smooth_l1, ssd_loss,
    teacher_student_sigmoid_loss,
    # norm.py / pooling.py
    l2_normalize, lrn, pool3d, adaptive_pool2d, adaptive_pool3d,
    # vision.py
    affine_channel, affine_grid, anchor_generator, bipartite_match,
    box_clip, box_coder, box_decoder_and_assign, collect_fpn_proposals,
    deformable_roi_pooling, density_prior_box, detection_output,
    distribute_fpn_proposals, generate_mask_labels,
    generate_proposal_labels, generate_proposals, grid_sampler,
    image_resize, prior_box, prroi_pool, psroi_pool, resize_bilinear,
    resize_nearest, resize_trilinear, roi_align, roi_pool,
    space_to_depth, yolo_box, yolov3_loss,
)
from .functional_aliases import (  # noqa: F401,E402
    logsigmoid, tanh_shrink, diag_embed)
