"""paddle_tpu.nn.functional — functional NN API surface.

Mirrors paddle.nn.functional by re-exporting the op library
(reference: python/paddle/fluid/layers/nn.py + loss.py functional surface).
"""
from ..ops.nn_ops import *  # noqa: F401,F403
from ..ops.loss import *  # noqa: F401,F403
from ..ops.manip import one_hot, pad  # noqa: F401
