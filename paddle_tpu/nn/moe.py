"""Mixture-of-Experts FFN — expert parallelism for the user Layer stack.

TPU-native design (no reference counterpart in Paddle Fluid 1.7 — the ep
axis is part of this framework's 5-axis scale-out story, matching the
manual-collective MoE in parallel/megatron.py): the GShard/Mesh-TF dense
dispatch formulation. Expert weights are STACKED on a leading [E] axis; a
top-1 gate builds a dispatch one-hot [T, E, C] (T tokens, C capacity per
expert) and the whole layer is four einsums. Under `fleet.distributed_model`
the expert axis is sharded over the mesh's `ep` axis (see
fleet.megatron_param_spec), and GSPMD lowers the dispatch/combine einsums
into the token all-to-all the megatron trainer writes by hand — static
shapes, MXU-friendly, no data-dependent control flow.

Load balancing: the standard GShard auxiliary loss E·Σ_e(mean_gate_e ·
frac_tokens_e) is computed every forward and stashed on the layer as
``self.aux_loss`` (a live Tensor on the autograd tape); training code adds
``moe_aux_loss(model)`` to its objective to activate it.
"""
from __future__ import annotations

import numpy as np

from .layer import Layer
from ..tensor import Tensor
from ..dispatch import apply
from .. import initializer as I

__all__ = ["MoEFFN", "moe_aux_loss"]


class MoEFFN(Layer):
    """Drop-in replacement for the Linear–act–Linear FFN block.

    d_model -> [num_experts] x (d_model -> d_ffn -> d_model), top-1 gated,
    capacity = ceil(T / E * capacity_factor) tokens per expert (overflow
    tokens pass through the residual untouched, GShard semantics).
    """

    def __init__(self, d_model, d_ffn, num_experts, capacity_factor=1.25,
                 activation="gelu"):
        super().__init__()
        self.num_experts = num_experts
        self.capacity_factor = float(capacity_factor)
        self.activation = activation
        self.gate_w = self.create_parameter(
            (d_model, num_experts),
            default_initializer=I.Normal(0.0, 0.02))
        k = 1.0 / np.sqrt(d_model)
        # expert-stacked: leading axis is the EXPERT axis (sharded over ep
        # by fleet.megatron_param_spec's "experts_" rule)
        self.experts_w1 = self.create_parameter(
            (num_experts, d_model, d_ffn),
            default_initializer=I.Uniform(-k, k))
        self.experts_b1 = self.create_parameter(
            (num_experts, d_ffn), is_bias=True)
        kf = 1.0 / np.sqrt(d_ffn)
        self.experts_w2 = self.create_parameter(
            (num_experts, d_ffn, d_model),
            default_initializer=I.Uniform(-kf, kf))
        self.experts_b2 = self.create_parameter(
            (num_experts, d_model), is_bias=True)
        self.aux_loss = None

    def forward(self, x):
        import jax
        import jax.numpy as jnp
        E = self.num_experts
        act_name = self.activation

        def impl(x, gate_w, w1, b1, w2, b2):
            lead = x.shape[:-1]
            d = x.shape[-1]
            tokens = x.reshape(-1, d)
            T = tokens.shape[0]
            C = max(1, int(np.ceil(T / E * self.capacity_factor)))

            logits = tokens @ gate_w                     # [T, E]
            probs = jax.nn.softmax(logits, axis=-1)
            expert = jnp.argmax(probs, axis=-1)          # [T]
            gate = jnp.max(probs, axis=-1)               # [T]

            onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)
            # position of each token within its expert's capacity bucket
            pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot   # [T, E]
            keep = (pos < C) & (onehot > 0)
            pos_c = jax.nn.one_hot(jnp.sum(pos, axis=-1).astype(jnp.int32),
                                   C, dtype=jnp.float32)         # [T, C]
            dispatch = keep.astype(jnp.float32)[:, :, None] * \
                pos_c[:, None, :]                                # [T, E, C]

            expert_in = jnp.einsum("tec,td->ecd", dispatch,
                                   tokens.astype(jnp.float32))
            h = jnp.einsum("ecd,edf->ecf", expert_in, w1) + b1[:, None, :]
            h = getattr(jax.nn, act_name)(h)
            out = jnp.einsum("ecf,efd->ecd", h, w2) + b2[:, None, :]
            combine = dispatch * gate[:, None, None]             # [T, E, C]
            y = jnp.einsum("tec,ecd->td", combine, out)
            y = y.astype(x.dtype).reshape(*lead, d)

            # GShard load-balance aux: E * sum_e mean_t(prob_e)*frac_e
            frac = jnp.mean(onehot, axis=0)
            mean_prob = jnp.mean(probs, axis=0)
            aux = E * jnp.sum(frac * mean_prob)
            return y, aux

        y, aux = apply(impl, (x, self.gate_w, self.experts_w1,
                              self.experts_b1, self.experts_w2,
                              self.experts_b2), name="moe_ffn", n_out=2)
        self.aux_loss = aux
        return y


def moe_aux_loss(model, weight=0.01):
    """Sum the aux_loss of every MoE-bearing layer in `model`, scaled by
    `weight` (call AFTER the forward pass; returns 0.0 if the model has no
    MoE). Any sublayer exposing a non-None ``aux_loss`` Tensor counts —
    MoEFFN itself, and aggregators like parallel.pipeline.PipelineStack
    which total the aux of MoE blocks hidden inside their scan."""
    total = None
    for layer in model.sublayers(include_self=True):
        aux = getattr(layer, "aux_loss", None)
        if aux is not None:
            total = aux if total is None else total + aux
    if total is None:
        return 0.0
    return total * weight
