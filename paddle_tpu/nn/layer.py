"""paddle_tpu.nn.layer — the Layer base class.

TPU-native rebuild of the reference's dygraph Layer
(reference: python/paddle/fluid/dygraph/layers.py Layer +
paddle/fluid/imperative/layer.h). A Layer owns Parameters and sub-Layers,
has train/eval mode, state_dict/set_state_dict, named traversal, and hooks.

TPU twist: Layers also support *functional extraction* — ``functional_call``
temporarily swaps every Parameter's payload with values from a pytree so the
same user-defined Layer runs under jit/pjit tracing (this is what
jit.to_static and the static Executor build on; the reference instead
re-declares the model as a static Program).
"""
from __future__ import annotations

import contextlib
import threading
from collections import OrderedDict

import numpy as np
import jax

from ..tensor import Tensor, Parameter, convert_dtype, get_default_dtype
from .. import initializer as I
from ..monitor import profile as _profile

# Remat hook (memory_plan): None until the first remat feature is used
# (mirrors tensor._arena_hook's cost discipline), then consulted once
# per __call__. The thread-local suspends it inside jit.recompute's
# checkpointed body — the subtree is already under a checkpoint, and
# the suspension must also hold during the backward replay.
_remat_hook = None
_remat_tls = threading.local()


@contextlib.contextmanager
def _remat_suspended():
    prev = getattr(_remat_tls, "skip", False)
    _remat_tls.skip = True
    try:
        yield
    finally:
        _remat_tls.skip = prev


# Global structure version: bumped whenever any Layer's parameter /
# sublayer / buffer maps change. jit.to_static caches its name→holder
# state map against this (plus optimizer-slot counts), turning the
# per-call named_parameters() walk — ~17ms/call on ResNet-50 — into a
# dict reuse. Coarse by design: layer construction happens at setup
# time, so the version stops moving once the train loop starts.
_STRUCT_VERSION = 0


def _bump_struct_version():
    global _STRUCT_VERSION
    _STRUCT_VERSION += 1


def struct_version():
    return _STRUCT_VERSION


class Layer:
    """Base network building block (reference: dygraph/layers.py:Layer)."""

    def __init__(self, name_scope=None, dtype=None, remat=None):
        self._parameters = OrderedDict()
        self._sub_layers = OrderedDict()
        self._buffers = OrderedDict()
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        self.training = True
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self._name_scope = name_scope or self.__class__.__name__
        # memory_plan: this layer's own remat policy ("dots"/"full"/
        # rules; "none" pins the layer out of an ambient policy).
        # Assignable after construction too — it's a plain attribute.
        self._remat = remat
        if remat is not None:
            from ..memory_plan import install_layer_hook
            install_layer_hook()

    # -- attribute plumbing -------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        if isinstance(value, Parameter) and params is not None:
            params[name] = value
            self.__dict__.pop(name, None)
            _bump_struct_version()
        elif isinstance(value, Layer) and layers is not None:
            layers[name] = value
            self.__dict__.pop(name, None)
            _bump_struct_version()
        else:
            if params is not None and name in params:
                del params[name]
                _bump_struct_version()
            if layers is not None and name in layers:
                del layers[name]
                _bump_struct_version()
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        params = self.__dict__.get("_parameters")
        if params is not None and name in params:
            return params[name]
        layers = self.__dict__.get("_sub_layers")
        if layers is not None and name in layers:
            return layers[name]
        buffers = self.__dict__.get("_buffers")
        if buffers is not None and name in buffers:
            return buffers[name]
        raise AttributeError(
            f"{type(self).__name__} has no attribute {name!r}")

    def __delattr__(self, name):
        if name in self._parameters:
            del self._parameters[name]
            _bump_struct_version()
        elif name in self._sub_layers:
            del self._sub_layers[name]
            _bump_struct_version()
        elif name in self._buffers:
            del self._buffers[name]
            _bump_struct_version()
        else:
            object.__delattr__(self, name)

    # -- parameter management ----------------------------------------------
    def create_parameter(self, shape, dtype=None, attr=None,
                         default_initializer=None, is_bias=False,
                         name=None):
        """reference: Layer.create_parameter + LayerHelper semantics."""
        from ..param_attr import ParamAttr
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) or self._dtype
        attr_init = attr.initializer if isinstance(attr, ParamAttr) else None
        if not isinstance(attr_init, I.Initializer) and not isinstance(
                attr_init, (int, float)):
            attr_init = None
        init = I._resolve(
            attr_init,
            I._resolve(default_initializer,
                       I.Constant(0.0) if is_bias else I.XavierUniform()))
        data = init(shape, dtype)
        p = Parameter(data, name=(attr.name if isinstance(attr, ParamAttr)
                                  and attr.name else name))
        if isinstance(attr, ParamAttr):
            if not attr.trainable:
                p.trainable = False
                p.stop_gradient = True
            p.regularizer = attr.regularizer
            p.optimize_attr["learning_rate"] = attr.learning_rate
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        _bump_struct_version()
        return parameter

    def register_buffer(self, name, tensor, persistable=True):
        """Non-trainable state (running stats etc.)."""
        if isinstance(tensor, Tensor):
            tensor.persistable = persistable
        self._buffers[name] = tensor
        _bump_struct_version()
        return tensor

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        _bump_struct_version()
        return sublayer

    # -- traversal ----------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else f"{prefix}.{name}"), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for item in layer.named_parameters(sub_prefix, True):
                    if id(item[1]) not in seen:
                        seen.add(id(item[1]))
                        yield item

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(sub_prefix, True)

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for layer in self._sub_layers.values():
            out.extend(layer.sublayers(include_self=True))
        return out

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(sub_prefix, include_self=True)

    def children(self):
        return list(self._sub_layers.values())

    def named_children(self):
        return list(self._sub_layers.items())

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # -- train / eval -------------------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # -- state dict ---------------------------------------------------------
    def state_dict(self, include_sublayers=True, keep_vars=True):
        """reference: Layer.state_dict — params + persistable buffers."""
        from .. import tensor as _ptensor
        if _ptensor._arena_hook is not None:
            # flat-arena training leaves param views stale between
            # steps; a state_dict read is a sync boundary
            from ..optimizer.arena import sync_all
            sync_all()
        out = OrderedDict()
        for name, p in self.named_parameters(
                include_sublayers=include_sublayers):
            out[name] = p if keep_vars else p.numpy()
        for name, b in self.named_buffers(
                include_sublayers=include_sublayers):
            if isinstance(b, Tensor) and b.persistable:
                out[name] = b if keep_vars else b.numpy()
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        """reference: Layer.set_state_dict/set_dict."""
        own = self.state_dict()
        missing, unexpected = [], []
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            if isinstance(value, Tensor):
                value = value.data
            target.set_value(value)
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- dtype --------------------------------------------------------------
    def to(self, dtype=None):
        if dtype is not None:
            dt = convert_dtype(dtype)
            import jax.numpy as jnp
            for p in self.parameters():
                if jnp.issubdtype(p.data.dtype, jnp.floating):
                    p.data = p.data.astype(dt)
            for b in self.buffers():
                if isinstance(b, Tensor) and jnp.issubdtype(
                        b.data.dtype, jnp.floating):
                    b.data = b.data.astype(dt)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks, hook)
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks, hook)
        return handle

    # -- call ---------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        # cost discipline: each disarmed hook (the default) costs one
        # module-flag check — no scope name, no context manager
        if _remat_hook is not None and \
                not getattr(_remat_tls, "skip", False):
            out = _remat_hook(self, args, kwargs)
            if out is not NotImplemented:
                return out
        if _profile.scopes_on:
            with jax.named_scope(_profile.layer_scope(self)):
                return self._run_forward(args, kwargs)
        return self._run_forward(args, kwargs)

    def _run_forward(self, args, kwargs):
        from .. import tensor as _ptensor
        if _ptensor._arena_hook is not None and \
                jax.core.trace_state_clean():
            # an EAGER forward is a read boundary for flat-arena params:
            # compiled steps leave leaf views stale on purpose (the flat
            # buffer is the carried state), so settle them before eager
            # math reads the payloads. Inside a trace the views are
            # bound by jit.py and must not be touched.
            from ..optimizer.arena import flush
            flush()
        for hook in self._forward_pre_hooks.values():
            res = hook(self, args)
            if res is not None:
                args = res if isinstance(res, tuple) else (res,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, args, out)
            if res is not None:
                out = res
        return out

    # -- grad management ----------------------------------------------------
    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    clear_grad = clear_gradients

    def __repr__(self):
        lines = [self.__class__.__name__ + "("]
        for name, layer in self._sub_layers.items():
            sub = repr(layer).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub}")
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else (
            self.__class__.__name__ + "()")


class _HookHandle:
    _next_id = [0]

    def __init__(self, hooks, hook):
        _HookHandle._next_id[0] += 1
        self.hook_id = _HookHandle._next_id[0]
        self._hooks = hooks
        hooks[self.hook_id] = hook

    def remove(self):
        self._hooks.pop(self.hook_id, None)


# ---------------------------------------------------------------------------
# functional extraction: run a Layer with parameter payloads swapped from a
# pytree. This is the bridge from the stateful Layer world to jax's
# functional transforms (jit / grad / pjit / shard_map).

def state_pytree(layer: Layer):
    """Collect {name: jax.Array} for all params + persistable buffers."""
    tree = {}
    for name, p in layer.named_parameters():
        tree[name] = p.data
    for name, b in layer.named_buffers():
        if isinstance(b, Tensor):
            tree["buffer:" + name] = b.data
    return tree


@contextlib.contextmanager
def bind_state(layer: Layer, tree):
    """Temporarily swap layer state payloads with ``tree`` values."""
    saved = {}
    params = dict(layer.named_parameters())
    buffers = {"buffer:" + n: b for n, b in layer.named_buffers()
               if isinstance(b, Tensor)}
    holders = {**params, **buffers}
    try:
        for name, holder in holders.items():
            if name in tree:
                saved[name] = holder.data
                holder.data = tree[name]
        yield holders
    finally:
        for name, value in saved.items():
            holders[name].data = value


def functional_call(layer: Layer, tree, *args, **kwargs):
    """Run layer.forward with parameters taken from ``tree`` (pytree of
    arrays keyed like state_pytree). Returns (output, new_tree) where
    new_tree reflects buffer mutations (e.g. batch-norm running stats)."""
    with bind_state(layer, tree) as holders:
        out = layer(*args, **kwargs)
        new_tree = {name: holder.data for name, holder in holders.items()}
    return out, new_tree
