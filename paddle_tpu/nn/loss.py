"""paddle_tpu.nn.loss — loss Layer classes.

Layer wrappers over paddle_tpu.ops.loss (reference: paddle.nn loss layers /
fluid.dygraph loss usage patterns).
"""
from __future__ import annotations

from .layer import Layer
from ..ops import loss as L


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True):
        super().__init__()
        self._a = dict(ignore_index=ignore_index, reduction=reduction,
                       soft_label=soft_label, axis=axis,
                       use_softmax=use_softmax)

    def forward(self, input, label):
        return L.cross_entropy(input, label, **self._a)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return L.mse_loss(input, label, self._reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return L.l1_loss(input, label, self._reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0):
        super().__init__()
        self._a = dict(reduction=reduction, delta=delta)

    def forward(self, input, label):
        return L.smooth_l1_loss(input, label, **self._a)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return L.binary_cross_entropy(input, label,
                                      reduction=self._reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, logit, label):
        return L.binary_cross_entropy_with_logits(
            logit, label, reduction=self._reduction)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return L.kl_div(input, label, self._reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean"):
        super().__init__()
        self._a = dict(ignore_index=ignore_index, reduction=reduction)

    def forward(self, input, label):
        return L.nll_loss(input, label, **self._a)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean"):
        super().__init__()
        self._a = dict(margin=margin, reduction=reduction)

    def forward(self, input, other, label):
        return L.margin_ranking_loss(input, other, label, **self._a)
