"""paddle_tpu.nn.decode — RNN decoding: dynamic_decode, beam search,
decode helpers.

TPU-native rebuild of the reference decoding stack
(reference: python/paddle/fluid/layers/rnn.py — Decoder:576,
BeamSearchDecoder:687, dynamic_decode:1147, DecodeHelper:1382,
TrainingHelper:1444, GreedyEmbeddingHelper:1597, BasicDecoder:1829; and
the C++ gather_tree_op).

Redesign: the reference builds a while-op sub-block with LoDTensorArrays
and grows outputs dynamically; XLA needs static shapes, so here
``dynamic_decode`` drives a ``lax.while_loop`` whose carry holds
fixed-size ``[max_step, ...]`` output buffers written by index — early
termination still happens (the loop predicate stops when every beam is
finished) but buffers never change shape. Beam state rides as
``[batch, beam, ...]`` arrays, beam reordering is one gather per step,
and the final backtrace (``gather_tree``) is a reverse ``lax.scan``.
Inference-only: runs under ``no_grad`` (tracer-safe through the Layer
dispatch), so the whole decode jits into one XLA while loop.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from .. import autograd

NEG_INF = -1e9


def _unwrap(tree):
    return jax.tree_util.tree_map(
        lambda x: x.data if isinstance(x, Tensor) else jnp.asarray(x), tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def _wrap(tree):
    return jax.tree_util.tree_map(lambda a: Tensor(a), tree)


class Decoder:
    """Decoder protocol (reference: layers/rnn.py:576). Subclasses
    implement initialize/step/finalize over raw jnp arrays."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states


class BeamSearchDecoder(Decoder):
    """reference: layers/rnn.py:687. Wraps a cell; each step scores
    ``beam_size`` continuations per batch row and keeps the top-k.

    cell: RNNCell-like Layer ((input, states) -> (output, new_states));
    embedding_fn maps ``[B, K]`` ids to inputs; output_fn maps cell output
    to vocab logits."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] (repeat each row beam_size times) —
        for tensors used inside cell.call (e.g. attention memory)."""
        a = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        tiled = jnp.repeat(a, beam_size, axis=0)
        return Tensor(tiled) if isinstance(x, Tensor) else tiled

    def _merge(self, x):
        # [B, K, ...] -> [B*K, ...]
        return x.reshape((-1,) + x.shape[2:])

    def _split(self, x, b):
        return x.reshape((b, self.beam_size) + x.shape[1:])

    def initialize(self, initial_cell_states):
        states = _unwrap(initial_cell_states)
        b = jax.tree_util.tree_leaves(states)[0].shape[0]
        k = self.beam_size
        states = jax.tree_util.tree_map(
            lambda s: jnp.repeat(s, k, axis=0), states)  # [B*K, ...]
        tokens = jnp.full((b, k), self.start_token, jnp.int32)
        # only beam 0 is live initially so the k start beams don't
        # duplicate the same hypothesis
        cum_lp = jnp.tile(jnp.array([0.0] + [NEG_INF] * (k - 1),
                                    jnp.float32)[None, :], (b, 1))
        finished = jnp.zeros((b, k), bool)
        return (tokens, cum_lp, finished), states

    def step(self, time, beam_state, cell_states):
        tokens, cum_lp, finished = beam_state
        b, k = tokens.shape

        with autograd.no_grad():
            emb = self.embedding_fn(Tensor(tokens)) if self.embedding_fn \
                else Tensor(tokens)
            emb = _unwrap(emb)
            emb = self._merge(emb)
            out, new_states = self.cell(Tensor(emb), _wrap(cell_states))
            out = _unwrap(out)
            if self.output_fn is not None:
                out = _unwrap(self.output_fn(Tensor(out)))
        new_states = _unwrap(new_states)

        v = out.shape[-1]
        lp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
        lp = self._split(lp, b)                                 # [B, K, V]
        # finished beams may only extend with end_token, at no cost
        end_only = jnp.full((v,), NEG_INF, jnp.float32).at[
            self.end_token].set(0.0)
        lp = jnp.where(finished[:, :, None], end_only[None, None, :], lp)

        total = cum_lp[:, :, None] + lp                         # [B, K, V]
        flat = total.reshape(b, k * v)
        new_lp, idx = jax.lax.top_k(flat, k)                    # [B, K]
        parent = (idx // v).astype(jnp.int32)
        token = (idx % v).astype(jnp.int32)

        prev_finished = jnp.take_along_axis(finished, parent, axis=1)
        new_finished = prev_finished | (token == self.end_token)

        # reorder cell states by parent beam
        gidx = (jnp.arange(b)[:, None] * k + parent).reshape(-1)
        new_states = jax.tree_util.tree_map(lambda s: s[gidx], new_states)

        return ((token, new_lp, new_finished), new_states,
                dict(token=token, parent=parent,
                     prev_finished=prev_finished))

    def finalize(self, step_tokens, step_parents, lengths, final_lp):
        """Backtrace the beam ancestry (the reference's gather_tree op)."""
        ids = gather_tree(step_tokens, step_parents, self.end_token)
        return ids, final_lp


def gather_tree(ids, parents, end_token=0):
    """reference: C++ gather_tree_op (exposed as fluid.layers.gather_tree).
    ids/parents: [T, B, K] -> full sequences [T, B, K] following each
    final beam's ancestry back through time (reverse lax.scan)."""
    ids = jnp.asarray(ids)
    parents = jnp.asarray(parents)
    t, b, k = ids.shape
    beam = jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32)[None, :], (b, k))

    def back(cursor, inp):
        ids_t, parents_t = inp
        tok = jnp.take_along_axis(ids_t, cursor, axis=1)
        prev = jnp.take_along_axis(parents_t, cursor, axis=1)
        return prev, tok

    _, toks = jax.lax.scan(back, beam, (ids, parents), reverse=True)
    return toks  # [T, B, K]


def dynamic_decode(decoder, inits=None, max_step_num=64,
                   output_time_major=False, return_length=False, **kwargs):
    """reference: layers/rnn.py:1147 dynamic_decode. Runs the decoder until
    every beam emits end_token or ``max_step_num`` is hit.

    Returns (ids, final_scores) — ids ``[B, T, K]`` (or time-major), plus
    lengths when ``return_length``."""
    (tokens0, cum0, fin0), states0 = decoder.initialize(inits)
    b, k = tokens0.shape
    t_max = int(max_step_num)

    tok_buf = jnp.zeros((t_max, b, k), jnp.int32)
    par_buf = jnp.zeros((t_max, b, k), jnp.int32)

    def cond(carry):
        t, beam_state, states, tok_buf, par_buf, lengths = carry
        _, _, finished = beam_state
        return jnp.logical_and(t < t_max, ~jnp.all(finished))

    def body(carry):
        t, beam_state, states, tok_buf, par_buf, lengths = carry
        new_beam, new_states, rec = decoder.step(t, beam_state, states)
        tok_buf = tok_buf.at[t].set(rec["token"])
        par_buf = par_buf.at[t].set(rec["parent"])
        lengths = lengths + (~rec["prev_finished"]).astype(jnp.int32)
        return (t + 1, new_beam, new_states, tok_buf, par_buf, lengths)

    carry0 = (jnp.asarray(0), (tokens0, cum0, fin0), states0, tok_buf,
              par_buf, jnp.zeros((b, k), jnp.int32))
    t, (tokens, cum_lp, finished), states, tok_buf, par_buf, lengths = \
        jax.lax.while_loop(cond, body, carry0)

    # pad the un-run tail so gather_tree passes finished beams through
    steps = jnp.arange(t_max)[:, None, None]
    tok_buf = jnp.where(steps < t, tok_buf, decoder.end_token
                        if hasattr(decoder, "end_token") else 0)
    par_buf = jnp.where(steps < t,
                        par_buf,
                        jnp.broadcast_to(
                            jnp.arange(k, dtype=jnp.int32)[None, None, :],
                            (t_max, b, k)))

    if hasattr(decoder, "finalize") and isinstance(decoder,
                                                   BeamSearchDecoder):
        ids, scores = decoder.finalize(tok_buf, par_buf, lengths, cum_lp)
    else:
        ids, scores = decoder.finalize(tok_buf, par_buf, lengths)

    if not output_time_major:
        ids = jnp.moveaxis(ids, 0, 1)  # [B, T, K]
    out = (Tensor(ids), Tensor(scores))
    if return_length:
        out = out + (Tensor(lengths),)
    return out


# ---------------------------------------------------------------------------
# helper-based decoding (reference: DecodeHelper:1382 family)

class DecodeHelper:
    """Protocol: initialize() -> (inputs, finished); sample(); next_inputs()
    (reference: layers/rnn.py:1382)."""


class TrainingHelper(DecodeHelper):
    """Teacher forcing: feed the gold inputs step by step
    (reference: layers/rnn.py:1444)."""

    def __init__(self, inputs, sequence_length, time_major=False):
        x = inputs.data if isinstance(inputs, Tensor) else jnp.asarray(
            inputs)
        self.inputs = x if time_major else jnp.moveaxis(x, 0, 1)  # [T, B,.]
        self.sequence_length = jnp.asarray(
            sequence_length.data if isinstance(sequence_length, Tensor)
            else sequence_length, jnp.int32)

    def initialize(self):
        finished = self.sequence_length <= 0
        return self.inputs[0], finished

    def sample(self, time, outputs):
        return jnp.argmax(outputs, axis=-1).astype(jnp.int32)

    def next_inputs(self, time, outputs, sample_ids):
        t = time + 1
        finished = t >= self.sequence_length
        nxt = self.inputs[jnp.minimum(t, self.inputs.shape[0] - 1)]
        return finished, nxt


class GreedyEmbeddingHelper(DecodeHelper):
    """Feed back argmax ids through an embedding
    (reference: layers/rnn.py:1597)."""

    def __init__(self, embedding_fn, start_tokens, end_token):
        self.embedding_fn = embedding_fn
        self.start_tokens = jnp.asarray(
            start_tokens.data if isinstance(start_tokens, Tensor)
            else start_tokens, jnp.int32)
        self.end_token = int(end_token)

    def initialize(self):
        finished = jnp.zeros_like(self.start_tokens, bool)
        with autograd.no_grad():
            emb = _unwrap(self.embedding_fn(Tensor(self.start_tokens)))
        return emb, finished

    def sample(self, time, outputs):
        return jnp.argmax(outputs, axis=-1).astype(jnp.int32)

    def next_inputs(self, time, outputs, sample_ids):
        finished = sample_ids == self.end_token
        with autograd.no_grad():
            emb = _unwrap(self.embedding_fn(Tensor(sample_ids)))
        return finished, emb


class SamplingEmbeddingHelper(GreedyEmbeddingHelper):
    """Sample ids from the output distribution
    (reference: layers/rnn.py sampling helper)."""

    def __init__(self, embedding_fn, start_tokens, end_token, seed=None):
        super().__init__(embedding_fn, start_tokens, end_token)
        self._seed = seed

    def sample(self, time, outputs):
        from .. import random as prandom
        key = jax.random.PRNGKey(self._seed + 0) if self._seed is not None \
            else prandom.next_key()
        key = jax.random.fold_in(key, time)
        return jax.random.categorical(key, outputs).astype(jnp.int32)


class BasicDecoder(Decoder):
    """Cell + helper decoding (reference: layers/rnn.py:1829). Emits
    (cell_output, sample_id) per step; driven by basic_decode below."""

    def __init__(self, cell, helper, output_fn=None):
        self.cell = cell
        self.helper = helper
        self.output_fn = output_fn

    def initialize(self, inits):
        inputs, finished = self.helper.initialize()
        return inputs, _unwrap(inits), finished

    def step(self, time, inputs, states):
        with autograd.no_grad():
            out, new_states = self.cell(Tensor(inputs), _wrap(states))
            out = _unwrap(out)
            if self.output_fn is not None:
                out = _unwrap(self.output_fn(Tensor(out)))
        sample_ids = self.helper.sample(time, out)
        finished, next_inputs = self.helper.next_inputs(time, out,
                                                        sample_ids)
        return (out, sample_ids), next_inputs, _unwrap(new_states), finished


class KVCacheCell:
    """Adapts a serving-style decode function (the
    ``paddle_tpu.serving.generate`` model contract: ``decode_fn(state,
    tokens[B], kv {leaf: [B, max_len, *tail]}, lengths[B]) -> (logits,
    entry)``) into an RNN cell for :class:`BasicDecoder` +
    :class:`GreedyEmbeddingHelper` (with an identity ``embedding_fn`` —
    the decode_fn embeds its own token ids). Cell states are
    ``(kv, lengths)``: the decode step attends over the cache, writes
    the incoming token's entry at position ``lengths``, and advances.

    This is the single-sequence twin of the continuous-batching engine:
    same decode math, same cache discipline, driven by the classic
    ``lax.while_loop`` decoding stack — the bit-parity bridge the
    serving tests assert across (same weights in, same tokens out)."""

    def __init__(self, decode_fn, state, max_len):
        self.decode_fn = decode_fn
        self.state = state
        self.max_len = int(max_len)

    def init_states(self, kv_chunks, lengths):
        """Seed the cell from a prefill: pad each ``[B, L, *tail]`` KV
        chunk out to ``[B, max_len, *tail]`` (zeros past the live
        length are never attended — the decode mask sees ``lengths``)
        and pair with those lengths."""
        lengths = jnp.asarray(_unwrap(lengths), jnp.int32)
        kv = {}
        for name, chunk in _unwrap(kv_chunks).items():
            pad = [(0, 0)] * chunk.ndim
            pad[1] = (0, self.max_len - chunk.shape[1])
            kv[name] = jnp.pad(chunk, pad)
        return kv, lengths

    def __call__(self, inputs, states):
        tokens = jnp.asarray(_unwrap(inputs), jnp.int32).reshape(-1)
        kv, lengths = _unwrap(states)
        logits, entry = self.decode_fn(self.state, tokens, kv, lengths)
        rows = jnp.arange(tokens.shape[0])
        pos = jnp.minimum(lengths, self.max_len - 1)
        kv = {name: buf.at[rows, pos].set(entry[name])
              for name, buf in kv.items()}
        return Tensor(logits), _wrap((kv, lengths + 1))


def basic_decode(decoder, inits, max_step_num=64, output_time_major=False):
    """Drive a BasicDecoder (helper-based). Returns (outputs, sample_ids)
    as [B, T, ...] / [B, T] plus lengths."""
    inputs0, states0, fin0 = decoder.initialize(inits)
    t_max = int(max_step_num)

    # probe one step for output shapes; the probe result is discarded, so
    # restore the global PRNG key afterwards (a sampling helper would
    # otherwise consume a key and shift the random stream)
    from .. import random as prandom
    _key_holder = prandom.global_key_tensor()
    _saved_key = _key_holder.data
    (out0, sid0), _, _, _ = decoder.step(jnp.asarray(0), inputs0, states0)
    _key_holder.data = _saved_key
    b = sid0.shape[0]
    out_buf = jnp.zeros((t_max,) + out0.shape, out0.dtype)
    sid_buf = jnp.zeros((t_max,) + sid0.shape, jnp.int32)

    def cond(carry):
        t, inputs, states, finished, out_buf, sid_buf, lengths = carry
        return jnp.logical_and(t < t_max, ~jnp.all(finished))

    def body(carry):
        t, inputs, states, finished, out_buf, sid_buf, lengths = carry
        (out, sids), nxt, new_states, new_fin = decoder.step(t, inputs,
                                                             states)
        out_buf = out_buf.at[t].set(out)
        sid_buf = sid_buf.at[t].set(sids)
        lengths = lengths + (~finished).astype(jnp.int32)
        return (t + 1, nxt, new_states, finished | new_fin, out_buf,
                sid_buf, lengths)

    carry0 = (jnp.asarray(0), inputs0, states0, fin0, out_buf, sid_buf,
              jnp.zeros((b,), jnp.int32))
    t, _, _, _, out_buf, sid_buf, lengths = jax.lax.while_loop(cond, body,
                                                               carry0)
    if not output_time_major:
        out_buf = jnp.moveaxis(out_buf, 0, 1)
        sid_buf = jnp.moveaxis(sid_buf, 0, 1)
    return Tensor(out_buf), Tensor(sid_buf), Tensor(lengths)
