"""paddle_tpu.nn.layers — the dygraph Layer zoo.

TPU-native rebuild of the reference's dygraph layers
(reference: python/paddle/fluid/dygraph/nn.py — Linear, Conv2D, Conv3D,
Conv2DTranspose, Pool2D, BatchNorm, LayerNorm, GroupNorm, InstanceNorm,
SpectralNorm, Embedding, Dropout, PRelu, NCE, BilinearTensorProduct,
GRUUnit). Parameters are created eagerly at construction (no LayerHelper /
startup Program); forward calls the pure functional ops, so every Layer
works identically in eager, to_static, and static-Program modes.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..tensor import Tensor, Parameter, convert_dtype
from .. import initializer as I
from .. import ops
from ..ops import nn_ops as F
from .layer import Layer


class Linear(Layer):
    """reference: dygraph/nn.py:Linear (weight [in, out] + bias)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierUniform())
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def __repr__(self):
        return (f"Linear(in={self.in_features}, out={self.out_features}, "
                f"bias={self.bias is not None})")


class Conv2D(Layer):
    """reference: dygraph/nn.py:Conv2D. Weight layout OIHW (API parity);
    XLA re-lays out for the MXU internally."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        ks = F._pair(kernel_size, 2)
        self._attrs = dict(stride=stride, padding=padding, dilation=dilation,
                           groups=groups, data_format=data_format)
        fan_in = in_channels * ks[0] * ks[1] // groups
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, ks[0], ks[1]),
            attr=weight_attr,
            default_initializer=I.Normal(0.0, np.sqrt(2.0 / fan_in)))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, **self._attrs)


class Conv2DTranspose(Layer):
    """reference: dygraph/nn.py:Conv2DTranspose (weight IOHW)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        ks = F._pair(kernel_size, 2)
        self._attrs = dict(stride=stride, padding=padding,
                           output_padding=output_padding, dilation=dilation,
                           groups=groups, data_format=data_format)
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups, ks[0], ks[1]),
            attr=weight_attr, default_initializer=I.XavierUniform())
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.conv2d_transpose(x, self.weight, self.bias, **self._attrs)


class Conv3D(Layer):
    """reference: dygraph/nn.py:Conv3D."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__()
        ks = F._pair(kernel_size, 3)
        self._attrs = dict(stride=stride, padding=padding, dilation=dilation,
                           groups=groups, data_format=data_format)
        fan_in = in_channels * int(np.prod(ks)) // groups
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups) + ks, attr=weight_attr,
            default_initializer=I.Normal(0.0, np.sqrt(2.0 / fan_in)))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, **self._attrs)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 data_format="NCHW"):
        super().__init__()
        self._a = dict(kernel_size=kernel_size, stride=stride,
                       padding=padding, ceil_mode=ceil_mode,
                       data_format=data_format)

    def forward(self, x):
        return F.max_pool2d(x, **self._a)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 data_format="NCHW"):
        super().__init__()
        self._a = dict(kernel_size=kernel_size, stride=stride,
                       padding=padding, exclusive=exclusive,
                       data_format=data_format)

    def forward(self, x):
        return F.avg_pool2d(x, **self._a)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW"):
        super().__init__()
        self._a = dict(output_size=output_size, data_format=data_format)

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, **self._a)


class Pool2D(Layer):
    """fluid.dygraph.Pool2D parity shim."""

    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, data_format="NCHW"):
        super().__init__()
        self._a = dict(pool_size=pool_size, pool_type=pool_type,
                       pool_stride=pool_stride, pool_padding=pool_padding,
                       global_pooling=global_pooling, data_format=data_format)

    def forward(self, x):
        return F.pool2d(x, **self._a)


class BatchNorm(Layer):
    """reference: dygraph/nn.py:BatchNorm. Running stats live in buffers;
    forward in train mode returns fresh stats and we write them back
    (functionally visible to to_static as carried state)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 dtype=None):
        super().__init__(dtype=dtype)
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean",
                             Tensor(jnp.zeros((num_features,), self._dtype)))
        self.register_buffer("_variance",
                             Tensor(jnp.ones((num_features,), self._dtype)))

    def forward(self, x):
        out, new_mean, new_var = F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format)
        if self.training:
            self._mean.data = new_mean.data
            self._variance.data = new_var.data
        return out


class BatchNorm1D(BatchNorm):
    def __init__(self, num_features, **kw):
        kw.setdefault("data_format", "NCL")
        super().__init__(num_features, **kw)


class BatchNorm2D(BatchNorm):
    pass


class BatchNorm3D(BatchNorm):
    def __init__(self, num_features, **kw):
        kw.setdefault("data_format", "NCDHW")
        super().__init__(num_features, **kw)


class SyncBatchNorm(BatchNorm):
    """Cross-replica BN (reference: sync_batch_norm_op.cu): inside a
    shard_map region with the data-parallel axis bound, batch statistics
    are psum-averaged over that axis, so all replicas normalize with the
    same global-batch stats; running stats are updated from the synced
    values. Outside SPMD it degrades to ordinary BatchNorm."""

    def __init__(self, num_features, axis_name="dp", **kw):
        super().__init__(num_features, **kw)
        self._axis_name = axis_name

    def forward(self, x):
        from ..parallel import collective
        from ..dispatch import apply as _apply
        if not (self.training and collective.in_spmd_context(
                self._axis_name)):
            return super().forward(x)

        axis_name = self._axis_name
        momentum, eps = self._momentum, self._epsilon
        chan_first = self._data_format.startswith("NC")

        def impl(x, rm, rv, w, b):
            import jax.numpy as jnp
            from jax import lax
            axes = ((0,) + tuple(range(2, x.ndim))) if chan_first else \
                tuple(range(x.ndim - 1))
            shape = ((1, -1) + (1,) * (x.ndim - 2)) if chan_first else \
                ((1,) * (x.ndim - 1) + (-1,))
            # shift accumulators by the running mean: it is REPLICATED
            # state (identical on every dp shard, unlike a local data
            # sample) so the psum'd moments stay consistent, and once rm
            # tracks the data mean both accumulators are O(sigma^2) —
            # the same cancellation guard as _one_pass_moments
            c = lax.stop_gradient(rm.astype(jnp.float32))
            xs = x.astype(jnp.float32) - c.reshape(shape)
            s = lax.psum(jnp.sum(xs, axis=axes), axis_name)
            sq = lax.psum(jnp.sum(jnp.square(xs), axis=axes), axis_name)
            cnt = lax.psum(jnp.asarray(
                np.prod([x.shape[a] for a in axes]), jnp.float32), axis_name)
            m_s = s / cnt
            mean = m_s + c
            var = jnp.maximum(sq / cnt - jnp.square(m_s), 0.0)
            new_rm = (momentum * rm + (1 - momentum) * mean).astype(
                rm.dtype)
            new_rv = (momentum * rv + (1 - momentum) * var).astype(
                rv.dtype)
            out = F._fold_scale_shift(x, mean, var, w, b, eps, shape)
            return out, new_rm, new_rv

        # weight_attr/bias_attr=False make the params None — substitute
        # identity affine (mirrors F.batch_norm's guard)
        w = self.weight if self.weight is not None else \
            Tensor(jnp.ones((self._num_features,), jnp.float32))
        b = self.bias if self.bias is not None else \
            Tensor(jnp.zeros((self._num_features,), jnp.float32))
        out, new_mean, new_var = _apply(
            impl, (x, self._mean, self._variance, w, b),
            n_out=3, name="sync_batch_norm")
        self._mean.data = new_mean.data
        self._variance.data = new_var.data
        return out


class LayerNorm(Layer):
    """reference: dygraph/nn.py:LayerNorm (fused kernel → XLA/Pallas)."""

    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, use_pallas=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        # None = auto, resolved via pallas.enabled() when forward traces
        # (configure() before the first jitted step; traced steps keep
        # the choice they were compiled with)
        self._use_pallas = use_pallas if len(self._normalized_shape) == 1 \
            else False
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self._normalized_shape,
                                              attr=bias_attr, is_bias=True)

    def forward(self, x):
        use = self._use_pallas
        if use is None:
            from ..ops import pallas as P
            use = P.enabled("layer_norm")
        if use and self.weight is not None and self.bias is not None:
            from ..ops.pallas.layer_norm import layer_norm as pallas_ln
            return pallas_ln(x, self.weight, self.bias, self._epsilon)
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        self._a = dict(num_groups=num_groups, epsilon=epsilon,
                       data_format=data_format)
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, weight=self.weight, bias=self.bias, **self._a)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, self.weight, self.bias, self._epsilon)


class SpectralNorm(Layer):
    """reference: dygraph/nn.py:SpectralNorm — power-iteration normalized
    weight. Returns the normalized weight of shape `weight_shape`."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            (h,), default_initializer=I.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            (w,), default_initializer=I.Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ..dispatch import apply
        dim, iters, eps = self._dim, self._power_iters, self._eps

        def impl(w, u, v):
            mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return w / sigma, u, v

        out, u, v = apply(impl, (weight, self.weight_u, self.weight_v),
                          n_out=3, name="spectral_norm")
        self.weight_u.data = u.data
        self.weight_v.data = v.data
        return out


class Embedding(Layer):
    """reference: dygraph/nn.py:Embedding (lookup_table)."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0 / np.sqrt(embedding_dim)))

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train"):
        super().__init__()
        self._a = dict(p=p, axis=axis, mode=mode)

    def forward(self, x):
        return F.dropout(x, training=self.training, **self._a)


class PRelu(Layer):
    """reference: dygraph/nn.py:PRelu (modes: all/channel/element)."""

    def __init__(self, mode="all", channel=None, input_shape=None,
                 weight_attr=None):
        super().__init__()
        if mode == "all":
            shape = (1,)
        elif mode == "channel":
            shape = (channel,)
        else:
            shape = tuple(input_shape)
        self.weight = self.create_parameter(
            shape, attr=weight_attr, default_initializer=I.Constant(0.25))

    def forward(self, x):
        return F.prelu(x, self.weight)


class BilinearTensorProduct(Layer):
    """reference: dygraph/nn.py:BilinearTensorProduct
    out_k = x W_k y^T + b_k."""

    def __init__(self, input1_dim, input2_dim, output_dim, name=None,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.weight = self.create_parameter(
            (output_dim, input1_dim, input2_dim), attr=weight_attr)
        self.bias = self.create_parameter((output_dim,), attr=bias_attr,
                                          is_bias=True)

    def forward(self, x, y):
        from ..dispatch import apply
        def impl(x, y, w, b):
            return jnp.einsum("bi,oij,bj->bo", x, w, y) + b
        return apply(impl, (x, y, self.weight, self.bias),
                     name="bilinear_tensor_product")


class GRUUnit(Layer):
    """reference: dygraph/nn.py:GRUUnit — one GRU step (gate_weight holds
    update/reset gates, candidate_weight the candidate state)."""

    def __init__(self, size, weight_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid"):
        super().__init__()
        d = size // 3
        self._hidden = d
        self.gate_weight = self.create_parameter((d, d * 2), attr=weight_attr)
        self.candidate_weight = self.create_parameter((d, d),
                                                      attr=weight_attr)
        self.gate_bias = self.create_parameter((d * 2,), attr=bias_attr,
                                               is_bias=True)
        self.candidate_bias = self.create_parameter((d,), attr=bias_attr,
                                                    is_bias=True)
        self._act = getattr(jnp, activation) if hasattr(jnp, activation) \
            else jnp.tanh
        import jax
        self._gate_act = jax.nn.sigmoid if gate_activation == "sigmoid" \
            else jnp.tanh

    def forward(self, input, hidden):
        from ..dispatch import apply
        d = self._hidden
        act, gate_act = self._act, self._gate_act

        def impl(x, h, gw, cw, gb, cb):
            xu, xr, xc = x[:, :d], x[:, d:2 * d], x[:, 2 * d:]
            gates = gate_act(jnp.concatenate([xu, xr], 1) + h @ gw + gb)
            u, r = gates[:, :d], gates[:, d:]
            c = act(xc + (r * h) @ cw + cb)
            new_h = u * h + (1 - u) * c
            return new_h, r, c

        out = apply(impl, (input, hidden, self.gate_weight,
                           self.candidate_weight, self.gate_bias,
                           self.candidate_bias), n_out=3, name="gru_unit")
        return out  # (hidden, reset_hidden_pre, gate)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self._start, self._stop = start_axis, stop_axis

    def forward(self, x):
        return ops.flatten(x, self._start, self._stop)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, data_format="NCHW"):
        super().__init__()
        self._a = dict(size=size, scale_factor=scale_factor, mode=mode,
                       align_corners=align_corners, data_format=data_format)

    def forward(self, x):
        return F.interpolate(x, **self._a)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW"):
        super().__init__()
        self._padding = padding
        self._mode = mode
        self._value = value

    def forward(self, x):
        return ops.pad(x, self._padding, self._mode, self._value)


# -- simple activation layers ------------------------------------------------

def _act_layer(name, fn):
    class _Act(Layer):
        def __init__(self, *a, **kw):
            super().__init__()
            self._a, self._kw = a, kw

        def forward(self, x):
            return fn(x, *self._a, **self._kw)
    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _act_layer("ReLU", F.relu)
ReLU6 = _act_layer("ReLU6", F.relu6)
LeakyReLU = _act_layer("LeakyReLU", F.leaky_relu)
GELU = _act_layer("GELU", F.gelu)
Sigmoid = _act_layer("Sigmoid", F.sigmoid)
Tanh = _act_layer("Tanh", ops.tanh)
Softmax = _act_layer("Softmax", F.softmax)
LogSoftmax = _act_layer("LogSoftmax", F.log_softmax)
Softplus = _act_layer("Softplus", F.softplus)
Hardswish = _act_layer("Hardswish", F.hard_swish)
Hardsigmoid = _act_layer("Hardsigmoid", F.hard_sigmoid)
Swish = _act_layer("Swish", F.swish)
Silu = _act_layer("Silu", F.silu)
Mish = _act_layer("Mish", F.mish)
ELU = _act_layer("ELU", F.elu)
SELU = _act_layer("SELU", F.selu)
Hardtanh = _act_layer("Hardtanh", F.hardtanh)


class NCE(Layer):
    """reference: dygraph/nn.py:NCE — noise-contrastive estimation loss for
    large-vocab softmax. Samples `num_neg_samples` noise classes per batch
    (uniform or custom_dist) and returns the NCE logistic loss."""

    def __init__(self, num_total_classes, dim, sample_weight=None,
                 param_attr=None, bias_attr=None, num_neg_samples=10,
                 sampler="uniform", custom_dist=None, seed=0):
        super().__init__()
        self.num_total_classes = num_total_classes
        self.num_neg_samples = num_neg_samples
        self.weight = self.create_parameter((num_total_classes, dim),
                                            attr=param_attr)
        self.bias = self.create_parameter((num_total_classes,),
                                          attr=bias_attr, is_bias=True)
        self._custom_dist = (np.asarray(custom_dist, dtype="f4")
                             if custom_dist is not None else None)

    def forward(self, input, label):
        from ..dispatch import apply
        from .. import random as prandom
        import jax
        k = self.num_neg_samples
        n_cls = self.num_total_classes
        key = prandom.next_key_graph()  # per-run symbolic key in static
        custom = self._custom_dist

        def impl(x, label, w, b, key):
            if custom is not None:
                dist = jnp.asarray(custom)
                noise = jax.random.categorical(key, jnp.log(dist + 1e-12),
                                               shape=(k,))
                noise_p = dist[noise]
            else:
                dist = None
                noise = jax.random.randint(key, (k,), 0, n_cls)
                noise_p = jnp.full((k,), 1.0 / n_cls)
            lbl = label.reshape(-1)
            pos_logit = jnp.sum(x * w[lbl], axis=-1) + b[lbl]
            # NCE logistic loss: each logit is corrected by log(k·q(class))
            # under the SAME noise distribution q for positives and
            # negatives
            pos_q = dist[lbl] if dist is not None else 1.0 / n_cls
            pos_loss = jax.nn.softplus(-(pos_logit -
                                         jnp.log(k * pos_q + 1e-12)))
            neg_logit = x @ w[noise].T + b[noise]  # [B, k]
            neg_loss = jax.nn.softplus(neg_logit -
                                       jnp.log(k * noise_p + 1e-12))
            return (pos_loss + jnp.sum(neg_loss, axis=-1)).reshape(-1, 1)

        return apply(impl, (input, label, self.weight, self.bias, key),
                     name="nce")


InstanceNorm = InstanceNorm2D  # fluid dygraph name (reference dygraph/nn.py)


class Conv3DTranspose(Layer):
    """reference: dygraph/nn.py:Conv3DTranspose → the lhs-dilated conv
    formulation (fluid.layers_extra.conv3d_transpose math)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__()
        ks = F._pair(kernel_size, 3)
        self._cfg = dict(stride=F._pair(stride, 3),
                         padding=F._pair(padding, 3),
                         dilation=F._pair(dilation, 3), groups=groups)
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups) + ks, attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        from ..dispatch import apply as _apply
        import jax.numpy as jnp
        from jax import lax
        st, pd, dl = (self._cfg["stride"], self._cfg["padding"],
                      self._cfg["dilation"])
        groups = self._cfg["groups"]

        def impl(x, w, *maybe_b):
            kdims = w.shape[2:]
            pads = [(dl[i] * (kdims[i] - 1) - pd[i],
                     dl[i] * (kdims[i] - 1) - pd[i]) for i in range(3)]
            wf = jnp.flip(w, axis=(2, 3, 4))
            cin = wf.shape[0]
            if groups > 1:
                wf = wf.reshape(groups, cin // groups, -1, *kdims)
                wf = jnp.moveaxis(wf, 2, 1)
                rhs = wf.reshape(-1, cin // groups, *kdims)
            else:
                rhs = jnp.moveaxis(wf, 1, 0)
            out = lax.conv_general_dilated(
                x, rhs, window_strides=(1, 1, 1), padding=pads,
                lhs_dilation=st, rhs_dilation=dl,
                feature_group_count=groups,
                dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
            if maybe_b:
                out = out + maybe_b[0].reshape(1, -1, 1, 1, 1)
            return out

        args = (x, self.weight)
        if self.bias is not None:
            args = args + (self.bias,)
        return _apply(impl, args, name="conv3d_transpose")


class TreeConv(Layer):
    """reference: dygraph/nn.py:TreeConv (tree-based convolution,
    TBCNN). nodes_vector (B, N, D) + edge_set (B, E, 2) parent→child
    edges; each node convolves over its (parent, self, children)
    neighborhood via three weight matrices — the adjacency-matmul
    formulation (dense, MXU-friendly) of the reference's gather kernel."""

    def __init__(self, feature_size, output_size, num_filters=1,
                 max_depth=8, act="tanh", param_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self._act = act
        self.num_filters = num_filters
        self.output_size = output_size
        # three role matrices: self / parent-side / child-side
        self.weight = self.create_parameter(
            (3, feature_size, output_size * num_filters), attr=param_attr,
            default_initializer=I.XavierUniform())
        self.bias = None if bias_attr is False else self.create_parameter(
            (output_size * num_filters,), attr=bias_attr, is_bias=True)

    def forward(self, nodes_vector, edge_set):
        from ..dispatch import apply as _apply
        import jax.numpy as jnp
        act = self._act

        def impl(x, edges, w, *b):
            bsz, n, d = x.shape
            par = edges[..., 0].astype(jnp.int32)
            chi = edges[..., 1].astype(jnp.int32)
            adj = jnp.zeros((bsz, n, n), x.dtype)
            bidx = jnp.arange(bsz)[:, None]
            down = adj.at[bidx, par, chi].set(1.0)   # parent → child
            up = adj.at[bidx, chi, par].set(1.0)     # child → parent
            self_t = jnp.einsum("bnd,do->bno", x, w[0])
            child_t = jnp.einsum("bnm,bmd,do->bno", down, x, w[1])
            parent_t = jnp.einsum("bnm,bmd,do->bno", up, x, w[2])
            out = self_t + child_t + parent_t
            if b:
                out = out + b[0]
            return out.reshape(bsz, n, -1, self.num_filters) \
                if self.num_filters > 1 else out

        args = (nodes_vector, edge_set, self.weight)
        if self.bias is not None:
            args = args + (self.bias,)
        out = _apply(impl, args, name="tree_conv")
        if act:
            out = getattr(F, act)(out) if hasattr(F, act) else \
                getattr(ops, act)(out)
        return out


class HSigmoid(Layer):
    """Hierarchical sigmoid (reference: dygraph/nn.py HSigmoid over
    hierarchical_sigmoid_op.cc). Default complete-binary-tree code book:
    class c's path is the ancestor chain of leaf c in a complete binary
    tree over num_classes leaves — path nodes and left/right codes come
    straight from the bits of (c + num_classes), so no Huffman tables are
    materialized. loss[i] = -Σ_d log σ((1-2·code_d)·(x_i·w_{node_d}+b))."""

    def __init__(self, feature_size, num_classes, param_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 dtype="float32"):
        super().__init__(dtype=dtype)
        if num_classes < 2:
            raise ValueError("num_classes must be >= 2")
        if is_custom or is_sparse:
            raise NotImplementedError(
                "HSigmoid is_custom/is_sparse trees are not supported; "
                "the default complete-binary-tree code book covers the "
                "reference's non-custom path")
        self._C = int(num_classes)
        self._depth = max(1, int(np.ceil(np.log2(self._C))))
        # (num_classes - 1, feature): one row per INTERNAL tree node,
        # matching the reference's parameter shape
        self.weight = self.create_parameter(
            (self._C - 1, feature_size), attr=param_attr,
            default_initializer=I.Normal(0.0, 1.0 / np.sqrt(feature_size)))
        self.bias = self.create_parameter((self._C - 1,), attr=bias_attr,
                                          is_bias=True)

    def forward(self, input, label):
        from ..dispatch import apply
        import jax
        import jax.numpy as jnp
        C, D = self._C, self._depth

        has_bias = self.bias is not None

        def impl(x, w, *rest):
            b = rest[0] if has_bias else jnp.zeros((C - 1,), x.dtype)
            lab = rest[-1]
            lab = lab.reshape(-1).astype(jnp.int32)
            # heap index of leaf `c` in a complete binary tree is c + C;
            # its ancestors c>>1 ... are the internal nodes (1..C-1)
            node = lab + C
            loss = jnp.zeros((x.shape[0],), jnp.float32)
            for _ in range(D):
                code = node & 1          # 1 = right child
                parent = node >> 1
                # internal node k (1..C-1) lives in weight row k-1
                idx = jnp.clip(parent, 1, C - 1) - 1
                logit = jnp.einsum("bd,bd->b", x, w[idx]) + b[idx]
                sign = 1.0 - 2.0 * code.astype(jnp.float32)
                valid = parent >= 1
                term = jax.nn.softplus(-sign * logit)
                loss = loss + jnp.where(valid, term, 0.0)
                node = parent
            return loss[:, None]

        args = (input, self.weight) + \
            ((self.bias,) if has_bias else ()) + (label,)
        return apply(impl, args, name="hsigmoid")
