"""paddle_tpu.nn.rnn — recurrent layers.

TPU-native rebuild of the reference's RNN stack
(reference: python/paddle/fluid/layers/rnn.py LSTMCell/GRUCell/rnn +
dygraph/rnn.py; C++ recurrent ops). The reference unrolls dynamic RNNs with
a C++ while-op over LoD tensors; on TPU the driver is `lax.scan` — one
compiled loop, static shapes, weights resident in VMEM across steps.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..tensor import Tensor, as_tensor
from ..dispatch import apply
from .. import initializer as I
from .layer import Layer


class RNNCellBase(Layer):
    def get_initial_states(self, batch_size, dtype="float32"):
        import jax.numpy as jnp
        from ..tensor import convert_dtype
        shape = (batch_size, self.hidden_size)
        if self.state_components == 1:
            return Tensor(jnp.zeros(shape, convert_dtype(dtype)))
        return tuple(Tensor(jnp.zeros(shape, convert_dtype(dtype)))
                     for _ in range(self.state_components))


class SimpleRNNCell(RNNCellBase):
    """reference: layers/rnn.py simple rnn — h' = act(Wx + Uh + b)."""

    state_components = 1

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_attr=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter((input_size, hidden_size),
                                               attr=weight_ih_attr)
        self.weight_hh = self.create_parameter((hidden_size, hidden_size),
                                               attr=weight_hh_attr)
        self.bias = self.create_parameter((hidden_size,), attr=bias_attr,
                                          is_bias=True)
        self._act = activation

    def forward(self, x, h):
        if isinstance(h, (tuple, list)):
            h = h[0]
        act = self._act

        def impl(x, h, wi, wh, b):
            pre = x @ wi + h @ wh + b
            return jnp.tanh(pre) if act == "tanh" else jnp.maximum(pre, 0)

        out = apply(impl, (x, h, self.weight_ih, self.weight_hh, self.bias),
                    name="simple_rnn_cell")
        return out, out


class LSTMCell(RNNCellBase):
    """reference: layers/rnn.py:LSTMCell (i,f,c,o gate order)."""

    state_components = 2

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_attr=None,
                 forget_bias=1.0):
        super().__init__()
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter((input_size, 4 * hidden_size),
                                               attr=weight_ih_attr)
        self.weight_hh = self.create_parameter((hidden_size, 4 * hidden_size),
                                               attr=weight_hh_attr)
        self.bias = self.create_parameter((4 * hidden_size,), attr=bias_attr,
                                          is_bias=True)
        self._forget_bias = forget_bias

    def forward(self, x, state):
        h, c = state
        fb = self._forget_bias

        def impl(x, h, c, wi, wh, b):
            gates = x @ wi + h @ wh + b
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f + fb)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            new_c = f * c + i * g
            new_h = o * jnp.tanh(new_c)
            return new_h, new_c

        new_h, new_c = apply(impl, (x, h, c, self.weight_ih, self.weight_hh,
                                    self.bias), n_out=2, name="lstm_cell")
        return new_h, (new_h, new_c)


class GRUCell(RNNCellBase):
    """reference: layers/rnn.py:GRUCell."""

    state_components = 1

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_attr=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter((input_size, 3 * hidden_size),
                                               attr=weight_ih_attr)
        self.weight_hh = self.create_parameter((hidden_size, 3 * hidden_size),
                                               attr=weight_hh_attr)
        self.bias_ih = self.create_parameter((3 * hidden_size,),
                                             attr=bias_attr, is_bias=True)
        self.bias_hh = self.create_parameter((3 * hidden_size,),
                                             attr=bias_attr, is_bias=True)

    def forward(self, x, h):
        if isinstance(h, (tuple, list)):
            h = h[0]

        def impl(x, h, wi, wh, bi, bh):
            gi = x @ wi + bi
            gh = h @ wh + bh
            i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
            h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(i_r + h_r)
            z = jax.nn.sigmoid(i_z + h_z)
            n = jnp.tanh(i_n + r * h_n)
            return (1 - z) * n + z * h

        out = apply(impl, (x, h, self.weight_ih, self.weight_hh,
                           self.bias_ih, self.bias_hh), name="gru_cell")
        return out, out


class RNN(Layer):
    """Scan driver over any cell (reference: layers/rnn.py:rnn /
    dygraph RNN wrapper). One `lax.scan` — static shapes, no per-step
    dispatch. Sequence-major internally; accepts batch-major."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        batch = inputs.shape[1 if self.time_major else 0]
        if initial_states is None:
            initial_states = self.cell.get_initial_states(batch)

        cell = self.cell
        names = sorted(dict(cell.named_parameters()))
        param_map = dict(cell.named_parameters())
        time_major = self.time_major
        reverse = self.is_reverse
        multi = not isinstance(initial_states, Tensor)
        states0 = tuple(s.data for s in initial_states) if multi else \
            (initial_states.data,)
        has_len = sequence_length is not None

        from .layer import bind_state

        def impl(x, *rest):
            if has_len:
                seq_len, rest = rest[0], rest[1:]
            states = rest[:len(states0)]
            pvals = rest[len(states0):]
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)  # [T, B, F]
            T = x.shape[0]
            if reverse:
                if has_len:
                    # valid-prefix reverse (padding stays in place), so the
                    # backward pass starts at each row's LAST REAL step
                    t_idx = jnp.arange(T)[None, :]
                    rev = jnp.where(t_idx < seq_len[:, None],
                                    seq_len[:, None] - 1 - t_idx, t_idx)
                    x = jnp.take_along_axis(
                        jnp.swapaxes(x, 0, 1),
                        rev.reshape(rev.shape + (1,) * (x.ndim - 2)
                                    ).astype(jnp.int32), axis=1)
                    x = jnp.swapaxes(x, 0, 1)
                else:
                    x = jnp.flip(x, axis=0)

            with bind_state(cell, dict(zip(names, pvals))):
                from .. import autograd as _ag

                def step(carry, xt_t):
                    xt, t = xt_t
                    st = tuple(Tensor(c) for c in carry)
                    with _ag.no_grad():
                        out, new_state = cell(
                            Tensor(xt), st if multi else st[0])
                    if isinstance(new_state, (tuple, list)):
                        new_c = tuple(s.data for s in new_state)
                    else:
                        new_c = (new_state.data,)
                    if has_len:
                        # freeze state and zero outputs past each row's len
                        alive = (t < seq_len)[:, None]
                        new_c = tuple(jnp.where(alive, n, c)
                                      for n, c in zip(new_c, carry))
                        y = jnp.where(alive, out.data, 0.0)
                    else:
                        y = out.data
                    return new_c, y

                final, ys = lax.scan(step, tuple(states),
                                     (x, jnp.arange(T)))
            if reverse:
                if has_len:
                    t_idx = jnp.arange(T)[None, :]
                    rev = jnp.where(t_idx < seq_len[:, None],
                                    seq_len[:, None] - 1 - t_idx, t_idx)
                    ys = jnp.swapaxes(ys, 0, 1)
                    ys = jnp.take_along_axis(
                        ys, rev.reshape(rev.shape + (1,) * (ys.ndim - 2)
                                        ).astype(jnp.int32), axis=1)
                    ys = jnp.swapaxes(ys, 0, 1)
                else:
                    ys = jnp.flip(ys, axis=0)
            if not time_major:
                ys = jnp.swapaxes(ys, 0, 1)
            return (ys,) + final

        extra = (as_tensor(sequence_length),) if has_len else ()
        args = (inputs,) + extra + tuple(
            initial_states if multi else [initial_states]) + tuple(
            param_map[n] for n in names)
        out = apply(impl, args, n_out=1 + len(states0), name="rnn_scan")
        ys = out[0]
        final = out[1:]
        final_states = tuple(final) if multi else final[0]
        return ys, final_states


class _MultiLayerRNN(Layer):
    """Stacked (optionally bidirectional) recurrent network."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0):
        super().__init__()
        self.mode = mode
        self.time_major = time_major
        self.bidirectional = direction in ("bidirect", "bidirectional")
        self.num_layers = num_layers
        self.hidden_size = hidden_size
        self.dropout = dropout
        cells_fw, cells_bw = [], []
        cell_cls = {"LSTM": LSTMCell, "GRU": GRUCell,
                    "RNN": SimpleRNNCell}[mode]
        for layer_i in range(num_layers):
            in_sz = input_size if layer_i == 0 else hidden_size * (
                2 if self.bidirectional else 1)
            cells_fw.append(cell_cls(in_sz, hidden_size))
            if self.bidirectional:
                cells_bw.append(cell_cls(in_sz, hidden_size))
        from .container import LayerList
        self.cells_fw = LayerList(cells_fw)
        self.cells_bw = LayerList(cells_bw) if self.bidirectional else None

    def _layer_init(self, initial_states, li, d):
        """Slice the [num_layers*dirs, B, ...] initial-state convention
        (paddle.nn.LSTM/GRU) down to one direction of one layer."""
        if initial_states is None:
            return None
        dirs = 2 if self.bidirectional else 1
        idx = li * dirs + d
        lead = initial_states[0].shape[0] if self.mode == "LSTM" else \
            initial_states.shape[0]
        if lead != self.num_layers * dirs:
            # jax indexing would CLAMP an OOB layer index and silently
            # reuse layer 0's state — fail loudly like the reference
            raise ValueError(
                f"initial_states leading dim {lead} != num_layers*dirs "
                f"({self.num_layers}*{dirs})")
        if self.mode == "LSTM":
            h0, c0 = initial_states
            return (h0[idx], c0[idx])
        return initial_states[idx]

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops import manip as M
        x = inputs
        finals = []
        for li in range(self.num_layers):
            fw = RNN(self.cells_fw[li], time_major=self.time_major)
            y_fw, s_fw = fw(x, initial_states=self._layer_init(
                initial_states, li, 0), sequence_length=sequence_length)
            if self.bidirectional:
                bw = RNN(self.cells_bw[li], is_reverse=True,
                         time_major=self.time_major)
                y_bw, s_bw = bw(x, initial_states=self._layer_init(
                    initial_states, li, 1), sequence_length=sequence_length)
                x = M.concat([y_fw, y_bw], axis=-1)
                finals.append((s_fw, s_bw))
            else:
                x = y_fw
                finals.append(s_fw)
            if self.dropout > 0 and li < self.num_layers - 1:
                from ..ops import nn_ops as F
                x = F.dropout(x, p=self.dropout, training=self.training)
        return x, finals


class LSTM(_MultiLayerRNN):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class GRU(_MultiLayerRNN):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class SimpleRNN(_MultiLayerRNN):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0):
        super().__init__("RNN", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class StaticRNN(Layer):
    """reference: layers/control_flow.py:StaticRNN parity — a python-level
    step recorder; on TPU prefer RNN/lax.scan (this exists for API parity
    and simply unrolls)."""

    def __init__(self):
        super().__init__()
        self._steps = []

    def step(self, fn):
        self._steps.append(fn)
        return fn

    def forward(self, xs, init):
        h = init
        outs = []
        for x in xs:
            for fn in self._steps:
                h = fn(x, h)
            outs.append(h)
        return outs, h
