"""User-facing pipeline (pp) stage sharding for the fleet bridge.

TPU-native rebuild of Fleet's pipeline strategy (reference:
python/paddle/fluid/optimizer.py:PipelineOptimizer +
incubate/fleet/collective DistributedStrategy pipeline mode). The
reference splits the Program into per-device section programs and
streams microbatches between them. The GSPMD formulation used here:
a trunk of IDENTICAL blocks (transformer encoder layers) has its
per-block parameters stacked on a leading axis sharded over the mesh's
`pp` axis — every stage's weights live only on its pipeline group — and
the forward is one `lax.scan` over the stacked axis. XLA then streams
each stage's (stage-resident) weights/activations with its own
collectives. This is the standard JAX/GSPMD pipeline recipe
("stacked-scan with stage-sharded weights"); the lower-level explicit
GPipe microbatch schedule over `ppermute` lives in parallel/megatron.py.

The stacked module is a drop-in replacement for a LayerList trunk:
optimizer/state_dict/checkpoint all see ordinary (sharded) Parameters.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..tensor import Tensor, Parameter
from ..dispatch import apply
from .. import autograd as _ag
from ..nn.layer import Layer

__all__ = ["PipelineStack"]


class PipelineStack(Layer):
    """Stack N identical blocks into stage-sharded scanned weights.

    blocks: list/LayerList of structurally identical Layers (same param
    names/shapes). mesh + pipeline_axis: where the stacked axis lives.
    spec_fn(name, shape) -> PartitionSpec gives the per-block placement
    (e.g. megatron tp specs); the pp axis is prepended to it.
    """

    def __init__(self, blocks, mesh=None, pipeline_axis="pp",
                 spec_fn=None):
        super().__init__()
        blocks = list(blocks)
        if not blocks:
            raise ValueError("PipelineStack needs at least one block")
        self._template = blocks[0]
        # template params are NOT trainable on their own — exclude the
        # template from registration (its holders get swapped per step)
        self._sub_layers.pop("_template", None)
        object.__setattr__(self, "_template", blocks[0])

        names = list(blocks[0].state_dict().keys())
        self._names = names
        self._flat_names = []
        for name in names:
            per = [b.state_dict()[name].data for b in blocks]
            stk = jnp.stack(per)
            if mesh is not None:
                spec = spec_fn(name, per[0].shape) if spec_fn else P()
                full = P(*((pipeline_axis,) + tuple(spec)))
                stk = jax.device_put(stk, NamedSharding(mesh, full))
            flat = "stk_" + name.replace(".", "__")
            setattr(self, flat, Parameter(stk))
            self._flat_names.append(flat)
        self.num_blocks = len(blocks)

    def forward(self, x, *extras):
        stacked = [self._parameters[n] for n in self._flat_names]
        template = self._template
        # the template is unregistered (its params are placeholders), so
        # train/eval mode must be forwarded by hand
        template.train() if self.training else template.eval()
        names = self._names

        def impl(x, *rest):
            stk = rest[:len(names)]
            extra_arr = rest[len(names):]

            def body(h, slices):
                holders = template.state_dict()
                saved = {}
                try:
                    for name, sl in zip(names, slices):
                        saved[name] = holders[name].data
                        holders[name].data = sl
                    with _ag.no_grad():
                        out = template(Tensor(h),
                                       *[Tensor(e) for e in extra_arr])
                    out = out.data if isinstance(out, Tensor) else out
                finally:
                    for name, v in saved.items():
                        holders[name].data = v
                return out, None

            h, _ = lax.scan(body, x, tuple(stk))
            return h

        return apply(impl, (x,) + tuple(stacked) + tuple(extras),
                     name="pipeline_stack")
