"""User-facing pipeline (pp) stage sharding for the fleet bridge.

TPU-native rebuild of Fleet's pipeline strategy (reference:
python/paddle/fluid/optimizer.py:PipelineOptimizer +
incubate/fleet/collective DistributedStrategy pipeline mode). The
reference splits the Program into per-device section programs and
streams microbatches between them. The GSPMD formulation used here:
a trunk of IDENTICAL blocks (transformer encoder layers) has its
per-block parameters stacked on a leading axis sharded over the mesh's
`pp` axis — every stage's weights live only on its pipeline group — and
the forward is one `lax.scan` over the stacked axis. XLA then streams
each stage's (stage-resident) weights/activations with its own
collectives. This is the standard JAX/GSPMD pipeline recipe
("stacked-scan with stage-sharded weights"); the lower-level explicit
GPipe microbatch schedule over `ppermute` lives in parallel/megatron.py.

The stacked module is a drop-in replacement for a LayerList trunk:
optimizer/state_dict/checkpoint all see ordinary (sharded) Parameters.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..tensor import Tensor, Parameter
from ..dispatch import apply
from .. import autograd as _ag
from ..nn.layer import Layer
from .collective import axis_size as _axis_size

__all__ = ["PipelineStack", "PipelineSchedule", "build_schedule",
           "pipeline_step"]


class PipelineStack(Layer):
    """Stack N identical blocks into stage-sharded scanned weights.

    blocks: list/LayerList of structurally identical Layers (same param
    names/shapes). mesh + pipeline_axis: where the stacked axis lives.
    spec_fn(name, shape) -> PartitionSpec gives the per-block placement
    (e.g. megatron tp specs); the pp axis is prepended to it.
    """

    def __init__(self, blocks, mesh=None, pipeline_axis="pp",
                 spec_fn=None, remat=False):
        super().__init__()
        blocks = list(blocks)
        if not blocks:
            raise ValueError("PipelineStack needs at least one block")
        # remat: jax.checkpoint each stage inside the scan (recompute
        # activations during backward — the fleet recompute strategy
        # applied to the stacked trunk)
        self._remat = bool(remat)
        self._template = blocks[0]
        # template params are NOT trainable on their own — exclude the
        # template from registration (its holders get swapped per step)
        self._sub_layers.pop("_template", None)
        object.__setattr__(self, "_template", blocks[0])

        names = list(blocks[0].state_dict().keys())
        self._names = names
        self._flat_names = []
        for name in names:
            per = [b.state_dict()[name].data for b in blocks]
            stk = jnp.stack(per)
            if mesh is not None:
                spec = spec_fn(name, per[0].shape) if spec_fn else P()
                full = P(*((pipeline_axis,) + tuple(spec)))
                stk = jax.device_put(stk, NamedSharding(mesh, full))
            flat = "stk_" + name.replace(".", "__")
            setattr(self, flat, Parameter(stk))
            self._flat_names.append(flat)
        self.num_blocks = len(blocks)

    def forward(self, x, *extras):
        from .. import random as prandom
        from ..nn.moe import MoEFFN
        stacked = [self._parameters[n] for n in self._flat_names]
        template = self._template
        # the template is unregistered (its params are placeholders), so
        # train/eval mode must be forwarded by hand
        template.train() if self.training else template.eval()
        names = self._names
        # MoE sublayers stash an aux (load-balance) loss during forward —
        # a scan-body tracer if left on the template. Thread the aux
        # values out as scan OUTPUTS and re-stash the per-trunk total on
        # this Layer (moe_aux_loss collects it from here).
        moe_subs = [l for l in template.sublayers(include_self=True)
                    if isinstance(l, MoEFFN)]

        def impl(x, rng_key, *rest):
            stk = rest[:len(names)]
            extra_arr = rest[len(names):]

            def stage_call(h, sub, *slices):
                # stochastic ops (dropout) inside the scan body must draw
                # from a key CARRIED through the scan — letting them
                # advance the global key would leak a scan-body tracer
                # into it (same invariant as jit.recompute)
                holders = template.state_dict()
                saved = {}
                saved_key = prandom._global_key.data
                prandom._global_key.data = sub
                try:
                    for name, sl in zip(names, slices):
                        saved[name] = holders[name].data
                        holders[name].data = sl
                    with _ag.no_grad():
                        out = template(Tensor(h),
                                       *[Tensor(e) for e in extra_arr])
                    out = out.data if isinstance(out, Tensor) else out
                    auxs = tuple(l.aux_loss.data for l in moe_subs)
                finally:
                    prandom._global_key.data = saved_key
                    for name, v in saved.items():
                        holders[name].data = v
                return out, auxs

            if self._remat:
                stage_call = jax.checkpoint(stage_call)

            def body(carry, slices):
                h, key = carry
                key, sub = jax.random.split(key)
                out, auxs = stage_call(h, sub, *slices)
                return (out, key), auxs

            (h, _), auxs = lax.scan(body, (x, rng_key), tuple(stk))
            # auxs: tuple of [num_blocks] arrays — total load-balance aux
            total_aux = None
            for a in auxs:
                s = jnp.sum(a)
                total_aux = s if total_aux is None else total_aux + s
            return (h, total_aux) if moe_subs else h

        args = (x, prandom.next_key_graph()) + tuple(stacked) + \
            tuple(extras)
        if not moe_subs:
            self.aux_loss = None
            return apply(impl, args, name="pipeline_stack")
        h, aux = apply(impl, args, name="pipeline_stack", n_out=2)
        self.aux_loss = aux
        return h


# ---------------------------------------------------------------------------
# Explicit microbatch schedules: GPipe, 1F1B, interleaved 1F1B.
#
# Reference: fluid/optimizer.py PipelineOptimizer splits the Program into
# per-device section programs and streams microbatches through them (GPipe
# order, schedule fixed by the section runner). The TPU rebuild makes the
# schedule a first-class object: a [T, n_ranks] table of (op, microbatch,
# chunk) slots produced by a dependency-respecting simulator, with analytic
# bubble/memory accounting, executed by `pipeline_step` as one lax.scan of
# lax.switch ticks over a ppermute ring inside shard_map.
#
# Schedule facts (fwd and bwd both 1 time unit):
#   gpipe        bubble = (n-1)/(m+n-1)    peak live acts = m
#   1f1b         bubble = (n-1)/(m+n-1)    peak live acts = min(m, n)
#   interleaved  bubble ~ (n-1)/(v*m+n-1)  peak live acts ~ min(m, n)+v-1
# (n = ranks, m = microbatches, v = chunks/rank). Non-interleaved 1F1B
# matches GPipe in TIME and wins on MEMORY (activations freed as soon as
# their backward runs); the interleaved schedule also shrinks the time
# bubble by ~v.

_IDLE, _FWD, _BWD = 0, 1, 2


class PipelineSchedule:
    """A simulated pipeline timeline.

    table: int32 [T, n_ranks, 3] of (op, microbatch, chunk) — op 0/1/2 =
    idle/forward/backward; chunk is the virtual-stage index on that rank
    (always 0 unless interleaved). Stage s = chunk * n_ranks + rank."""

    def __init__(self, kind, table, n_ranks, n_micro, n_chunks):
        self.kind = kind
        self.table = table
        self.n_ranks = n_ranks
        self.n_micro = n_micro
        self.n_chunks = n_chunks

    @property
    def n_ticks(self):
        return self.table.shape[0]

    def bubble_fraction(self, bwd_cost=1.0):
        """Idle fraction of the timeline. bwd_cost weights backward ops
        (Megatron's accounting uses ~2.0: bwd is two matmul passes);
        each tick's duration is the COSTLIEST op running in it (lockstep
        SPMD: every rank waits for the slowest)."""
        ops = self.table[:, :, 0]
        cost = {_IDLE: 0.0, _FWD: 1.0, _BWD: float(bwd_cost)}
        tick_len = np.array([max(cost[int(o)] for o in row)
                             for row in ops])
        busy = sum(cost[int(o)] for row in ops for o in row)
        total = float(tick_len.sum()) * self.n_ranks
        return 1.0 - busy / total if total else 0.0

    def render(self):
        """ASCII timeline (ranks x ticks): F3/B3 = fwd/bwd of microbatch
        3; for interleaved, chunk c shows as c:F3. Debugging aid."""
        # one fixed cell width keeps tick columns vertically aligned
        width = 1 + len(str(self.n_micro - 1)) + (
            2 if self.n_chunks > 1 else 0)
        lines = []
        for r in range(self.n_ranks):
            cells = []
            for t in range(self.n_ticks):
                op, mb, c = self.table[t, r]
                if op == _IDLE:
                    cells.append(".".center(width))
                else:
                    tag = "F" if op == _FWD else "B"
                    pre = f"{c}:" if self.n_chunks > 1 else ""
                    cells.append(f"{pre}{tag}{mb}".rjust(width))
            lines.append(f"rank{r}: " + " ".join(cells))
        return "\n".join(lines)

    def peak_live_activations(self):
        """Max over (rank, chunk) of simultaneously-saved fwd activations
        (saved at F, freed at the matching B) — the per-stage activation
        memory the schedule needs."""
        peak = 0
        for r in range(self.n_ranks):
            live = {}
            for t in range(self.n_ticks):
                op, mb, c = self.table[t, r]
                if op == _FWD:
                    live[c] = live.get(c, 0) + 1
                    peak = max(peak, live[c])
                elif op == _BWD:
                    live[c] = live.get(c, 0) - 1
        return peak


def _rank_orders(kind, n, m, v):
    """Per-rank total op order (list of (op, mb, chunk) per rank)."""
    if kind == "gpipe":
        return [[(_FWD, mb, 0) for mb in range(m)]
                + [(_BWD, mb, 0) for mb in reversed(range(m))]
                for _ in range(n)]
    if kind == "1f1b":
        orders = []
        for r in range(n):
            w = min(m, n - 1 - r)          # warmup forwards
            ops = [(_FWD, mb, 0) for mb in range(w)]
            fwd, bwd = w, 0
            while fwd < m:                  # steady 1F1B
                ops.append((_FWD, fwd, 0)); fwd += 1
                ops.append((_BWD, bwd, 0)); bwd += 1
            while bwd < m:                  # cooldown backwards
                ops.append((_BWD, bwd, 0)); bwd += 1
            orders.append(ops)
        return orders
    if kind == "interleaved":
        if m % n != 0:
            raise ValueError("interleaved schedule needs n_micro % "
                             "n_ranks == 0 (Megatron constraint)")
        orders = []
        for r in range(n):
            # forward/backward enumeration: groups of n microbatches cycle
            # through the chunks (Megatron interleaved order)
            fseq, bseq = [], []
            for g in range(m // n):
                base = g * n
                for c in range(v):
                    fseq += [(_FWD, base + i, c) for i in range(n)]
                for c in reversed(range(v)):
                    bseq += [(_BWD, base + i, c) for i in range(n)]
            warm = min(len(fseq), (n - 1 - r) * 2 + (v - 1) * n)
            ops = fseq[:warm]
            fi, bi = warm, 0
            while fi < len(fseq):
                ops.append(fseq[fi]); fi += 1
                ops.append(bseq[bi]); bi += 1
            ops += bseq[bi:]
            orders.append(ops)
        return orders
    raise ValueError(f"unknown schedule kind {kind!r}")


def build_schedule(kind, n_ranks, n_micro, n_chunks=1):
    """Simulate `kind` into a dependency-valid timeline.

    Greedy lockstep simulation: at each tick every rank runs the next op
    in its order whose dependencies completed on an EARLIER tick (the
    activation/cotangent ride one ppermute hop between ticks):
      F(s, mb) needs F(s-1, mb);  B(s, mb) needs F(s, mb) and B(s+1, mb)
    where stage s = chunk * n_ranks + rank runs on rank s % n_ranks."""
    n, m, v = n_ranks, n_micro, n_chunks
    if kind != "interleaved" and v != 1:
        raise ValueError("n_chunks > 1 only for the interleaved schedule")
    orders = _rank_orders(kind, n, m, v)
    done_f, done_b = {}, {}   # (stage, mb) -> completion tick
    idx = [0] * n
    rows = []
    t = 0
    limit = 4 * v * (m + n) + 16
    while any(idx[r] < len(orders[r]) for r in range(n)):
        if t > limit:
            raise RuntimeError(f"schedule {kind} deadlocked (bug in the "
                               "per-rank order)")
        row = []
        fired = []
        for r in range(n):
            if idx[r] >= len(orders[r]):
                row.append((_IDLE, 0, 0))
                continue
            op, mb, c = orders[r][idx[r]]
            s = c * n + r
            if op == _FWD:
                ready = (s == 0) or done_f.get((s - 1, mb), t) < t
            else:
                last = s == v * n - 1
                ready = done_f.get((s, mb), t) < t and (
                    last or done_b.get((s + 1, mb), t) < t)
            if ready:
                row.append((op, mb, c))
                fired.append((r, op, s, mb))
                idx[r] += 1
            else:
                row.append((_IDLE, 0, 0))
        for r, op, s, mb in fired:
            (done_f if op == _FWD else done_b)[(s, mb)] = t
        rows.append(row)
        t += 1
    table = np.asarray(rows, np.int32)
    return PipelineSchedule(kind, table, n, m, v)


def pipeline_step(schedule, stage_fn, loss_fn, params, x_micro,
                  labels_micro, axis="pp"):
    """Execute one fwd+bwd pipeline pass under `schedule`. Runs INSIDE
    shard_map with `axis` bound (one rank per pipeline stage).

    params: pytree whose leaves carry a leading [n_chunks] axis (this
    rank's virtual stages; n_chunks=1 for gpipe/1f1b).
    stage_fn(x, p_chunk) -> y with y.shape == x.shape.
    loss_fn(y, labels_mb) -> scalar (per-microbatch mean).
    x_micro: [m, ...] stage-0 inputs; labels_micro: [m, ...] last-stage
    targets (replicated — each rank reads only what its ops use).

    Returns (loss, grads): loss = mean over microbatches (on every rank);
    grads = pytree like params. BACKWARD IS MANUAL — per-tick jax.vjp with
    recompute-from-saved-input (the activation a B op consumes is the
    stage INPUT saved by its F op; the stage is re-run inside vjp), so
    activation memory follows the schedule's peak_live_activations, not
    the autodiff engine's whole-timeline saves."""
    n = _axis_size(axis)
    r = lax.axis_index(axis)
    m = schedule.n_micro
    v = schedule.n_chunks
    assert schedule.n_ranks == n, (schedule.n_ranks, n)
    table = jnp.asarray(schedule.table)          # [T, n, 3]
    A = schedule.peak_live_activations() + 2     # act/inbox slots (+transit)

    x_shape = x_micro.shape[1:]
    zero_x = jnp.zeros(x_shape, x_micro.dtype)
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    bwd_perm = [(i, (i - 1) % n) for i in range(n)]

    def tick(carry, trow):
        acts, inbox_f, inbox_b, grads, loss_acc = carry
        op, mb, c = trow[r, 0], trow[r, 1], trow[r, 2]
        s = c * n + r                             # global stage id
        slot = mb % A
        p_c = jax.tree_util.tree_map(lambda l: l[c], params)

        def do_idle(acts, grads, loss_acc):
            return acts, zero_x, zero_x, grads, loss_acc

        def do_fwd(acts, grads, loss_acc):
            x = jnp.where(s == 0, x_micro[mb], inbox_f[c, slot])
            acts = acts.at[c, slot].set(x)
            y = stage_fn(x, p_c)
            return acts, y, zero_x, grads, loss_acc

        def do_bwd(acts, grads, loss_acc):
            x = acts[c, slot]

            def full(x, p):
                y = stage_fn(x, p)
                return y, loss_fn(y, labels_micro[mb])

            (y, lval), vjp_fn = jax.vjp(full, x, p_c)
            is_last = s == v * n - 1
            ct_y = jnp.where(is_last, jnp.zeros_like(y), inbox_b[c, slot])
            ct_l = jnp.where(is_last, 1.0 / m, 0.0)
            dx, dp = vjp_fn((ct_y.astype(y.dtype),
                             jnp.asarray(ct_l, lval.dtype)))
            grads = jax.tree_util.tree_map(
                lambda g, d: g.at[c].add(d), grads, dp)
            loss_acc = loss_acc + jnp.where(is_last, lval / m, 0.0)
            return acts, zero_x, dx, grads, loss_acc

        acts, y_out, dx_out, grads, loss_acc = lax.switch(
            op, (do_idle, do_fwd, do_bwd), acts, grads, loss_acc)

        # ride the ring every tick (collectives must run on all ranks).
        # Each payload is tagged with its microbatch (-1 = nothing) and
        # the RECEIVER's chunk index — stage s+1 lives on rank (s+1)%n at
        # chunk (s+1)//n — and filed into the receiver's (chunk, mb)
        # inbox slot. The last stage sends no activation; stage 0 sends
        # no cotangent.
        sent_f = jnp.where((op == _FWD) & (s < v * n - 1), mb, -1)
        sent_fc = jnp.clip((s + 1) // n, 0, v - 1)
        sent_b = jnp.where((op == _BWD) & (s > 0), mb, -1)
        sent_bc = jnp.clip((s - 1) // n, 0, v - 1)
        recv_y = lax.ppermute(y_out, axis, fwd_perm)
        recv_fmb = lax.ppermute(sent_f, axis, fwd_perm)
        recv_fc = lax.ppermute(sent_fc, axis, fwd_perm)
        recv_dx = lax.ppermute(dx_out, axis, bwd_perm)
        recv_bmb = lax.ppermute(sent_b, axis, bwd_perm)
        recv_bc = lax.ppermute(sent_bc, axis, bwd_perm)
        fslot = jnp.clip(recv_fmb, 0) % A
        bslot = jnp.clip(recv_bmb, 0) % A
        inbox_f = inbox_f.at[recv_fc, fslot].set(
            jnp.where(recv_fmb >= 0, recv_y, inbox_f[recv_fc, fslot]))
        inbox_b = inbox_b.at[recv_bc, bslot].set(
            jnp.where(recv_bmb >= 0, recv_dx, inbox_b[recv_bc, bslot]))
        return (acts, inbox_f, inbox_b, grads, loss_acc), None

    acts0 = jnp.zeros((v, A) + x_shape, x_micro.dtype)
    inbox0 = jnp.zeros((v, A) + x_shape, x_micro.dtype)
    grads0 = jax.tree_util.tree_map(jnp.zeros_like, params)
    carry0 = (acts0, inbox0, inbox0, grads0,
              jnp.zeros((), jnp.float32))
    (_, _, _, grads, loss), _ = lax.scan(tick, carry0, table)
    # the last stage lives on one rank: hand every pp rank the loss and
    # the stage-sharded grads stay local (stage s params live where s runs)
    loss = lax.psum(loss, axis)
    return loss, grads
