"""paddle_tpu.parallel — distribution: mesh, collectives, fleet, parallel
layers (reference: fluid/incubate/fleet, operators/collective, dygraph
parallel; redesigned over jax.sharding / shard_map / ICI collectives)."""
from . import collective
from .collective import (make_mesh, get_mesh, set_mesh, shard, replicated,
                         all_reduce, all_gather, reduce_scatter, broadcast,
                         all_to_all, ppermute, barrier,
                         all_reduce_quantized, matmul_reduce_scatter)
from . import overlap
from .overlap import (GradSyncScheduler, local_value_and_grad, sync_tree,
                      plan_buckets)
from . import layout
from .layout import mesh_signature, extract_layout, adapt_spec, reshard
from . import planner
from .planner import (MeshPlan, MEGATRON_RULES, TRANSFORMER_RULES,
                      advise, plan)
from .env import ParallelEnv, prepare_context
from . import fleet as fleet_mod
from .fleet import fleet, DistributedStrategy, PaddleCloudRoleMaker, init
from .data_parallel import DataParallel
from .ring_attention import ring_attention
from .embedding import ShardedEmbedding, sharded_lookup
