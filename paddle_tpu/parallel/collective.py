"""paddle_tpu.parallel.collective — collective communication.

TPU-native rebuild of the reference's collective operators
(reference: paddle/fluid/operators/collective/{c_allreduce_op, c_allgather_op,
c_reducescatter_op, c_broadcast_op, barrier_op, c_gen_nccl_id_op}.* and
python/paddle/fluid/layers/collective.py, transpiler/collective.py).

NCCL rings become XLA collectives on the ICI mesh: inside a
``shard_map``/``pjit`` region the ops lower to `lax.psum` / `all_gather` /
`psum_scatter` / `ppermute`, which XLA schedules directly onto ICI links —
there is no NCCL-style id bootstrap (gen_nccl_id) because device topology is
part of the mesh. Outside an SPMD region (single chip eager) they are
identity/no-ops, matching single-process semantics of the reference.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P, NamedSharding

from ..tensor import Tensor, as_tensor
from ..dispatch import apply
from .. import monitor as _monitor

# ---------------------------------------------------------------------------
# global mesh registry (the TPU analogue of the reference's communicator /
# ParallelContext state)

_global_mesh = None


def set_mesh(mesh: Mesh):
    global _global_mesh
    _global_mesh = mesh
    return mesh


def get_mesh() -> Mesh:
    return _global_mesh


def make_mesh(axes: dict, devices=None) -> Mesh:
    """Create and register a Mesh, e.g. make_mesh({'dp': 2, 'tp': 4})."""
    devices = devices if devices is not None else jax.devices()
    names = tuple(axes)
    sizes = tuple(axes[n] for n in names)
    need = int(np.prod(sizes))
    if need > len(devices):
        raise ValueError(f"mesh needs {need} devices, have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(sizes)
    return set_mesh(Mesh(arr, names))


def replicated(x, mesh=None):
    """Place an array/Tensor replicated over the mesh."""
    mesh = mesh or _global_mesh
    if mesh is None:
        return x
    sh = NamedSharding(mesh, P())
    if isinstance(x, Tensor):
        x.data = jax.device_put(x.data, sh)
        return x
    return jax.device_put(x, sh)


def shard(x, spec, mesh=None):
    """Place an array/Tensor with a PartitionSpec over the mesh."""
    mesh = mesh or _global_mesh
    if mesh is None:
        return x
    sh = NamedSharding(mesh, spec if isinstance(spec, P) else P(*spec))
    if isinstance(x, Tensor):
        x.data = jax.device_put(x.data, sh)
        return x
    return jax.device_put(x, sh)


# ---------------------------------------------------------------------------
# SPMD-region detection: collectives need an axis name bound by
# shard_map/pmap; in plain eager (or plain jit) they act as identity.

def axis_size(axis_name):
    """lax.axis_size(axis_name) across jax versions. Older jax has no
    lax.axis_size; psum of the literal 1 folds statically to the axis
    size inside any SPMD region and raises NameError outside — exactly
    the contract callers (and in_spmd_context) need."""
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def shard_map_compat(f, mesh, in_specs, out_specs, check_vma=None):
    """jax.shard_map across jax versions: older jax only ships
    jax.experimental.shard_map.shard_map, whose replication-check kwarg
    is spelled check_rep rather than check_vma."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
    from jax.experimental.shard_map import shard_map as _sm
    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def in_spmd_context(axis_name=None):
    try:
        if axis_name is not None:
            axis_size(axis_name)
            return True
        return False
    except (NameError, KeyError, Exception):
        return False


# ---------------------------------------------------------------------------
# collectives (reference: c_allreduce_{sum,max,min,prod}, c_allgather,
# c_reducescatter, c_broadcast, barrier)

def _maybe(axis_name):
    return axis_name is not None and in_spmd_context(axis_name)


def _account(op, x, axis_name):
    """Monitor accounting for one issued collective: op count + payload
    bytes by mesh axis, plus a ``collective.<op>`` instant marker on the
    monitor.trace timeline (so collective issue sites line up against
    the executor/step spans in the Perfetto export). Runs AFTER the
    SPMD gate, so eager identity fallbacks don't count. Shapes are
    static under shard_map tracing, so this works on tracers; bytes are
    the per-shard payload, and inside a jitted region the record is per
    trace, not per device execution."""
    if not _monitor.enabled():
        return
    a = x.data if isinstance(x, Tensor) else x
    shape = tuple(getattr(a, "shape", ()) or ())
    try:
        itemsize = jnp.dtype(getattr(a, "dtype", jnp.float32)).itemsize
    except TypeError:
        itemsize = 4
    nbytes = int(np.prod(shape, dtype=np.int64)) * itemsize if shape \
        else itemsize
    _monitor.record_collective(op, axis_name, nbytes)


def all_reduce(x, op="sum", axis_name="dp", group=None):
    """c_allreduce_* → lax.psum/pmean/pmax/pmin on the ICI mesh axis.
    ``op="mean"`` is first-class (lax.pmean) — callers must not
    hand-divide a psum by the axis size."""
    if not _maybe(axis_name):
        return as_tensor(x)
    _account(f"c_allreduce_{op}", x, axis_name)
    fns = {"sum": lax.psum, "mean": lax.pmean, "max": lax.pmax,
           "min": lax.pmin,
           "prod": lambda v, n: jnp.exp(lax.psum(jnp.log(v), n))}
    if op not in fns:
        raise ValueError(
            f"all_reduce op {op!r} unknown; supported: {sorted(fns)}")
    fn = fns[op]
    return apply(lambda x: fn(x, axis_name), (x,), name=f"c_allreduce_{op}")


def all_gather(x, axis=0, axis_name="dp", group=None):
    """c_allgather → lax.all_gather along the mesh axis."""
    if not _maybe(axis_name):
        return as_tensor(x)
    _account("c_allgather", x, axis_name)
    return apply(lambda x: lax.all_gather(x, axis_name, axis=axis,
                                          tiled=True),
                 (x,), name="c_allgather")


def reduce_scatter(x, axis=0, axis_name="dp", group=None):
    """c_reducescatter → lax.psum_scatter."""
    if not _maybe(axis_name):
        return as_tensor(x)
    _account("c_reducescatter", x, axis_name)
    return apply(lambda x: lax.psum_scatter(x, axis_name,
                                            scatter_dimension=axis,
                                            tiled=True),
                 (x,), name="c_reducescatter")


def broadcast(x, src=0, axis_name="dp", group=None):
    """c_broadcast: every rank takes rank-src's value (select+psum)."""
    if not _maybe(axis_name):
        return as_tensor(x)
    _account("c_broadcast", x, axis_name)

    def impl(x):
        idx = lax.axis_index(axis_name)
        masked = jnp.where(idx == src, x, jnp.zeros_like(x))
        return lax.psum(masked, axis_name)

    return apply(impl, (x,), name="c_broadcast")


def all_to_all(x, split_axis=0, concat_axis=0, axis_name="dp", group=None):
    """alltoall_op → lax.all_to_all (the sequence/expert-parallel workhorse)."""
    if not _maybe(axis_name):
        return as_tensor(x)
    _account("alltoall", x, axis_name)
    return apply(lambda x: lax.all_to_all(x, axis_name, split_axis,
                                          concat_axis, tiled=True),
                 (x,), name="alltoall")


def ppermute(x, perm, axis_name="dp"):
    """Point-to-point ring permute (building block for ring attention and
    pipeline parallelism)."""
    if not _maybe(axis_name):
        return as_tensor(x)
    _account("ppermute", x, axis_name)
    return apply(lambda x: lax.ppermute(x, axis_name, perm), (x,),
                 name="ppermute")


def barrier(axis_name="dp", group=None):
    """barrier_op — on XLA a barrier is an all-reduce of a scalar."""
    if not _maybe(axis_name):
        return
    _account("barrier", jnp.zeros((), jnp.float32), axis_name)
    lax.psum(jnp.zeros((), jnp.float32), axis_name)


def rank(axis_name="dp"):
    if not _maybe(axis_name):
        return 0
    return lax.axis_index(axis_name)


def world_size(axis_name="dp"):
    if not _maybe(axis_name):
        return 1
    return axis_size(axis_name)


# reference-parity aliases (fluid.layers.collective underscored names)
_c_allreduce = all_reduce
_c_allgather = all_gather
_c_reducescatter = reduce_scatter
_c_broadcast = broadcast


def matmul_reduce_scatter(x, w, axis_name="tp", fused=True):
    """Fused matmul-then-reduce-scatter for the tensor-parallel exit of
    a row-split layer (fused computation-collectives, arxiv 2305.06942;
    reference analogue: the c_reducescatter op a Megatron row layer
    would issue after its partial matmul).

    ``x @ w`` where x is [m, k_local] and w is [k_local, N] with N
    divisible by the axis size; every rank holds a partial [m, N]
    product that must be reduce-scattered over the last dim. The
    unfused form is ``lax.psum_scatter(x @ w, ...)`` — the full partial
    product materialises, then the wire moves it. The fused schedule
    interleaves per-block matmuls with ring ppermute hops of the
    accumulator (start at column block (r-1)%n, permute forward, add
    block (r-t-2)%n each hop), so the collective for block t rides
    under the matmul for block t+1 and rank r ends holding fully
    reduced block r — bit-compatible layout with
    ``lax.psum_scatter(..., tiled=True)``. Outside an SPMD region it
    degrades to the plain local matmul (reduce_scatter's identity
    semantics)."""
    if not _maybe(axis_name):
        a = x.data if isinstance(x, Tensor) else x
        b = w.data if isinstance(w, Tensor) else w
        return as_tensor(jnp.asarray(a) @ jnp.asarray(b))
    _account("matmul_reduce_scatter", w, axis_name)

    def impl(x, w):
        n = axis_size(axis_name)
        m, N = x.shape[0], w.shape[1]
        if N % n:
            raise ValueError(
                f"matmul_reduce_scatter: output dim {N} not divisible "
                f"by axis {axis_name!r} size {n}")
        bs = N // n
        if not fused:
            return lax.psum_scatter(x @ w, axis_name,
                                    scatter_dimension=1, tiled=True)
        r = lax.axis_index(axis_name)
        fwd = [(i, (i + 1) % n) for i in range(n)]

        def block(j):
            return x @ lax.dynamic_slice(w, (0, j * bs),
                                         (w.shape[0], bs))

        acc = block((r - 1) % n)
        for t in range(n - 1):
            acc = lax.ppermute(acc, axis_name, fwd)
            acc = acc + block((r - t - 2) % n)
        return acc

    return apply(impl, (x, w), name="matmul_reduce_scatter")


QUANTIZED_WIRE_BITS = (4, 8)


def all_reduce_quantized(x, axis_name="dp", bits=8, op="sum"):
    """Quantized ring all-reduce: int8 (or packed-int4) chunks + one
    f32 scale per hop on the wire instead of f32 tensors (the EQuARX
    direction, arxiv 2506.17615; the reference's analogous bandwidth
    lever is DGC sparsification over NCCL). Ring reduce-scatter then
    ring all-gather, n-1 ppermute hops each, with per-hop symmetric
    requantization — wire bytes drop ~4x (int8) / ~8x (int4, two
    values packed per byte) for bf16/f32 grads at a bounded
    quantization error that grows with ring length (callers should
    reserve it for bandwidth-bound DCN/large-dp regimes; exact psum
    stays the default everywhere).

    Only meaningful inside shard_map with `axis_name`; returns the SUM
    over the axis (like lax.psum), or the mean with ``op="mean"`` —
    the division happens once, after the ring, so both ops share one
    wire schedule."""
    if bits not in QUANTIZED_WIRE_BITS:
        raise ValueError(
            f"quantized wire width bits={bits} unsupported; supported "
            f"widths: {QUANTIZED_WIRE_BITS} (int8, packed int4)")
    if op not in ("sum", "mean"):
        raise ValueError(
            f"all_reduce_quantized op {op!r} unknown; supported: "
            f"['mean', 'sum']")
    n = axis_size(axis_name)
    if n == 1:
        return x
    qmax = 127.0 if bits == 8 else 7.0

    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    c = -(-flat.shape[0] // n)
    if bits == 4:
        c += c % 2  # packed pairs: chunk length must be even
    flat = jnp.pad(flat, (0, n * c - flat.shape[0]))
    chunks = flat.reshape(n, c)
    r = lax.axis_index(axis_name)
    fwd = [(i, (i + 1) % n) for i in range(n)]

    if bits == 8:
        def quant(v):
            s = jnp.max(jnp.abs(v)) / qmax + 1e-30
            q = jnp.clip(jnp.round(v / s), -127, 127).astype(jnp.int8)
            return q, s

        def dequant(q, s):
            return q.astype(jnp.float32) * s
    else:
        # packed int4: q ∈ [-7, 7] biased to [1, 15], two nibbles per
        # uint8 byte — ~8x less wire than f32 plus one scale per hop
        def quant(v):
            s = jnp.max(jnp.abs(v)) / qmax + 1e-30
            q = jnp.clip(jnp.round(v / s), -7, 7)
            b = (q + 8.0).astype(jnp.uint8)
            packed = b[..., 0::2] | (b[..., 1::2] << 4)
            return packed, s

        def dequant(packed, s):
            lo = (packed & 0xF).astype(jnp.float32) - 8.0
            hi = (packed >> 4).astype(jnp.float32) - 8.0
            q = jnp.stack([lo, hi], axis=-1).reshape(
                packed.shape[:-1] + (2 * packed.shape[-1],))
            return q * s

    # ring reduce-scatter: after n-1 hops rank r owns the fully
    # reduced chunk (r + 1) % n
    for t in range(n - 1):
        send_idx = (r - t) % n
        recv_idx = (r - t - 1) % n
        piece = lax.dynamic_slice(chunks, (send_idx, 0), (1, c))
        q, s = quant(piece)
        q = lax.ppermute(q, axis_name, fwd)
        s = lax.ppermute(s, axis_name, fwd)
        got = dequant(q, s)
        cur = lax.dynamic_slice(chunks, (recv_idx, 0), (1, c))
        chunks = lax.dynamic_update_slice(chunks, cur + got,
                                          (recv_idx, 0))

    # ring all-gather of the owned (reduced) chunks. Each chunk is
    # quantized ONCE at its owner and the same (q, scale) pair rides
    # the whole ring — so every rank reconstructs bit-identical values
    # (per-hop requantization here would give each rank a different
    # approximation, and replicated params would silently drift).
    own_idx = (r + 1) % n
    own = lax.dynamic_slice(chunks, (own_idx, 0), (1, c))
    q, s = quant(own)
    # store the dequantized form locally too — identical on all ranks
    chunks = lax.dynamic_update_slice(chunks, dequant(q, s),
                                      (own_idx, 0))
    for t in range(n - 1):
        q = lax.ppermute(q, axis_name, fwd)
        s = lax.ppermute(s, axis_name, fwd)
        idx = (r - t) % n  # arriving chunk originated at rank
        # (r - t - 1), which owns chunk (r - t) % n
        chunks = lax.dynamic_update_slice(chunks, dequant(q, s),
                                          (idx, 0))

    out = chunks.reshape(-1)[:int(np.prod(shape))].reshape(shape)
    if op == "mean":
        # one division AFTER the ring: every rank scales the identical
        # dequantized sum, so the cross-rank bit-equality invariant of
        # the all-gather phase survives
        out = out / n
    return out.astype(x.dtype)
