"""paddle_tpu.parallel.planner — profile-guided GSPMD auto-sharding.

One planner behind every parallelism surface. Two halves:

**Layout half** — :class:`MeshPlan`: an ordered tuple of
``(regex, PartitionSpec)`` rules matched against parameter names
(first match wins, ``re.search`` semantics; scalars are always
replicated; unmatched leaves take the plan's ``default`` spec). The
plan annotates the WHOLE param/optimizer/grad-accumulator pytree —
``spec_for`` / ``annotate`` / ``place`` / ``as_spec_fn`` — and is the
single object threaded through ``hapi.Model.fit(mesh_plan=)``,
``Executor.run`` / ``train_from_dataset``, ``DataParallel``,
``MegatronConfig.mesh_plan`` and ``jit.to_static(plan=)``. Its
``plan_key()`` (mesh signature + rule-set hash) joins every executable
cache key so switching plans can never silently reuse a stale
executable. Non-divisible dims degrade through
``layout.adapt_spec`` — warned once, counted in ``layout.degraded``,
and visible to the advisor as a penalty (a degraded param's work
replicates instead of dividing).

**Advisor half** — closes the loop with measurement:
``score()`` estimates a candidate layout's step time from the roofline
model ``monitor.profile`` uses for its per-region ledger
(``max(flops/peak_flops, bytes/hbm_bw)``) plus a comm model priced in
the same wire-bytes currency as the ``comm.*`` series
(``overlap.wire_bytes`` per collective × ring factor ÷ link
bandwidth, measurable via :func:`measure_link_bandwidth`).
``advise()`` ranks candidate meshes (deterministic, tie-broken by
degradation then sizes, so the table is rank-stable), ``plan(auto=True)``
picks the winner, and the decision lands in the monitor ledger
(``planner.*`` counters/gauges, a ``kind="planner"`` JSONL record
cross-linked to the current top hotspot, and a ``planner`` block in
``/snapshot`` via :func:`last_decision`).

Cost-model honesty notes (all documented approximations, good enough
to ORDER layouts, not to predict absolute times):

* compute/memory: per-device flops and HBM bytes divide by the axes
  that split them; vocabulary logits replicate over tp; degraded
  params don't divide at all.
* comm: dp grad sync is a ring all-reduce (``2·(n−1)/n`` of the wire
  payload per rank); tp activation collectives are the Megatron f/g
  psum pairs, two per block direction; ppermute rings count one hop
  payload per step.
"""
from __future__ import annotations

import hashlib
import json
import re
import time

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .layout import adapt_spec, mesh_signature, spec_to_lists
from . import collective as _coll

__all__ = [
    "MeshPlan", "MEGATRON_RULES", "TRANSFORMER_RULES", "resolve",
    "candidate_sizes", "megatron_candidate_stats", "stats_from_profile",
    "score", "advise", "plan", "measure_link_bandwidth",
    "link_bandwidth", "last_decision",
]


# ---------------------------------------------------------------------------
# canonical rule sets

# Reproduces parallel.megatron.init_params' hand specs bit-identically
# (the plan_smoke gate): qkv/ffn1 column-split over tp (qkv via its
# explicit heads axis), attn_out/ffn2 row-split, stages stacked over pp,
# experts over ep, everything else replicated.
MEGATRON_RULES = (
    (r"^qkv_w$", P("pp", None, None, None, "tp", None)),
    (r"^qkv_b$", P("pp", None, None, "tp", None)),
    (r"^attn_out_w$", P("pp", None, "tp", None, None)),
    (r"^ffn1_w$", P("pp", None, None, "tp")),
    (r"^ffn1_b$", P("pp", None, "tp")),
    (r"^ffn2_w$", P("pp", None, "tp", None)),
    (r"^moe_w[12]$", P("ep", None, None, None)),
    (r"^(ln[12]_[wb]|attn_out_b|ffn2_b)$", P("pp", None, None)),
    (r"^(embed|pos|lnf_[wb]|moe_router)$", P()),
)

# Generic transformer-shaped nn.Layer trees (zoo BERT/Transformer
# naming, the same column/row split fleet.megatron_param_spec applies
# imperatively — expressed here as data so plans hash and diff).
TRANSFORMER_RULES = (
    (r"(qkv|q_proj|k_proj|v_proj|kv_proj|ffn1|fc1|linear1|intermediate)"
     r"[^.]*\.weight$", P(None, "tp")),
    (r"(qkv|q_proj|k_proj|v_proj|kv_proj|ffn1|fc1|linear1|intermediate)"
     r"[^.]*\.bias$", P("tp")),
    (r"(out|o_proj|out_proj|ffn2|fc2|linear2|output)[^.]*\.weight$",
     P("tp", None)),
)


def _as_spec(s):
    """Accept a PartitionSpec, a spec_to_lists form, or None."""
    if s is None:
        return P()
    if isinstance(s, P):
        return s
    from .layout import spec_from_lists
    return spec_from_lists(list(s))


# ---------------------------------------------------------------------------
# MeshPlan

class MeshPlan:
    """Ordered regex→PartitionSpec rules bound to a mesh.

    rules      — iterable of ``(pattern, spec)``; spec may be a
                 PartitionSpec or its spec_to_lists form. First match
                 (``re.search``) wins.
    mesh       — jax Mesh; defaults to ``collective.get_mesh()`` or a
                 pure-dp mesh over every visible device.
    default    — spec for unmatched non-scalar leaves (replicated).
    data_axes  — mesh axes that shard the batch dim of *inputs*
                 (``data_spec`` / ``shard_input``) and carry grad sync.
    """

    def __init__(self, rules, mesh=None, default=P(), data_axes=("dp",),
                 name="plan"):
        if mesh is None:
            mesh = _coll.get_mesh()
        if mesh is None:
            # pure-dp fallback over every visible device — built
            # directly (NOT via collective.make_mesh) so constructing a
            # plan never mutates the process-global registered mesh
            from jax.sharding import Mesh
            devs = np.asarray(jax.devices())
            mesh = Mesh(devs.reshape((devs.size,)), ("dp",))
        self.mesh = mesh
        self.name = name
        self.default = _as_spec(default)
        self.data_axes = tuple(data_axes)
        self.sizes = {str(n): int(s) for n, s in mesh.shape.items()}
        self.rules = tuple((str(pat), _as_spec(spec))
                           for pat, spec in (rules or ()))
        self._compiled = tuple((re.compile(pat), spec)
                               for pat, spec in self.rules)
        self._validate()
        # degradation ledger for the advisor: name -> elems replicated
        # instead of sharded (filled lazily as spec_for runs)
        self.degraded = {}

    # -- validation ---------------------------------------------------
    def _axes_of(self, spec):
        out = []
        for e in tuple(spec):
            if e is None:
                continue
            out.extend(e if isinstance(e, (tuple, list)) else (e,))
        return out

    def _validate(self):
        known = set(self.sizes)
        for pat, spec in self.rules + (("<default>", self.default),):
            for ax in self._axes_of(spec):
                if str(ax) not in known:
                    raise ValueError(
                        f"mesh_plan rule {pat!r} shards over axis "
                        f"{ax!r}, but the mesh only has axes "
                        f"{sorted(known)}")
        for ax in self.data_axes:
            if ax not in known:
                raise ValueError(
                    f"mesh_plan data axis {ax!r} not on mesh "
                    f"(axes {sorted(known)})")

    # -- rule matching ------------------------------------------------
    def match(self, name):
        """The raw rule spec for `name` (no shape adaptation), or the
        default. Scalars are handled by spec_for."""
        for rx, spec in self._compiled:
            if rx.search(name):
                return spec
        return self.default

    def spec_for(self, name, shape):
        """PartitionSpec for one leaf: first-match rule, trimmed and
        divisibility-adapted to `shape` (degradations warn once and
        count in layout.degraded + this plan's ledger)."""
        shape = tuple(shape or ())
        if len(shape) == 0:
            return P()
        lists = spec_to_lists(self.match(name), len(shape))
        spec, changed = adapt_spec(lists, shape, self.mesh, name=name)
        if changed:
            self.degraded[name] = int(np.prod(shape)) if shape else 1
        entries = list(tuple(spec))
        while entries and entries[-1] is None:  # canonical: P(None,)==P()
            entries.pop()
        return P(*entries)

    def annotate(self, named_shapes):
        """{name: shape-or-array} → {name: PartitionSpec} for the whole
        tree (params, optimizer slots, grad accumulators alike — slots
        share their param's name prefix so the same rules bind)."""
        out = {}
        for k, v in named_shapes.items():
            shape = v if isinstance(v, (tuple, list)) else np.shape(
                getattr(v, "data", v))
            out[k] = self.spec_for(k, shape)
        return out

    def as_spec_fn(self):
        """(name, shape) → spec callable, for fleet.shard_model."""
        return lambda name, shape: self.spec_for(name, shape)

    def place(self, name, value):
        """device_put one leaf under its planned spec (Tensor-aware)."""
        arr = getattr(value, "data", value)
        spec = self.spec_for(name, np.shape(arr))
        placed = jax.device_put(arr, NamedSharding(self.mesh, spec))
        if hasattr(value, "data"):
            value.data = placed
            return value
        return placed

    def place_model(self, model):
        """Shard every parameter (and replicate every buffer) of an
        nn.Layer tree in place. Unlike fleet.shard_model, an applied
        plan is authoritative: existing placements are overridden."""
        for name, prm in model.named_parameters():
            self.place(name, prm)
        for name, buf in model.named_buffers():
            if hasattr(buf, "data"):
                buf.data = jax.device_put(
                    buf.data, NamedSharding(self.mesh, P()))
        return model

    def place_optimizer(self, optimizer):
        """Place optimizer accumulator slots exactly like their params
        (call after place_model so params carry their planned
        sharding). Shape-matched slots inherit the param's sharding;
        scalar state (beta powers, step counts) is left alone."""
        params = list(getattr(optimizer, "_parameter_list", None) or [])
        acc = getattr(optimizer, "_accumulators", None) or {}
        for prm in params:
            arr = getattr(prm, "data", prm)
            sh = getattr(arr, "sharding", None)
            if sh is None:
                continue
            for _slot, t in acc.get(id(prm), {}).items():
                tarr = getattr(t, "data", None)
                if tarr is not None and np.shape(tarr) == np.shape(arr):
                    t.data = jax.device_put(tarr, sh)
        return optimizer

    # -- input/batch layout -------------------------------------------
    def dp_size(self):
        return int(np.prod([self.sizes.get(a, 1) for a in self.data_axes]))

    def data_spec(self, ndim):
        """Batch-dim sharding for inputs: leading dim over the data
        axes (those actually >1), rest replicated."""
        axes = tuple(a for a in self.data_axes if self.sizes.get(a, 1) > 1)
        if ndim == 0 or not axes:
            return P()
        lead = axes[0] if len(axes) == 1 else axes
        return P(*((lead,) + (None,) * (ndim - 1)))

    def shard_input(self, arr):
        """Place one input batch: leading dim split over the data axes
        when divisible, replicated otherwise (never an invalid layout)."""
        shape = np.shape(arr)
        dp = self.dp_size()
        if len(shape) == 0 or dp <= 1:
            return jax.device_put(arr, NamedSharding(self.mesh, P()))
        if shape[0] % dp == 0:
            spec = self.data_spec(len(shape))
        else:
            spec = P()
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    # -- arena / grad-sync contract -----------------------------------
    def arena_compatible(self, named_shapes):
        """The flat ParamArena packs leaves into ONE replicated buffer,
        so every planned leaf must be replicated on every axis of size
        > 1. Returns the first offending (name, spec) or None."""
        for k, v in named_shapes.items():
            shape = v if isinstance(v, (tuple, list)) else np.shape(
                getattr(v, "data", v))
            spec = self.spec_for(k, shape)
            for ax in self._axes_of(spec):
                if self.sizes.get(str(ax), 1) > 1:
                    return k, spec
        return None

    def grad_axis(self):
        """The axis grad sync reduces over (first data axis of size>1)."""
        for a in self.data_axes:
            if self.sizes.get(a, 1) > 1:
                return a
        return self.data_axes[0] if self.data_axes else "dp"

    # -- identity / cache keys ----------------------------------------
    def signature(self):
        """JSON-able identity: mesh topology + canonical rule set."""
        sig = dict(mesh_signature(self.mesh))
        # axis ORDER changes device placement, and json.dumps(sort_keys)
        # would erase it from the axes dict — record it explicitly
        sig["axis_order"] = list(self.sizes)
        return {
            "mesh": sig,
            "rules": [[pat, spec_to_lists(spec, len(tuple(spec)))]
                      for pat, spec in self.rules],
            "default": spec_to_lists(self.default,
                                     len(tuple(self.default))),
            "data_axes": list(self.data_axes),
        }

    def plan_key(self):
        """Short stable string for executable cache keys: switching the
        mesh OR the rule set changes it, so no stale reuse."""
        blob = json.dumps(self.signature(), sort_keys=True)
        h = hashlib.sha1(blob.encode()).hexdigest()[:12]
        axes = "x".join(f"{a}{s}" for a, s in sorted(self.sizes.items())
                        if s > 1) or "1dev"
        return f"plan:{axes}:{h}"

    def __repr__(self):
        return (f"MeshPlan({self.name!r}, {len(self.rules)} rules, "
                f"mesh={self.sizes}, key={self.plan_key()})")


def resolve(mesh_plan, mesh=None, default=P(), data_axes=("dp",), **auto_kw):
    """Coerce the user-facing ``mesh_plan=`` knob into a MeshPlan:
    None → None, MeshPlan → itself, "auto" → plan(auto=True),
    rule iterable → MeshPlan(rules)."""
    if mesh_plan is None:
        return None
    if isinstance(mesh_plan, MeshPlan):
        return mesh_plan
    if isinstance(mesh_plan, str):
        if mesh_plan == "auto":
            return plan(auto=True, mesh=mesh, **auto_kw)
        raise ValueError(f"mesh_plan string must be 'auto', "
                         f"got {mesh_plan!r}")
    return MeshPlan(mesh_plan, mesh=mesh, default=default,
                    data_axes=data_axes)


# ---------------------------------------------------------------------------
# advisor: candidate enumeration, cost model, ranking

def candidate_sizes(n_devices, axes=("dp", "tp")):
    """All complete factorizations of `n_devices` over `axes` (every
    device used; order = axes order). 8 devices over (dp, tp) →
    [{'dp': 8, 'tp': 1}, {'dp': 4, 'tp': 2}, {'dp': 2, 'tp': 4},
    {'dp': 1, 'tp': 8}]."""
    axes = tuple(axes)
    out = []

    def rec(i, rest, acc):
        if i == len(axes) - 1:
            out.append(dict(acc, **{axes[i]: rest}))
            return
        for d in range(1, rest + 1):
            if rest % d == 0:
                rec(i + 1, rest // d, dict(acc, **{axes[i]: d}))

    if n_devices < 1:
        return []
    rec(0, int(n_devices), {})
    return out


def link_bandwidth(link_gbps=None, ceilings=None):
    """Interconnect bandwidth (bytes/s) for the comm model. Priority:
    explicit arg → PADDLE_TPU_LINK_GBPS env → device-kind default
    (TPU ICI ~90 GB/s; CPU 'links' are host memcpys, ~8 GB/s)."""
    import os
    if link_gbps is not None:
        return float(link_gbps) * 1e9
    env = os.environ.get("PADDLE_TPU_LINK_GBPS")
    if env:
        return float(env) * 1e9
    plat = None
    try:
        plat = jax.devices()[0].platform
    except Exception:
        pass
    return 90e9 if plat == "tpu" else 8e9


def measure_link_bandwidth(mesh, axis, n_elems=1 << 22, repeats=3):
    """Measured link bandwidth: time a jitted psum of `n_elems` f32 over
    `axis` and divide the ring wire bytes by the best wall time. Returns
    bytes/s, or None when the axis has size 1 (nothing on the wire)."""
    sizes = {str(n): int(s) for n, s in mesh.shape.items()}
    n = sizes.get(axis, 1)
    if n <= 1:
        return None
    from .collective import shard_map_compat
    spec = P(axis)
    x = jax.device_put(np.ones((n_elems,), "f4"),
                       NamedSharding(mesh, spec))

    def dev(v):
        from jax import lax
        return lax.psum(v, axis)

    f = jax.jit(shard_map_compat(dev, mesh=mesh, in_specs=(spec,),
                                 out_specs=spec, check_vma=False))
    f(x).block_until_ready()  # compile
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    wire = 2.0 * (n - 1) / n * 4.0 * n_elems
    return wire / max(best, 1e-9)


def _ring_factor(n):
    return 2.0 * (n - 1) / n if n > 1 else 0.0


def score(stats, ceilings=None, link_gbps=None):
    """Per-layout step-time estimate from per-DEVICE stats:
    ``{"flops", "hbm_bytes", "comm": [(axis, payload_bytes, n_ranks)]}``
    → ``{"compute_s", "hbm_s", "comm_s", "pred_step_s", "bound"}``.
    Same roofline as monitor.profile (max of compute/memory ceilings),
    comm serialized on top (the planner scores what XLA may NOT
    overlap — the pessimistic bound orders layouts conservatively)."""
    if ceilings is None:
        from ..monitor import profile as _prof
        ceilings = _prof.roofline_ceilings()
    peak = float(ceilings["peak_flops"])
    hbm = float(ceilings["hbm_bytes_per_sec"])
    link = link_bandwidth(link_gbps)
    compute_s = float(stats.get("flops", 0)) / peak
    hbm_s = float(stats.get("hbm_bytes", 0)) / hbm
    comm_s = 0.0
    for _axis, payload, n in stats.get("comm", ()):
        comm_s += float(payload) * _ring_factor(int(n)) / link
    roof = max(compute_s, hbm_s)
    return {
        "compute_s": compute_s, "hbm_s": hbm_s, "comm_s": comm_s,
        "pred_step_s": roof + comm_s,
        "bound": ("comm" if comm_s > roof else
                  "compute" if compute_s >= hbm_s else "memory"),
    }


def megatron_candidate_stats(cfg, sizes, global_batch=None):
    """Analytic per-device stats for one MegatronConfig on one mesh
    factorization — the advisor input when there is no profile yet.
    `global_batch` is candidate-independent (defaults to
    cfg.microbatch, read as the GLOBAL batch so candidates stay
    comparable). pp>1 changes the model itself in this trainer
    (stage-stacked params), so candidates should vary dp/tp/sp only."""
    full = {"dp": 1, "pp": 1, "tp": 1, "sp": 1, "ep": 1}
    full.update(sizes)
    dp, tp, sp = full["dp"], full["tp"], full["sp"]
    h, V = cfg.hidden, cfg.vocab_size
    ffn = h * cfg.ffn_mult
    L = cfg.layers_per_stage * full["pp"]
    B = int(global_batch if global_batch is not None else cfg.microbatch)
    tokens_g = cfg.n_micro * B * cfg.seq_len
    tokens_dev = tokens_g / max(dp * sp, 1)

    # tp divisibility: heads carry qkv/attn_out, ffn carries ffn1/ffn2.
    # A non-divisible split degrades to replicated — full per-device
    # work and full-size grads (the layout.degraded penalty, priced in).
    heads_split = tp if cfg.n_heads % tp == 0 else 1
    ffn_split = tp if ffn % tp == 0 else 1
    attn_mm = L * 4 * h * h            # qkv (3h·h) + attn_out (h·h)
    ffn_mm = L * 2 * h * ffn           # ffn1 + ffn2
    embed_mm = V * h                   # logits matmul, replicated on tp
    mm_local = (attn_mm / heads_split + ffn_mm / ffn_split + embed_mm)
    flops = 6.0 * tokens_dev * mm_local
    # attention scores/context: 4·tokens·s_ctx·h fwd, ×3 with backward
    flops += 12.0 * tokens_dev * cfg.seq_len * h / heads_split
    # HBM bytes: every matmul operand + activation streamed ~3× (fwd,
    # grad, residual re-read) at f32
    param_local = (attn_mm / heads_split + ffn_mm / ffn_split
                   + embed_mm + cfg.seq_len * h)
    hbm = 4.0 * (3.0 * param_local + 12.0 * tokens_dev * h * L
                 / max(1, 1))  # activations don't split over tp (f/g)
    hbm = float(hbm)

    comm = []
    # dp grad sync: replicated params at full size (embed/pos/lns/
    # biases + any degraded split) + sharded locals, wire-priced in the
    # grad_sync mode's format
    from .overlap import wire_bytes
    grad_elems = (attn_mm / heads_split + ffn_mm / ffn_split
                  + embed_mm + cfg.seq_len * h + 10 * L * h)
    mode = cfg.grad_sync
    if getattr(cfg, "quantized_grad_allreduce", False) and mode == "exact":
        mode = "quantized"
    if dp > 1:
        comm.append(("dp", float(wire_bytes(int(grad_elems), mode,
                                            bits=cfg.grad_bits,
                                            n_ranks=dp)), dp))
    # tp activation psums: f/g pair per block sub-layer → 2 fwd + 2 bwd
    # psums per block, each tokens_dev·h f32
    if tp > 1:
        comm.append(("tp", 4.0 * L * tokens_dev * h * 4.0, tp))
    # sp ring attention: k,v ride the ring once per block per direction
    if sp > 1:
        comm.append(("sp", 4.0 * L * tokens_dev * h * 4.0
                     / max(heads_split, 1), sp))
    degraded = (heads_split == 1 and tp > 1) or (ffn_split == 1 and tp > 1)

    # predicted peak HBM residency per device (the pre-flight budget):
    # training state = params + grads + 2 Adam slots (4× param bytes),
    # plus the backward's saved activations (residual + ffn streams per
    # block) and the replicated logits buffer — f32 throughout. An
    # ordering model, same honesty contract as the flops/bytes halves.
    state_elems = 4.0 * param_local
    act_elems = L * tokens_dev * (2.0 * h + ffn / ffn_split)
    logits_elems = tokens_dev * V
    peak_hbm = 4.0 * (state_elems + act_elems + logits_elems)
    return {"flops": float(flops), "hbm_bytes": hbm, "comm": comm,
            "degraded_frac": 1.0 if degraded else 0.0,
            "peak_hbm_bytes": float(peak_hbm),
            # decomposition for the memory-policy advisory columns:
            # activations are what remat removes, the two Adam slots
            # (half the training state) are what offload removes
            "peak_act_bytes": 4.0 * float(act_elems),
            "peak_opt_bytes": 4.0 * float(2.0 * param_local)}


def stats_from_profile(sizes, report=None, param_elems=0,
                       grad_mode="exact", grad_bits=8,
                       data_axes=("dp",), model_axes=("tp",)):
    """Advisor input from the measured roofline ledger: take
    monitor.profile's attributed per-region flops/bytes (captured on
    the CURRENT layout, totalled) and rescale to a candidate mesh —
    compute/memory divide across all axes, grad traffic rides the data
    axes at ``param_elems / model-split`` wire bytes."""
    if report is None:
        from ..monitor import profile as _prof
        report = _prof.last_report()
    if not report:
        raise ValueError(
            "stats_from_profile needs a monitor.profile report — run a "
            "profiled step first (monitor.profile.enable()) or pass "
            "report=")
    flops = sum(float(r.get("flops", 0)) for r in report["regions"])
    nbytes = sum(float(r.get("bytes", 0)) for r in report["regions"])
    n = int(np.prod([max(1, int(v)) for v in sizes.values()]))
    model_split = int(np.prod([max(1, int(sizes.get(a, 1)))
                               for a in model_axes]))
    dp = int(np.prod([max(1, int(sizes.get(a, 1))) for a in data_axes]))
    comm = []
    if dp > 1 and param_elems:
        from .overlap import wire_bytes
        comm.append((data_axes[0],
                     float(wire_bytes(int(param_elems // model_split),
                                      grad_mode, bits=grad_bits,
                                      n_ranks=dp)), dp))
    # peak residency from the measured liveness model when one exists:
    # state bytes (params/opt slots) divide over the model axes, the
    # activation/temp working set over the data axes
    peak_hbm = act_bytes = opt_bytes = None
    try:
        from ..monitor import memory as _mem
        mrep = _mem.last_report()
        if mrep:
            bc = mrep.get("by_class", {})
            state = float(bc.get("param", 0) + bc.get("opt_state", 0))
            work = float(bc.get("activation", 0) + bc.get("remat", 0)
                         + bc.get("temp", 0))
            peak_hbm = state / model_split + work / max(dp, 1)
            act_bytes = work / max(dp, 1)
            opt_bytes = float(bc.get("opt_state", 0)) / model_split
    except Exception:
        peak_hbm = None
    return {"flops": flops / n, "hbm_bytes": nbytes / n, "comm": comm,
            "degraded_frac": 0.0, "peak_hbm_bytes": peak_hbm,
            "peak_act_bytes": act_bytes, "peak_opt_bytes": opt_bytes}


def advise(n_devices=None, cfg=None, candidates=None, axes=("dp", "tp"),
           global_batch=None, report=None, param_elems=0,
           ceilings=None, link_gbps=None, timeshared=None,
           hbm_limit=None):
    """Ranked layout table, best first. Each row:
    ``{rank, sizes, pred_step_s, compute_s, hbm_s, comm_s, bound,
    degraded_frac, peak_hbm_bytes, feasible, remat, offload,
    mem_overhead_s}`` — the last three are ADVISORY memory-policy
    columns (the cheapest memory_plan ladder rung that would fit the
    candidate under the HBM budget and its predicted overhead; "none"/
    False/0.0 when it already fits). Deterministic: ties
    break on degradation then on the sizes dict, so repeated calls are
    rank-stable.

    The pre-flight HBM budget (ROADMAP item 4): each candidate carries
    its predicted per-device peak residency, and a candidate whose peak
    exceeds ``hbm_limit`` (default: ``monitor.memory.device_hbm_limit()``
    — env override, live ``bytes_limit``, or the device-kind capacity
    table) is marked ``feasible: False`` and ranked BELOW every feasible
    layout regardless of its predicted step time — a layout that OOMs
    has no step time. With no limit (CPU, unknown device) and no
    override, everything stays feasible: no invented verdicts.

    ``timeshared`` (default: auto-true on CPU): the "devices" are
    virtual shards of one host, so per-device work does NOT run
    concurrently — wall clock follows TOTAL work. Stats are scaled by
    the device count and priced at honest host throughput
    ($PADDLE_TPU_HOST_GFLOPS, default 10) instead of the assumed-TPU
    ceilings, so a CPU rehearsal ranks layouts the way the CPU actually
    runs them (the plan_smoke A/B gate). On real TPU meshes this is
    off and the per-device roofline applies unchanged."""
    if n_devices is None:
        n_devices = len(jax.devices())
    if candidates is None:
        candidates = candidate_sizes(n_devices, axes)
    if not candidates:
        return []
    if timeshared is None:
        try:
            timeshared = jax.devices()[0].platform == "cpu"
        except Exception:
            timeshared = False
    if timeshared and ceilings is None:
        import os
        gf = float(os.environ.get("PADDLE_TPU_HOST_GFLOPS", "10"))
        ceilings = {"peak_flops": gf * 1e9,
                    "hbm_bytes_per_sec": 2.0 * gf * 1e9,
                    "device_kind": "timeshared-host", "assumed": True}
    if hbm_limit is None:
        try:
            from ..monitor import memory as _mem
            hbm_limit = _mem.device_hbm_limit()
        except Exception:
            hbm_limit = None
    rows = []
    for sizes in candidates:
        if cfg is not None:
            stats = megatron_candidate_stats(cfg, sizes,
                                             global_batch=global_batch)
        else:
            stats = stats_from_profile(sizes, report=report,
                                       param_elems=param_elems)
        if timeshared:
            n = int(np.prod([max(1, int(v)) for v in sizes.values()]))
            stats = dict(stats, flops=stats["flops"] * n,
                         hbm_bytes=stats["hbm_bytes"] * n)
        row = score(stats, ceilings=ceilings, link_gbps=link_gbps)
        row["sizes"] = dict(sizes)
        row["degraded_frac"] = float(stats.get("degraded_frac", 0.0))
        peak = stats.get("peak_hbm_bytes")
        row["peak_hbm_bytes"] = (float(peak) if peak is not None
                                 else None)
        row["hbm_limit_bytes"] = hbm_limit
        row["feasible"] = not (hbm_limit is not None
                               and peak is not None
                               and peak > hbm_limit)
        row["remat"], row["offload"], row["mem_overhead_s"] = \
            _mem_advice(row, stats, hbm_limit)
        rows.append(row)
    rows.sort(key=lambda r: (0 if r["feasible"] else 1,
                             round(r["pred_step_s"], 15),
                             r["degraded_frac"],
                             json.dumps(r["sizes"], sort_keys=True)))
    for i, r in enumerate(rows):
        r["rank"] = i + 1
    return rows


def _mem_advice(row, stats, hbm_limit):
    """Advisory memory-policy columns for an advise() row: the cheapest
    memory_plan ladder rung (none → dots-remat → full-remat → +offload)
    that would bring this candidate's predicted peak under the budget,
    plus its predicted step-time overhead. Purely informational —
    ``feasible`` and the ranking still describe the layout AS-IS;
    enacting the suggestion is fit(memory=)/plan_memory()'s job."""
    peak = row.get("peak_hbm_bytes")
    if peak is None or hbm_limit is None or peak <= hbm_limit:
        return "none", False, 0.0
    act = float(stats.get("peak_act_bytes") or 0.0)
    opt = float(stats.get("peak_opt_bytes") or 0.0)
    # fwd ≈ 1/3 of the fwd+bwd flop time already priced into the row
    fwd_s = float(row.get("compute_s", 0.0)) / 3.0
    from ..memory_plan import host_link_bandwidth
    link = host_link_bandwidth()
    ladder = (("dots", peak - 0.5 * act, False, 0.25 * fwd_s),
              ("full", peak - 0.9 * act, False, fwd_s),
              ("full", peak - 0.9 * act - opt, True,
               fwd_s + (2.0 * opt / link if link else 0.0)))
    for name, p2, off, over in ladder:
        if p2 <= hbm_limit:
            return name, off, float(over)
    # even the deepest rung stays over budget: report it anyway so the
    # row shows how close the best effort gets
    name, _, off, over = ladder[-1]
    return name, off, float(over)


# ---------------------------------------------------------------------------
# plan() — the one entry point — and the monitor ledger hook

_last_decision = None


def last_decision():
    """The most recent plan()/advise() decision (the /snapshot block)."""
    return _last_decision


def _record(p, table, auto):
    global _last_decision
    from .. import monitor as _monitor
    _monitor.counter("planner.plan").inc()
    if auto:
        _monitor.counter("planner.auto_pick").inc()
    n_cand = len(table) if table else 0
    _monitor.gauge("planner.candidates").set(n_cand)
    winner = table[0] if table else None
    if winner is not None:
        _monitor.gauge("planner.predicted_step_s").set(
            winner["pred_step_s"])
    hotspot = None
    try:
        from ..monitor import profile as _prof
        hs = _prof.last_summary(top_k=1)
        if hs and hs.get("hotspots"):
            hotspot = hs["hotspots"][0].get("region")
    except Exception:
        hotspot = None
    decision = {
        "ts": time.time(),
        "plan": p.plan_key(),
        "name": p.name,
        "mesh": p.sizes,
        "auto": bool(auto),
        "n_rules": len(p.rules),
        "candidates": n_cand,
        "chosen": dict(winner["sizes"]) if winner else dict(p.sizes),
        "predicted_step_s": (winner["pred_step_s"] if winner else None),
        "bound": winner["bound"] if winner else None,
        "peak_hbm_bytes": (winner.get("peak_hbm_bytes")
                           if winner else None),
        "hbm_limit_bytes": (winner.get("hbm_limit_bytes")
                            if winner else None),
        "infeasible": sum(1 for r in (table or [])
                          if not r.get("feasible", True)),
        "degraded": dict(p.degraded),
        # cross-link: the hotspot the profiler currently blames most —
        # grep the JSONL for this region to see what the layout choice
        # was reacting to
        "hotspot": hotspot,
        "table": [{k: r.get(k) for k in
                   ("rank", "sizes", "pred_step_s", "bound",
                    "degraded_frac", "peak_hbm_bytes", "feasible")}
                  for r in (table or [])[:8]],
    }
    _last_decision = decision
    if _monitor.enabled():
        _monitor.emit(kind="planner", **{
            k: v for k, v in decision.items() if k not in ("ts",)})
    return decision


def plan(rules=None, mesh=None, auto=False, cfg=None, n_devices=None,
         axes=("dp", "tp"), default=P(), data_axes=("dp",), name=None,
         record=True, **advise_kw):
    """THE entry point: build a MeshPlan, optionally letting the
    advisor pick the mesh.

    Manual: ``plan(rules, mesh=...)`` binds a rule set to a mesh.
    Auto:   ``plan(auto=True, cfg=megatron_cfg)`` (or with a profile
    report) ranks every factorization of the device count over `axes`,
    builds the winner's mesh, binds `rules` (MEGATRON_RULES when a cfg
    is given, TRANSFORMER_RULES otherwise) and records the decision in
    the monitor ledger. The returned plan carries the ranked table as
    ``.advice``."""
    if auto:
        table = advise(n_devices=n_devices, cfg=cfg, axes=axes,
                       **advise_kw)
        if not table:
            raise ValueError("advisor produced no candidate layouts")
        winner_row = next((r for r in table if r.get("feasible", True)),
                          None)
        if winner_row is None:
            lim = table[0].get("hbm_limit_bytes")
            raise ValueError(
                "advisor: every candidate layout exceeds the device "
                f"HBM budget ({lim and int(lim)} bytes) — shrink the "
                "model/batch, add devices, or raise "
                "PADDLE_TPU_HBM_LIMIT_BYTES")
        winner = winner_row["sizes"]
        if mesh is None:
            if cfg is not None:
                from .megatron import make_mesh as _mk
                mesh, _ = _mk(n_devices or len(jax.devices()),
                              sizes=winner)
            else:
                # keep size-1 axes on the mesh: rules that name them
                # stay valid (and harmless) instead of erroring
                mesh = _coll.make_mesh(
                    {a: int(s) for a, s in winner.items()})
        if rules is None:
            rules = MEGATRON_RULES if cfg is not None else \
                TRANSFORMER_RULES
        p = MeshPlan(rules, mesh=mesh, default=default,
                     data_axes=data_axes, name=name or "auto")
        p.advice = table
        if record:
            _record(p, table, auto=True)
        return p
    if rules is None:
        raise ValueError("plan() needs rules (or auto=True)")
    p = MeshPlan(rules, mesh=mesh, default=default, data_axes=data_axes,
                 name=name or "manual")
    p.advice = None
    if record:
        _record(p, None, auto=False)
    return p
