"""paddle_tpu.parallel.overlap — bucketed, overlapped, quantized grad sync.

The data-parallel gradient exchange as a *scheduled* communication plan
instead of one monolithic all-reduce at the end of backward (reference
analogue: the NCCL fused-allreduce + DGC bandwidth levers in
python/paddle/fluid/dygraph/parallel.py; direction per EQuARX,
arxiv 2506.17615, and fused computation-collectives, arxiv 2305.06942):

* :func:`plan_buckets` — order-preserving, size-bounded bucketing of a
  flat grad pytree (the same pad-to-a-small-bucket-set discipline as
  ``io.bucketing``, so bucket executables are reused, not re-minted).
* :func:`sync_tree` — the *in-SPMD* bucketed reduce for shard_map
  trainers (megatron): every bucket is one flat f32 vector reduced with
  ``lax.pmean``/``psum`` or the quantized ring
  (``collective.all_reduce_quantized``, int8 or packed-int4 wire).
* :class:`GradSyncScheduler` — the *host-level* scheduler for explicit
  DDP loops over stacked per-rank grads (``[n_dp, ...]`` leaves from
  :func:`local_value_and_grad`). Bucket reduces are jitted shard_map
  executables; in ``overlap`` mode they run on a dedicated comm-worker
  thread (XLA executions release the GIL, so they genuinely overlap the
  main thread's backward compute — observed as a separate
  ``comm.bucket_reduce`` track in the Chrome trace, not inferred), and
  ``async_apply`` (lag-1, mirroring the Executor's ``async_fetch``)
  lets step N apply the synced grads of step N-1 so almost no wire time
  stays on the critical path.

Exposed wire time is *measured*: every second the caller spends blocked
on an unfinished reduce lands in ``scheduler.exposed_wait_s`` and the
``comm.exposed_wait_s`` histogram; ``comm.bytes_wire`` vs
``comm.bytes_logical`` records what quantization saved. bench.py's
``collective_overlap`` stage and ``scripts/comm_smoke.py`` gate on
both.

Mode knob (one string everywhere — DataParallel, MegatronConfig,
Optimizer, hapi/static entry points):

* ``"exact"``      — discrete f32 reduce on the caller's thread (the
  baseline whose wire time is fully exposed).
* ``"quantized"``  — same schedule, int8/int4 ring wire (``bits=``).
* ``"overlap"``    — bucket reduces launched on the comm worker as soon
  as each bucket's grads exist; implies lag-1 ``async_apply`` unless
  explicitly disabled. Inside a single shard_map region (``sync_tree``)
  "overlap" means *bucketed* issue — XLA's scheduler interleaves the
  independent per-bucket collectives with remaining compute; host-side
  lag-1 does not apply there.

Checkpoint discipline: ``state_dict()`` serialises the lag-1 pending
synced grads (materialised, NOT flushed), so a restore resumes
bit-identically with an uninterrupted run — comm_smoke gates on this.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .collective import (all_reduce_quantized, axis_size, get_mesh,
                         shard_map_compat)
from ..io.bucketing import next_bucket
from .. import monitor as _monitor
from ..monitor import trace as _trace

__all__ = [
    "MODES", "SUPPORTED_BITS", "plan_buckets", "wire_bytes", "sync_tree",
    "sync_arena_flat", "local_value_and_grad", "GradSyncScheduler",
]

MODES = ("exact", "quantized", "overlap")
SUPPORTED_BITS = (4, 8)

# default bucket: 4 MiB of f32 grads — small enough that several buckets
# exist for bench-scale models, large enough to amortise dispatch
DEFAULT_BUCKET_BYTES = 4 << 20


def _check_mode(mode):
    if mode not in MODES:
        raise ValueError(
            f"grad_sync mode {mode!r} unknown; supported: {MODES}")
    return mode


def plan_buckets(sizes, bucket_bytes=DEFAULT_BUCKET_BYTES, itemsize=4):
    """Greedy, order-preserving bucketing: ``sizes`` are per-leaf
    element counts; returns a list of index lists, each bucket's total
    payload ≤ ``bucket_bytes`` (a single oversized leaf gets its own
    bucket). Order is preserved so buckets fill in the order backward
    produces grads — the property overlap relies on."""
    cap = max(int(bucket_bytes) // int(itemsize), 1)
    buckets, cur, cur_n = [], [], 0
    for i, sz in enumerate(sizes):
        sz = int(sz)
        if cur and cur_n + sz > cap:
            buckets.append(cur)
            cur, cur_n = [], 0
        cur.append(i)
        cur_n += sz
    if cur:
        buckets.append(cur)
    return buckets


def wire_bytes(n_elems, mode, bits=8, n_ranks=2):
    """Bytes of the wire *representation* of an ``n_elems`` f32 bucket
    payload: f32 for exact/overlap, ``bits``-wide ints plus the per-hop
    f32 scales for quantized (2·(n−1) transmitted chunk scales per
    rank). Representation size, not total link traffic — the comparable
    figure ``comm.bytes_logical`` records is the same payload at f32."""
    n_elems = int(n_elems)
    if mode != "quantized":
        return 4 * n_elems
    payload = (n_elems * int(bits) + 7) // 8
    return payload + 4 * max(2 * (int(n_ranks) - 1), 1)


def _account(mode, bits, n_ranks, logical_elems, n_buckets,
             wire=None):
    if not _monitor.enabled():
        return
    logical = 4 * int(logical_elems)
    wb = wire_bytes(logical_elems, wire or mode, bits, n_ranks)
    _monitor.counter("comm.bytes_logical").inc(logical)
    _monitor.counter("comm.bytes_wire").inc(wb)
    _monitor.counter("comm.buckets").inc(int(n_buckets))
    _monitor.counter(f"comm.sync.{mode}").inc()


# ---------------------------------------------------------------------------
# in-SPMD bucketed reduce (megatron / any shard_map trainer)

def _reduce_flat(flat, axis_name, mode, bits, op):
    if mode == "quantized":
        return all_reduce_quantized(flat, axis_name, bits=bits, op=op)
    return (lax.pmean if op == "mean" else lax.psum)(flat, axis_name)


def sync_tree(tree, axis_name="dp", mode="exact", bits=8,
              bucket_bytes=DEFAULT_BUCKET_BYTES, op="mean",
              extra_mean_axes=()):
    """Bucketed gradient sync *inside* a shard_map region: flatten the
    pytree, concatenate leaves into size-bounded f32 buckets (padded to
    the ``io.bucketing`` power-of-two set so bucket shapes stay in a
    small family), reduce each bucket over ``axis_name`` (exact psum /
    pmean, or the quantized ring for ``mode="quantized"``), then mean
    over any ``extra_mean_axes`` (megatron's sp). ``mode="overlap"``
    here means bucketed issue — the per-bucket collectives are
    independent, so XLA is free to interleave them with remaining
    compute. Returns the tree with every leaf reduced, original dtypes
    restored."""
    _check_mode(mode)
    if mode == "quantized" and bits not in SUPPORTED_BITS:
        raise ValueError(
            f"quantized wire width {bits} unsupported; "
            f"supported: {SUPPORTED_BITS}")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    sizes = [int(np.prod(l.shape)) if l.shape else 1 for l in leaves]
    plan = plan_buckets(sizes, bucket_bytes)
    try:
        n_ranks = axis_size(axis_name)
    except Exception:
        n_ranks = 1
    _account(mode, bits, n_ranks, sum(sizes), len(plan))
    out = [None] * len(leaves)
    for idxs in plan:
        flat = jnp.concatenate(
            [leaves[i].reshape(-1).astype(jnp.float32) for i in idxs])
        size = flat.shape[0]
        padded = next_bucket(size)
        if padded > size:
            flat = jnp.pad(flat, (0, padded - size))
        red = _reduce_flat(flat, axis_name, mode, bits, op)
        for ax in extra_mean_axes:
            red = lax.pmean(red, ax)
        off = 0
        for i in idxs:
            out[i] = red[off:off + sizes[i]] \
                .reshape(leaves[i].shape).astype(leaves[i].dtype)
            off += sizes[i]
    return jax.tree_util.tree_unflatten(treedef, out)


def sync_arena_flat(flat, bounds, axis_name="dp", mode="exact", bits=8,
                    op="mean"):
    """Bucketed reduce over a flat-arena gradient buffer *inside* a
    shard_map region: ``bounds`` is the arena's contiguous-slice bucket
    plan (``ParamArena.bucket_bounds()[tag]``), so every bucket is a
    static slice of ``flat`` — the per-leaf gather ``sync_tree`` pays is
    replaced by pure offsets, and the reassembly is one ordered concat
    XLA fuses with the downstream flat optimizer update. Padding to the
    ``io.bucketing`` size family keeps the quantized ring's executable
    reuse."""
    _check_mode(mode)
    if mode == "quantized" and bits not in SUPPORTED_BITS:
        raise ValueError(
            f"quantized wire width {bits} unsupported; "
            f"supported: {SUPPORTED_BITS}")
    try:
        n_ranks = axis_size(axis_name)
    except Exception:
        n_ranks = 1
    total = int(flat.shape[0])
    _account(mode, bits, n_ranks, total, len(bounds))
    orig = flat.dtype
    pieces = []
    for start, stop in bounds:
        seg = flat[start:stop].astype(jnp.float32)
        size = stop - start
        padded = next_bucket(size)
        if padded > size:
            seg = jnp.pad(seg, (0, padded - size))
        red = _reduce_flat(seg, axis_name, mode, bits, op)
        pieces.append(red[:size].astype(orig))
    return jnp.concatenate(pieces) if len(pieces) > 1 else pieces[0]


# ---------------------------------------------------------------------------
# host-level scheduler over stacked per-rank grads

def local_value_and_grad(loss_fn, mesh=None, axis_name="dp"):
    """Per-rank loss/grads for explicit-DDP loops: returns a jitted
    ``f(params, batch) -> (loss [n], grads)`` where every grad leaf is
    stacked ``[n, *param_shape]`` — one UNREDUCED row per ``axis_name``
    rank (params replicated, batch sharded on its leading dim). Feed
    the grads to :meth:`GradSyncScheduler.reduce`. Without a mesh the
    eager fallback returns the same shapes with n=1."""
    mesh = mesh or get_mesh()

    def _local(params, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        return (jnp.asarray(loss, jnp.float32)[None],
                jax.tree_util.tree_map(lambda g: g[None], grads))

    if mesh is None:
        return _local
    sm = shard_map_compat(
        _local, mesh,
        in_specs=(P(), P(axis_name)),
        out_specs=(P(axis_name), P(axis_name)),
        check_vma=False)
    return jax.jit(sm)


class GradSyncScheduler:
    """Bucketed gradient-sync scheduler (see module docstring).

    Two integration surfaces:

    * :meth:`reduce` — stacked per-rank grads (``[n_dp, ...]`` leaves)
      from an explicit-DDP loop; buckets are reduced by jitted
      shard_map executables, on the comm-worker thread in ``overlap``
      mode, with lag-1 ``async_apply`` returning the *previous* step's
      synced tree (``None`` on the warm-up step — skip the apply).
    * :meth:`process` — ``Optimizer.step`` hook over eager
      ``(param, grad)`` pairs. Under GSPMD those grads arrive already
      reduced, so here the knob contributes lag-1 apply pipelining and
      ``comm.*`` accounting; the wire-level effects live in
      :meth:`reduce` / :func:`sync_tree`. Inside a traced step
      (jit.to_static) lag staging would leak tracers, so it passes
      through unchanged.
    """

    def __init__(self, mode="overlap", mesh=None, axis_name="dp",
                 bits=8, bucket_bytes=DEFAULT_BUCKET_BYTES,
                 async_apply=None, op="mean", quantized=None, plan=None):
        _check_mode(mode)
        if plan is not None:
            # a parallel.planner.MeshPlan supplies the mesh and the
            # grad-sync axis, so the scheduler reduces over exactly the
            # axis the plan shards batches on
            mesh = mesh if mesh is not None else plan.mesh
            axis_name = plan.grad_axis()
        if bits not in SUPPORTED_BITS:
            raise ValueError(
                f"quantized wire width {bits} unsupported; "
                f"supported: {SUPPORTED_BITS}")
        self.mode = mode
        self.bits = int(bits)
        # the wire format is orthogonal to scheduling: "quantized" mode
        # implies it, and overlap mode can opt in (quantized=True) to
        # run int8/int4 ring reduces on the comm worker
        self.quantized = (mode == "quantized") if quantized is None \
            else bool(quantized)
        self.op = op
        self.bucket_bytes = int(bucket_bytes)
        self.axis_name = axis_name
        self._mesh = mesh
        self.async_apply = (mode == "overlap") if async_apply is None \
            else bool(async_apply)
        self.steps = 0
        self.exposed_wait_s = 0.0
        self.last_plan = None   # bucket plan of the newest reduce()
        self._pool = None
        self._fn_cache = {}      # bucket signature -> jitted reduce
        self._plan_cache = {}    # leaves signature -> bucket plan
        self._pending = None     # (treedef, launches, n_leaves)
        self._restored = None    # leaves restored from a checkpoint
        self._pending_pg = None  # lag-1 state for process()
        self._lock = threading.Lock()

    # -- infrastructure ----------------------------------------------------
    @property
    def compiled_buckets(self):
        """Distinct bucket-reduce executables minted so far (the
        comm_smoke zero-extra-recompiles gate reads this)."""
        return len(self._fn_cache)

    def _mesh_now(self):
        return self._mesh or get_mesh()

    def _worker(self):
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="comm-worker")
        return self._pool

    def _plan(self, leaves):
        key = tuple((tuple(l.shape), str(jnp.result_type(l)))
                    for l in leaves)
        plan = self._plan_cache.get(key)
        if plan is None:
            # per-rank payload: leaves are stacked [n, ...]
            sizes = [int(np.prod(l.shape[1:])) if l.ndim > 1 else 1
                     for l in leaves]
            plan = plan_buckets(sizes, self.bucket_bytes)
            self._plan_cache[key] = plan
        return plan

    def _bucket_fn(self, bucket_leaves, mesh):
        shapes = tuple(tuple(l.shape[1:]) for l in bucket_leaves)
        dtypes = tuple(str(jnp.result_type(l)) for l in bucket_leaves)
        n = int(mesh.shape[self.axis_name]) if mesh is not None and \
            self.axis_name in getattr(mesh, "shape", {}) else 1
        wire = "quantized" if self.quantized else "exact"
        key = (shapes, dtypes, n, wire, self.bits, self.op)
        fn = self._fn_cache.get(key)
        if fn is not None:
            return fn
        sizes = [max(int(np.prod(s)), 1) for s in shapes]
        total = sum(sizes)
        padded = next_bucket(total)
        bits, op, axis = self.bits, self.op, self.axis_name

        def _unpack(red):
            out, off = [], 0
            for s, sz, dt in zip(shapes, sizes, dtypes):
                out.append(red[off:off + sz].reshape(s).astype(dt))
                off += sz
            return tuple(out)

        if mesh is None or n == 1:
            # eager fallback: the stacking axis IS the reduce axis
            def host_fn(*stacked):
                rfn = jnp.mean if op == "mean" else jnp.sum
                flat = jnp.concatenate(
                    [rfn(x.astype(jnp.float32), axis=0).reshape(-1)
                     for x in stacked])
                return _unpack(jnp.pad(flat, (0, padded - total)))
            fn = jax.jit(host_fn)
        else:
            def device_fn(*locals_):
                flat = jnp.concatenate(
                    [x.reshape(-1).astype(jnp.float32) for x in locals_])
                flat = jnp.pad(flat, (0, padded - total))
                return _unpack(_reduce_flat(flat, axis, wire, bits, op))

            fn = jax.jit(shard_map_compat(
                device_fn, mesh,
                in_specs=P(self.axis_name),
                out_specs=P(),
                check_vma=False))
        self._fn_cache[key] = fn
        if _monitor.enabled():
            _monitor.counter("comm.bucket_compile").inc()
        return fn

    # -- stacked-grad path (explicit DDP) ----------------------------------
    def reduce(self, grads):
        """Sync a stacked-grad pytree. Returns the synced tree with the
        rank axis reduced away — or, with ``async_apply``, the
        *previous* call's synced tree (``None`` on the first call)."""
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        if not leaves:
            return grads if not self.async_apply else None
        mesh = self._mesh_now()
        n = int(mesh.shape[self.axis_name]) if mesh is not None and \
            self.axis_name in getattr(mesh, "shape", {}) else 1
        plan = self._plan(leaves)
        self.last_plan = plan
        per_rank = sum(int(np.prod(l.shape[1:])) if l.ndim > 1 else 1
                       for l in leaves)
        _account(self.mode, self.bits, max(n, 2), per_rank, len(plan),
                 wire="quantized" if self.quantized else "exact")
        use_worker = self.mode == "overlap" or self.async_apply
        launches = []
        for b_id, idxs in enumerate(plan):
            bucket = [leaves[i] for i in idxs]
            fn = self._bucket_fn(bucket, mesh)
            nbytes = 4 * sum(int(np.prod(l.shape[1:])) if l.ndim > 1
                             else 1 for l in bucket)
            if use_worker:
                fut = self._worker().submit(
                    self._run_bucket, fn, bucket, b_id, nbytes)
                launches.append((idxs, fut))
            else:
                t0 = time.perf_counter()
                res = self._run_bucket(fn, bucket, b_id, nbytes)
                self._note_exposed(time.perf_counter() - t0)
                launches.append((idxs, res))
        self.steps += 1
        if not self.async_apply:
            return self._collect((treedef, launches, len(leaves)))
        prev, self._pending = self._pending, (treedef, launches,
                                              len(leaves))
        if self._restored is not None:
            # lag-1 state carried through a checkpoint: the restored
            # synced grads are this step's apply, bit-identical to the
            # uninterrupted run
            restored, self._restored = self._restored, None
            return jax.tree_util.tree_unflatten(treedef, restored)
        if prev is None:
            if _monitor.enabled():
                _monitor.counter("comm.lag_warmup").inc()
            return None
        return self._collect(prev)

    def reduce_arena(self, stacked, bounds):
        """Arena path for explicit-DDP loops: ``stacked`` is ONE
        ``[n_dp, total]`` flat gradient buffer in arena layout;
        ``bounds`` its contiguous-slice bucket plan. Each bucket is a
        cheap contiguous slice (no per-leaf gather) fed through the
        standard launch/overlap/lag-1 machinery; returns the synced flat
        buffer (or None on the lag-1 warm-up step)."""
        segs = [stacked[:, a:b] for a, b in bounds]
        out = self.reduce(segs)
        if out is None:
            return None
        return jnp.concatenate(out) if len(out) > 1 else out[0]

    def _run_bucket(self, fn, bucket, b_id, nbytes):
        with _trace.span("comm.bucket_reduce", bucket=b_id,
                         bytes=nbytes, mode=self.mode):
            out = fn(*bucket)
            jax.block_until_ready(out)
        if _monitor.enabled():
            _monitor.counter("comm.reduce_launch").inc()
        return out

    def _collect(self, pending, count_exposed=True):
        treedef, launches, n_leaves = pending
        out = [None] * n_leaves
        t0 = time.perf_counter()
        with _trace.span("comm.wait", mode=self.mode):
            for idxs, item in launches:
                res = item.result() if isinstance(item, Future) else item
                for k, i in enumerate(idxs):
                    out[i] = res[k]
        if count_exposed:
            self._note_exposed(time.perf_counter() - t0)
        return jax.tree_util.tree_unflatten(treedef, out)

    def _note_exposed(self, dt):
        self.exposed_wait_s += dt
        if _monitor.enabled():
            _monitor.histogram("comm.exposed_wait_s").observe(dt)
            _monitor.counter("comm.exposed_wait_s_total").inc(dt)

    def flush(self):
        """Drain the lag-1 tail: the final enqueued step's synced tree,
        or None when nothing is pending. Call once after the last
        training step so its gradient is not dropped."""
        if self._pending is None:
            return None
        pending, self._pending = self._pending, None
        return self._collect(pending)

    # -- Optimizer.step path (eager (param, grad) pairs) -------------------
    def process(self, params_grads):
        """Optimizer hook: lag-1 pipelining + accounting over eager
        pairs (grads already reduced under GSPMD — see class
        docstring). Returns pairs to apply now, or None on the lag-1
        warm-up step."""
        elems = sum(int(np.prod(np.shape(g))) for _, g in params_grads
                    if g is not None)
        _account(self.mode, self.bits, 2, elems, 1)
        traced = any(isinstance(g, jax.core.Tracer)
                     for _, g in params_grads if g is not None)
        if traced or not self.async_apply:
            return params_grads
        prev, self._pending_pg = self._pending_pg, list(params_grads)
        if self._restored is not None:
            restored, self._restored = self._restored, None
            params = [p for p, _ in params_grads]
            if len(restored) == len(params):
                return list(zip(params, [jnp.asarray(g)
                                         for g in restored]))
        if prev is None:
            if _monitor.enabled():
                _monitor.counter("comm.lag_warmup").inc()
            return None
        return prev

    def flush_process(self):
        """Drain the process()-path lag-1 tail."""
        prev, self._pending_pg = self._pending_pg, None
        return prev

    # -- checkpoint discipline ---------------------------------------------
    def state_dict(self):
        """Serialisable scheduler state. The lag-1 pending synced grads
        are MATERIALISED (waited for), never flushed — flushing would
        apply them early and diverge from the uninterrupted run."""
        sd = {"mode": self.mode, "steps": int(self.steps)}
        if self._pending is not None:
            synced = self._collect(self._pending, count_exposed=False)
            leaves, _ = jax.tree_util.tree_flatten(synced)
            sd["pending"] = [np.asarray(jax.device_get(x))
                             for x in leaves]
            # keep serving the same synced tree to the next reduce()
            # call — state_dict() must not consume the pipeline
            self._pending = None
            self._restored = [jnp.asarray(x) for x in sd["pending"]]
        elif self._restored is not None:
            sd["pending"] = [np.asarray(jax.device_get(x))
                             for x in self._restored]
        elif self._pending_pg is not None:
            sd["pending"] = [np.asarray(jax.device_get(
                g.data if hasattr(g, "data") else g))
                for _, g in self._pending_pg]
        return sd

    def set_state_dict(self, sd):
        self.steps = int(sd.get("steps", 0))
        pending = sd.get("pending")
        self._pending = None
        self._pending_pg = None
        self._restored = None if pending is None else \
            [jnp.asarray(x) for x in pending]

    def shutdown(self):
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
