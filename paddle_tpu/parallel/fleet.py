"""paddle_tpu.parallel.fleet — distributed training orchestration.

TPU-native rebuild of the reference's Fleet
(reference: python/paddle/fluid/incubate/fleet/{base/fleet_base.py,
base/distributed_strategy, collective/__init__.py} and
fluid/incubate/fleet/parameter_server/*).

Redesign: Fleet's collective mode maps to a `jax.sharding.Mesh` with named
axes (dp/tp/pp/sp/ep). `fleet.init` builds the mesh (multi-host via
jax.distributed), `distributed_optimizer` wraps the optimizer so that under
to_static the whole step is GSPMD-partitioned: parameters are placed with
NamedShardings, batches are split on the dp axis, and XLA inserts the ICI
collectives the reference implements as NCCL allreduce ops. The
parameter-server mode (CTR path) is redesigned as sharded-embedding data
parallelism (see parallel/embedding.py) since TPU pods have no PS role.
"""
from __future__ import annotations

import os
import re

import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec as P, NamedSharding

from ..tensor import Tensor
from . import collective
from .env import ParallelEnv


# ---------------------------------------------------------------------------
# Megatron-style tensor-parallel placement for user models.
#
# Column-parallel layers (qkv / first ffn projection) split their OUTPUT
# features over tp; row-parallel layers (attention out / second ffn
# projection) split their INPUT features. With parameters placed this way,
# GSPMD propagates the shardings through the jitted train step and inserts
# exactly the all-reduce pair Megatron implements by hand (the f/g
# collectives in parallel/megatron.py are the manual-shard_map flavor of
# the same schedule).

_COL_PAT = re.compile(
    r"(qkv|q_proj|k_proj|v_proj|kv_proj|ffn1|fc1|linear1|intermediate)"
    r"[^.]*\.weight$")
_COL_BIAS_PAT = re.compile(
    r"(qkv|q_proj|k_proj|v_proj|kv_proj|ffn1|fc1|linear1|intermediate)"
    r"[^.]*\.bias$")
_ROW_PAT = re.compile(
    r"(out|o_proj|out_proj|ffn2|fc2|linear2|output)[^.]*\.weight$")


def megatron_param_spec(name, shape, tensor_axis="tp", expert_axis="ep"):
    """Default param_spec_fn for shard_model: Megatron column/row splits
    for transformer-shaped Layers (zoo BERT/Transformer naming),
    expert-stacked MoE weights over the expert axis, replicated
    otherwise."""
    if "experts_" in name and (expert_axis or tensor_axis):
        # nn.MoEFFN stacks: w1 [E, d, f] / w2 [E, f, d] / biases [E, *] —
        # experts over ep, ffn dim additionally over tp (column then row)
        if len(shape) == 3 and name.endswith("w1"):
            return P(expert_axis, None, tensor_axis)
        if len(shape) == 3 and name.endswith("w2"):
            return P(expert_axis, tensor_axis, None)
        if len(shape) == 2 and name.endswith("b1"):
            return P(expert_axis, tensor_axis)
        return P(expert_axis)
    if tensor_axis is None:
        return P()
    if len(shape) == 2 and _COL_PAT.search(name):
        return P(None, tensor_axis)
    if len(shape) == 1 and _COL_BIAS_PAT.search(name):
        return P(tensor_axis)
    if len(shape) == 2 and _ROW_PAT.search(name):
        return P(tensor_axis, None)
    return P()


class DistributedStrategy:
    """reference: DistributedStrategy — knobs consumed at init/compile time."""

    def __init__(self):
        self.amp = False
        self.recompute = False
        self.sharding = False          # ZeRO-style param sharding over dp
        self.mesh_shape = None         # e.g. {'dp': 8} / {'dp': 2, 'tp': 4}
        self.data_axis = "dp"
        self.tensor_axis = "tp"
        self.pipeline_axis = "pp"
        self.sequence_axis = "sp"
        self.expert_axis = "ep"
        self.nccl_comm_num = 1         # parity no-op
        self.use_local_sgd = False
        self.mode = "collective"
        # gradient-sync levers (parallel.overlap), routed to the wrapped
        # optimizer by fleet.distributed_optimizer — the fluid-style user
        # journey's way to turn compression/overlap on:
        self.grad_sync = None            # None/"exact"|"quantized"|"overlap"
        self.quantized_allreduce = False  # int8/int4 wire (implies
        #                                   "quantized" when no mode is set)
        self.grad_bits = 8               # wire width for quantized reduces
        self.grad_bucket_bytes = None    # None -> overlap default (4 MiB)
        # zero-copy flat parameter arena (optimizer.arena, Adam/AdamW)
        self.flat_arena = False
        # planner-driven layout (parallel.planner): a MeshPlan, a tuple
        # of (regex, spec) rules, or "auto" — distributed_model places
        # params by the plan's rules instead of megatron_param_spec
        self.mesh_plan = None


class RoleMakerBase:
    def __init__(self):
        self._env = ParallelEnv()

    def worker_num(self):
        return self._env.world_size

    def worker_index(self):
        return self._env.rank

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def is_first_worker(self):
        return self._env.rank == 0


class PaddleCloudRoleMaker(RoleMakerBase):
    """reference: role_maker.py:PaddleCloudRoleMaker (collective mode)."""

    def __init__(self, is_collective=True):
        super().__init__()
        self.is_collective = is_collective


UserDefinedRoleMaker = PaddleCloudRoleMaker


class Fleet:
    """reference: fleet_base.py:Fleet (collective implementation)."""

    def __init__(self):
        self._role_maker = None
        self._strategy = None
        self._mesh = None
        self._initialized = False
        self._model = None  # last distributed_model, for save_persistables

    # -- lifecycle ----------------------------------------------------------
    def init(self, role_maker=None, is_collective=True, strategy=None,
             mesh_shape=None, devices=None):
        """Build the device mesh (multi-host aware). mesh_shape maps axis
        names to sizes, e.g. {'dp': 2, 'tp': 4}; default all-dp."""
        self._role_maker = role_maker or PaddleCloudRoleMaker(is_collective)
        self._strategy = strategy or DistributedStrategy()
        if mesh_shape is None:
            mesh_shape = self._strategy.mesh_shape
        devices = devices if devices is not None else jax.devices()
        if mesh_shape is None:
            mesh_shape = {self._strategy.data_axis: len(devices)}
        self._mesh = collective.make_mesh(mesh_shape, devices)
        self._initialized = True
        return self

    @property
    def mesh(self):
        return self._mesh

    @property
    def strategy(self):
        return self._strategy

    def worker_num(self):
        return self._role_maker.worker_num() if self._role_maker else 1

    def worker_index(self):
        return self._role_maker.worker_index() if self._role_maker else 0

    def is_first_worker(self):
        return self.worker_index() == 0

    def barrier_worker(self):
        pass  # single-controller JAX: nothing to do

    # -- placement ----------------------------------------------------------
    def shard_model(self, model, param_spec_fn=None):
        """Place every parameter/buffer on the mesh. Default replicated;
        param_spec_fn(name, shape) -> PartitionSpec enables tensor/ZeRO
        sharding. (The reference broadcasts params over NCCL at startup —
        on TPU placement IS the broadcast.)"""
        mesh = self._mesh
        for name, p in model.named_parameters():
            # params already mesh-placed with a non-trivial spec (e.g. a
            # PipelineStack's pp-stacked weights) keep their placement
            cur = getattr(p.data, "sharding", None)
            if isinstance(cur, NamedSharding) and \
                    any(ax is not None for ax in cur.spec):
                continue
            spec = param_spec_fn(name, p.data.shape) if param_spec_fn else P()
            p.data = jax.device_put(p.data, NamedSharding(mesh, spec or P()))
        for name, b in model.named_buffers():
            if isinstance(b, Tensor):
                b.data = jax.device_put(b.data, NamedSharding(mesh, P()))
        return model

    def shard_batch(self, *arrays, axis=None):
        """Split a batch along the dp axis (first dim)."""
        mesh = self._mesh
        axis = axis or self._strategy.data_axis
        out = []
        for a in arrays:
            if isinstance(a, Tensor):
                a = a.data
            import jax.numpy as jnp
            a = jnp.asarray(a)
            spec = P(axis) if a.ndim >= 1 else P()
            out.append(Tensor(jax.device_put(
                a, NamedSharding(mesh, spec))))
        return out[0] if len(out) == 1 else tuple(out)

    def distributed_optimizer(self, optimizer, strategy=None):
        """reference: fleet.distributed_optimizer — wraps so that optimizer
        state is mesh-placed; with GSPMD the grads arrive already psum'd
        (XLA inserts the allreduce the reference ran via NCCL). The
        strategy's ``grad_sync``/``quantized_allreduce`` knobs attach a
        parallel.overlap.GradSyncScheduler, and ``flat_arena`` turns on
        the zero-copy flat parameter arena (Adam/AdamW)."""
        if strategy is not None:
            self._strategy = strategy
        st = self._strategy
        if st is not None:
            mode = getattr(st, "grad_sync", None)
            quant = bool(getattr(st, "quantized_allreduce", False))
            if quant and not mode:
                mode = "quantized"
            if mode and mode != "exact":
                from .overlap import (DEFAULT_BUCKET_BYTES,
                                      GradSyncScheduler)
                optimizer.set_grad_sync(GradSyncScheduler(
                    mode=mode, mesh=self._mesh,
                    bits=int(getattr(st, "grad_bits", 8)),
                    bucket_bytes=getattr(st, "grad_bucket_bytes", None)
                    or DEFAULT_BUCKET_BYTES,
                    quantized=True if quant else None))
            if getattr(st, "flat_arena", False):
                optimizer.set_flat_arena(True)
        return DistributedOptimizer(optimizer, self)

    def _default_spec_fn(self):
        """megatron_param_spec bound to whichever of the strategy's
        tensor/expert axes actually exist (size > 1) on the mesh; None if
        neither does."""
        if self._mesh is None:
            return None
        names = self._mesh.axis_names

        def active(ax):
            return ax if ax in names and self._mesh.shape[ax] > 1 else None

        t_ax = active(self._strategy.tensor_axis)
        e_ax = active(self._strategy.expert_axis)
        if not (t_ax or e_ax):
            return None
        return lambda n, s: megatron_param_spec(n, s, tensor_axis=t_ax,
                                                expert_axis=e_ax)

    def distributed_model(self, model, param_spec_fn=None):
        """Place a user nn.Layer on the mesh. When the mesh has a >1
        tensor axis, parameters get Megatron column/row shardings by
        default (megatron_param_spec); compose with jit.to_static and
        GSPMD partitions the whole fwd+bwd+update step across dp×tp.

        A ``strategy.mesh_plan`` (parallel.planner rules / MeshPlan /
        "auto") takes precedence over megatron_param_spec: the plan's
        regex rules decide every param's spec."""
        if param_spec_fn is None and self._strategy is not None and \
                getattr(self._strategy, "mesh_plan", None) is not None:
            from . import planner as _planner
            param_spec_fn = _planner.resolve(
                self._strategy.mesh_plan, mesh=self._mesh).as_spec_fn()
        if param_spec_fn is None:
            param_spec_fn = self._default_spec_fn()
        self.shard_model(model, param_spec_fn)
        self._model = model
        return model

    def pipeline_stack(self, blocks, spec_fn=None, remat=None):
        """Stage-shard a trunk of identical blocks over the mesh's pp
        axis (reference: Fleet pipeline strategy / PipelineOptimizer —
        see parallel/pipeline.py for the GSPMD redesign). Returns a
        drop-in Layer replacing the LayerList. remat defaults to the
        strategy's recompute flag (per-stage jax.checkpoint)."""
        from .pipeline import PipelineStack
        if spec_fn is None:
            spec_fn = self._default_spec_fn()
        if remat is None:
            remat = self._strategy.recompute
        return PipelineStack(blocks, mesh=self._mesh,
                             pipeline_axis=self._strategy.pipeline_axis,
                             spec_fn=spec_fn, remat=remat)

    # -- io parity ----------------------------------------------------------
    def save_persistables(self, executor=None, dirname=None,
                          main_program=None, model=None, optimizer=None):
        """Save the distributed model's (and optionally optimizer's) state
        as an orbax checkpoint (reference: fleet_base.py
        save_persistables → io.save_persistables). The sharded arrays are
        gathered on save; load_persistables re-places them onto each
        parameter's live sharding."""
        from .. import io as pio
        model = model or self._model
        if dirname is None or model is None:
            raise ValueError("save_persistables needs dirname= and a model "
                             "(pass model= or call distributed_model first)")
        state = {"model": model.state_dict()}
        if optimizer is not None:
            state["optimizer"] = optimizer.state_dict()
        pio.orbax_save(dirname, state)

    def load_persistables(self, executor=None, dirname=None,
                          main_program=None, model=None, optimizer=None):
        """Restore save_persistables output with placement preserved."""
        from .. import io as pio
        model = model or self._model
        if dirname is None or model is None:
            raise ValueError("load_persistables needs dirname= and a model")
        template = {"model": model.state_dict()}
        if optimizer is not None:
            template["optimizer"] = optimizer.state_dict()
        state = pio.orbax_restore(dirname, template=template)
        model.set_state_dict(state["model"])
        if optimizer is not None and "optimizer" in state:
            optimizer.set_state_dict(state["optimizer"])
        return state

    def save_inference_model(self, dirname=None, feeded_var_names=None,
                             target_vars=None, executor=None,
                             main_program=None, model=None,
                             input_spec=None):
        """Export the (gathered) model for inference (reference:
        fleet_base.py save_inference_model → io.save_inference_model)."""
        from .. import io as pio
        model = model or self._model
        if dirname is None or model is None:
            raise ValueError("save_inference_model needs dirname= and a "
                             "model")
        pio.save_inference_model(os.path.join(dirname, "model"), model,
                                 input_spec=input_spec)


class DistributedOptimizer:
    """Wrapper keeping optimizer slot state mesh-resident: every
    accumulator is placed with ITS PARAMETER's sharding, so the jitted
    train step updates tp-sharded params with tp-sharded moments and no
    resharding traffic appears on the update path (reference: fleet
    DistributedStrategy sharding / DGC options)."""

    def __init__(self, inner, fleet_obj):
        self.inner = inner
        self._fleet = fleet_obj
        self._placed = False

    def __getattr__(self, item):
        return getattr(self.inner, item)

    def _place_slots(self):
        self.inner._ensure_all_slots()
        params_by_id = {id(p): p for p in self.inner._params()}
        for pid, slots in self.inner._accumulators.items():
            p = params_by_id.get(pid)
            if p is None:
                continue
            psharding = getattr(p.data, "sharding", None)
            for t in slots.values():
                if psharding is not None and \
                        t.data.shape == p.data.shape:
                    t.data = jax.device_put(t.data, psharding)
                elif self._fleet._mesh is not None:
                    t.data = jax.device_put(
                        t.data, NamedSharding(self._fleet._mesh, P()))
        self._placed = True

    def _ensure_all_slots(self):
        # called by jit.to_static before tracing — placement hook
        if not self._placed:
            self._place_slots()
        else:
            self.inner._ensure_all_slots()

    def step(self):
        if not self._placed:
            self._place_slots()
        self.inner.step()

    def minimize(self, loss, **kw):
        return self.inner.minimize(loss, **kw)


fleet = Fleet()


def init(role_maker=None, is_collective=True, strategy=None, mesh_shape=None,
         devices=None):
    return fleet.init(role_maker, is_collective, strategy, mesh_shape,
                      devices)
