"""paddle_tpu.parallel.env — ParallelEnv.

TPU-native rebuild of reference python/paddle/fluid/dygraph/parallel.py
ParallelEnv (+ prepare_context): rank/world topology comes from the JAX
runtime (jax.process_index / device mesh) instead of env-var + NCCL-id
bootstrap.
"""
from __future__ import annotations

import os

import jax


class ParallelEnv:
    """reference: dygraph/parallel.py:ParallelEnv."""

    @property
    def rank(self):
        return jax.process_index()

    @property
    def local_rank(self):
        return jax.process_index()

    @property
    def world_size(self):
        return jax.process_count()

    @property
    def nranks(self):
        return jax.device_count()

    @property
    def device_id(self):
        return 0

    @property
    def dev_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:0")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else ["127.0.0.1:0"]


Env = ParallelEnv


def prepare_context(strategy=None):
    """reference: dygraph.parallel.prepare_context — no NCCL bootstrap
    needed; the mesh IS the communicator."""
    return ParallelEnv()
