"""paddle_tpu.parallel.layout — mesh/PartitionSpec layout extraction and
reshard-on-load.

The sharded checkpoint contract (paddle_tpu.io.sharded) needs three
things from the parallelism layer, all of which live here so the io
layer never reaches into jax.sharding internals directly:

* :func:`mesh_signature` — a JSON-able fingerprint of a mesh's topology
  (axis names → sizes, device count, platform). Saved into every
  sharded-checkpoint manifest; a restore onto a mesh with a different
  signature is a *resharding* restore (``ckpt.restore_resharded``).
* :func:`spec_of` / :func:`spec_to_lists` / :func:`spec_from_lists` —
  extract a live array's ``PartitionSpec`` and round-trip it through a
  JSON-able form (``[["dp"], None, ["tp","sp"]]``-style lists).
* :func:`adapt_spec` / :func:`reshard` — map a saved spec onto the
  *current* mesh, which may have different axis sizes (dp×tp resize),
  missing axes, or fewer devices. Axes the new mesh doesn't have are
  dropped; a dimension whose sharded axis product no longer divides the
  dimension falls back to replication for that dimension — placement
  degrades to *correct but less sharded*, never to an invalid layout.

Everything here is topology math on host metadata; no collective is
issued and nothing requires an SPMD region.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def mesh_signature(mesh):
    """JSON-able topology fingerprint: ``{"axes": {name: size}, ...}``.
    ``None`` (no mesh) signs as a single-device/no-mesh layout."""
    if mesh is None:
        return {"axes": {}, "n_devices": 1, "platform": None}
    axes = {str(name): int(size) for name, size in mesh.shape.items()}
    devs = mesh.devices.reshape(-1)
    platform = getattr(devs[0], "platform", None) if len(devs) else None
    return {"axes": axes, "n_devices": int(devs.size), "platform": platform}


def same_signature(a, b):
    """Topology equality: axis names+sizes and device count (platform is
    informational — a CPU rehearsal of a TPU layout still reshards)."""
    return (a or {}).get("axes") == (b or {}).get("axes") and \
        (a or {}).get("n_devices") == (b or {}).get("n_devices")


def spec_of(value):
    """The PartitionSpec of a live array/Tensor, or None when it has no
    NamedSharding (numpy, fully-committed single device, GSPMD opaque)."""
    arr = getattr(value, "data", value)
    sh = getattr(arr, "sharding", None)
    if isinstance(sh, NamedSharding):
        return sh.spec
    return None


def spec_to_lists(spec, ndim):
    """PartitionSpec → JSON form: one entry per dim, each ``None`` or a
    list of axis names (a dim sharded over multiple axes keeps them in
    order). Dims beyond the spec's length are unsharded."""
    out = []
    entries = tuple(spec) if spec is not None else ()
    for d in range(ndim):
        e = entries[d] if d < len(entries) else None
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append([str(a) for a in e])
        else:
            out.append([str(e)])
    return out


def spec_from_lists(lists):
    """Inverse of :func:`spec_to_lists`."""
    entries = []
    for e in lists or ():
        if not e:
            entries.append(None)
        elif len(e) == 1:
            entries.append(e[0])
        else:
            entries.append(tuple(e))
    return P(*entries)


def extract_layout(named_values):
    """{name: live array/Tensor} → {name: spec-lists} for every value
    that carries a NamedSharding (the manifest's layout record)."""
    out = {}
    for name, v in named_values.items():
        spec = spec_of(v)
        if spec is not None:
            arr = getattr(v, "data", v)
            out[name] = spec_to_lists(spec, int(getattr(arr, "ndim", 0)))
    return out


# (param-name, dim, axes) triples already warned about — degradation is
# warned ONCE per site so a training loop re-placing every step doesn't
# spam; the layout.degraded counter keeps the full count for the planner.
_degrade_warned = set()


def _note_degraded(name, d, axes, dim, prod, reason):
    from .. import monitor as _monitor
    _monitor.counter("layout.degraded").inc()
    key = (name, d, tuple(axes))
    if key in _degrade_warned:
        return
    _degrade_warned.add(key)
    import warnings
    who = f"param {name!r}" if name else "array"
    warnings.warn(
        f"layout: {who} dim {d} (size {dim}) degraded to replicated — "
        f"{reason} (requested axes {list(axes)}, product {prod}). "
        f"Counted in layout.degraded; further degradations of this dim "
        f"are silent.", RuntimeWarning, stacklevel=4)


def adapt_spec(lists, shape, mesh, name=None):
    """Map a saved spec (lists form) onto `mesh` for an array of `shape`.

    Returns ``(PartitionSpec, changed)``. Per dimension: axis names the
    mesh doesn't have are dropped; if the surviving axes' size product
    does not divide the dimension, the whole dimension falls back to
    replicated. `changed` is True when any dim degraded — the signal
    behind ``ckpt.restore_resharded`` accounting and the planner's
    degradation penalty. Every degraded dim bumps the
    ``layout.degraded`` counter and warns once per (name, dim, axes).
    """
    if mesh is None:
        return P(), bool(lists and any(lists))
    sizes = {str(n): int(s) for n, s in mesh.shape.items()}
    entries, changed = [], False
    for d, e in enumerate(lists or ()):
        if not e:
            entries.append(None)
            continue
        kept = [a for a in e if a in sizes]
        if len(kept) != len(e):
            changed = True
        prod = int(np.prod([sizes[a] for a in kept])) if kept else 1
        dim = int(shape[d]) if d < len(shape) else 1
        if not kept or prod <= 0 or dim % prod != 0:
            if kept:
                changed = True
                _note_degraded(name, d, e, dim, prod,
                               "axis product does not divide the dim")
            elif e:
                _note_degraded(name, d, e, dim, prod,
                               "mesh has none of the requested axes")
            entries.append(None)
            continue
        entries.append(kept[0] if len(kept) == 1 else tuple(kept))
    return P(*entries), changed


def reshard(value, lists, mesh):
    """Place a (host) array onto `mesh` under the saved spec, adapted to
    the mesh actually present. Returns ``(jax.Array, changed)``; with no
    mesh the value passes through as-is."""
    if mesh is None:
        return value, False
    spec, changed = adapt_spec(lists, np.shape(value), mesh)
    return jax.device_put(value, NamedSharding(mesh, spec)), changed


def shard_index_bounds(index, shape):
    """Normalize an ``addressable_shards[...].index`` slice tuple into
    JSON-able ``[[start, stop], ...]`` bounds over `shape`."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def bounds_to_slices(bounds):
    return tuple(slice(b[0], b[1]) for b in bounds)
