"""paddle_tpu.parallel.embedding — mesh-sharded embedding tables.

TPU-native redesign of the reference's parameter-server sparse tables
(reference: fluid/incubate/fleet/parameter_server + distributed transpiler
splitting lookup_table over pservers; operators/distributed lookup ops).

A TPU pod has no parameter-server role, so the big table is *row-sharded
over a mesh axis*:

* **GSPMD path (default)**: the weight carries a NamedSharding of
  P(axis, None). A plain gather inside a jitted step is partitioned by
  XLA, which inserts the needed ICI collectives — zero manual code.
* **shard_map path**: `sharded_lookup` does the classic mask-gather-psum
  dance explicitly for code running inside shard_map (each device gathers
  hits in its row range, others contribute zeros, one psum combines).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P, NamedSharding

from ..tensor import Tensor
from ..dispatch import apply
from .. import nn
from .. import initializer as I
from . import collective


class ShardedEmbedding(nn.Layer):
    """Row-sharded embedding table (drop-in for nn.Embedding)."""

    def __init__(self, num_embeddings, embedding_dim, axis_name="mp",
                 weight_attr=None, mesh=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.axis_name = axis_name
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0 / np.sqrt(embedding_dim)))
        mesh = mesh or collective.get_mesh()
        if mesh is not None and axis_name in mesh.axis_names:
            self.weight.data = jax.device_put(
                self.weight.data, NamedSharding(mesh, P(axis_name, None)))

    def forward(self, ids):
        if collective.in_spmd_context(self.axis_name):
            return sharded_lookup(ids, self.weight, self.axis_name)
        # GSPMD path: plain gather; XLA partitions it over the sharded table
        def impl(ids, w):
            return jnp.take(w, ids, axis=0)
        return apply(impl, (ids, self.weight), name="sharded_embedding")


def sharded_lookup(ids, weight, axis_name="mp"):
    """Explicit lookup for shard_map regions: `weight` is the LOCAL row
    shard; out-of-range ids contribute zeros; one psum merges."""
    def impl(ids, w):
        n = collective.axis_size(axis_name)
        r = lax.axis_index(axis_name)
        rows = w.shape[0]
        lo = r * rows
        local = ids - lo
        in_range = (local >= 0) & (local < rows)
        safe = jnp.clip(local, 0, rows - 1)
        out = jnp.take(w, safe, axis=0)
        out = jnp.where(in_range[..., None], out, 0.0)
        return lax.psum(out, axis_name)
    return apply(impl, (ids, weight), name="c_sharded_lookup")
