"""paddle_tpu.parallel.ring_attention — sequence-parallel attention.

Long-context attention over a sequence-sharded mesh axis (SURVEY §2 #30 —
beyond the reference, required for TPU long-context parity). Each device
holds a local [B, H, S/n, D] block of Q/K/V; K/V blocks rotate around the
ICI ring via `lax.ppermute` while a flash-style online softmax accumulates
(running max m, normalizer l, weighted sum acc), so the full S×S attention
is computed with S/n-sized working sets and no all-gather of K/V.

Use inside shard_map with the sequence axis bound, e.g.:

    out = shard_map(lambda q,k,v: ring_attention(q,k,v,axis_name='sp'),
                    mesh=mesh, in_specs=P(None,None,'sp',None), ...)

Causal masking accounts for the global positions of rotating blocks.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..tensor import Tensor, as_tensor
from ..dispatch import apply
from .collective import axis_size as _axis_size


def _ring_attention_impl(q, k, v, axis_name, causal, scale):
    n = _axis_size(axis_name)
    me = lax.axis_index(axis_name)
    sl = q.shape[-2]  # local seq block
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / np.sqrt(d)
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = me * sl + jnp.arange(sl)  # global positions of my queries

    def block(carry, step):
        m, l, acc, kb, vb = carry
        src = (me - step) % n  # which global block this kb/vb came from
        logits = jnp.einsum("...qd,...kd->...qk", q, kb) * s
        if causal:
            k_pos = src * sl + jnp.arange(sl)
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask, logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        # guard fully-masked rows (m_new = -inf): exp(-inf - -inf) -> nan
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(logits - m_safe[..., None])
        p = jnp.where(jnp.isneginf(logits), 0.0, p)
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum("...qk,...kd->...qd",
                                                     p, vb)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (m_new, l_new, acc_new, kb, vb), None

    # derive the initial carry from q so it inherits q's varying-axis type
    # under shard_map (a plain jnp.zeros would be axis-invariant and fail
    # lax.scan's carry type check)
    m0 = jnp.full_like(q[..., 0], -jnp.inf)
    l0 = jnp.zeros_like(q[..., 0])
    acc0 = jnp.zeros_like(q)
    (m, l, acc, _, _), _ = lax.scan(block, (m0, l0, acc0, k, v),
                                    jnp.arange(n))
    return acc / jnp.maximum(l, 1e-20)[..., None]


def ring_attention(q, k, v, axis_name="sp", causal=False, scale=None,
                   name=None):
    """Sequence-parallel attention (framework op: differentiable via the
    tape like every other op). Outside an SPMD region it degrades to plain
    attention (n=1 ring)."""
    from . import collective
    if not collective.in_spmd_context(axis_name):
        # single-block fallback: ordinary attention
        from ..ops.nn_ops import scaled_dot_product_attention
        return scaled_dot_product_attention(q, k, v, is_causal=causal,
                                            scale=scale, training=False)
    return apply(_ring_attention_impl, (q, k, v),
                 dict(axis_name=axis_name, causal=causal, scale=scale),
                 name="ring_attention")
