"""paddle_tpu.parallel.data_parallel — DataParallel.

TPU-native rebuild of reference python/paddle/fluid/dygraph/parallel.py
DataParallel (+ scale_loss / apply_collective_grads over NCCL).

Redesign: on TPU, data parallelism is a *sharding*, not an explicit
gradient exchange. Wrapping a model in DataParallel (after fleet.init)
places its parameters replicated on the mesh; feeding batches sharded on
the dp axis makes XLA's GSPMD partitioner emit the gradient all-reduce on
ICI automatically inside the compiled train step. scale_loss /
apply_collective_grads are therefore identity shims kept for API parity —
the math they performed (grad-sum ÷ nranks) is what GSPMD produces.
"""
from __future__ import annotations

import jax

from ..nn.layer import Layer
from .fleet import fleet
from . import collective


class DataParallel(Layer):
    """reference: dygraph/parallel.py:DataParallel."""

    def __init__(self, layers, strategy=None, mesh=None):
        super().__init__()
        self._layers = layers
        mesh = mesh or collective.get_mesh()
        if mesh is None and not fleet._initialized:
            fleet.init()
            mesh = fleet.mesh
        if mesh is not None:
            fleet._mesh = fleet._mesh or mesh
            fleet.shard_model(layers)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        """Parity shim: with mean-reduced losses + GSPMD allreduce the
        scaling is already correct."""
        return loss

    def apply_collective_grads(self):
        """Parity shim: GSPMD emits the grad allreduce inside the compiled
        step; nothing to do here."""
        return

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)
