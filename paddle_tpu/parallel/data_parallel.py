"""paddle_tpu.parallel.data_parallel — DataParallel.

TPU-native rebuild of reference python/paddle/fluid/dygraph/parallel.py
DataParallel (+ scale_loss / apply_collective_grads over NCCL).

Redesign: on TPU, data parallelism is a *sharding*, not an explicit
gradient exchange. Wrapping a model in DataParallel (after fleet.init)
places its parameters replicated on the mesh; feeding batches sharded on
the dp axis makes XLA's GSPMD partitioner emit the gradient all-reduce on
ICI automatically inside the compiled train step. scale_loss /
apply_collective_grads are therefore identity shims kept for API parity —
the math they performed (grad-sum ÷ nranks) is what GSPMD produces.

``grad_sync="overlap"|"quantized"|"exact"`` attaches a
:class:`~paddle_tpu.parallel.overlap.GradSyncScheduler` (exposed as
``.grad_scheduler``): explicit-DDP loops feed it stacked per-rank grads
(``overlap.local_value_and_grad``) for bucketed / overlapped /
quantized-ring sync, and ``apply_collective_grads`` drains any
in-flight bucket reduces — see docs/performance.md "Communication
overlap & quantized sync".
"""
from __future__ import annotations

import jax

from ..nn.layer import Layer
from .fleet import fleet
from . import collective


class DataParallel(Layer):
    """reference: dygraph/parallel.py:DataParallel."""

    def __init__(self, layers, strategy=None, mesh=None,
                 grad_sync=None, grad_bits=8, grad_bucket_bytes=None,
                 async_apply=None, flat_arena=None, optimizer=None,
                 mesh_plan=None):
        super().__init__()
        self._layers = layers
        self.flat_arena = flat_arena
        self.mesh_plan = None
        if mesh_plan is None and strategy is not None:
            mesh_plan = getattr(strategy, "mesh_plan", None)
        mesh = mesh or collective.get_mesh()
        if mesh is None and not fleet._initialized:
            fleet.init()
            mesh = fleet.mesh
        if mesh_plan is not None:
            # planner-driven layout: rules decide each param's spec
            # (tp/sp splits included) instead of blanket replication;
            # the resolved plan is exposed for jit.to_static(plan=)
            from . import planner as _planner
            self.mesh_plan = _planner.resolve(mesh_plan, mesh=mesh)
            mesh = self.mesh_plan.mesh
            fleet._mesh = fleet._mesh or mesh
            self.mesh_plan.place_model(layers)
        elif mesh is not None:
            fleet._mesh = fleet._mesh or mesh
            fleet.shard_model(layers)
        self.grad_scheduler = None
        if grad_sync is not None and grad_sync != "exact":
            from .overlap import (DEFAULT_BUCKET_BYTES,
                                  GradSyncScheduler)
            self.grad_scheduler = GradSyncScheduler(
                mode=grad_sync, mesh=mesh, bits=grad_bits,
                bucket_bytes=grad_bucket_bytes or DEFAULT_BUCKET_BYTES,
                async_apply=async_apply, plan=self.mesh_plan)
        # optimizer= routes the wrapper-level knobs straight to the
        # optimizer driving this model (the one-call DDP setup)
        if optimizer is not None:
            if self.grad_scheduler is not None:
                optimizer.set_grad_sync(self.grad_scheduler)
            if flat_arena is not None:
                optimizer.set_flat_arena(flat_arena)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        """Parity shim: with mean-reduced losses + GSPMD allreduce the
        scaling is already correct."""
        return loss

    def apply_collective_grads(self):
        """GSPMD emits the grad allreduce inside the compiled step, so
        without a grad scheduler this stays a parity no-op; with one it
        drains the in-flight bucket reduces (the lag-1 tail) so every
        launched gradient lands before the caller reads params."""
        if self.grad_scheduler is not None:
            return self.grad_scheduler.flush()
        return

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)
