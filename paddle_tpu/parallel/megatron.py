"""paddle_tpu.parallel.megatron — the flagship SPMD transformer trainer.

This is the TPU-native answer to the reference's multi-GPU training stack
(reference: Fleet collective mode + pipeline/recompute DistributedStrategy,
NCCL allreduce ops, and the transpiler's program-splitting) rebuilt as ONE
`shard_map` over a 5-axis mesh:

    dp — data parallel          (grad psum, reference c_allreduce)
    pp — pipeline parallel      (GPipe microbatch ring over ppermute)
    tp — tensor parallel        (Megatron column/row splits, psum on exit)
    sp — sequence/context par.  (ring attention over ppermute — long ctx)
    ep — expert parallel        (MoE ffn, all_to_all token routing)

Everything is explicit lax collectives — the schedule the XLA compiler
rides onto ICI links. The trainer is pure-functional (params pytree in,
params pytree out) and is what `__graft_entry__.dryrun_multichip` compiles.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P, NamedSharding

from .collective import axis_size as _axis_size


# ---------------------------------------------------------------------------
# config

class MegatronConfig(NamedTuple):
    vocab_size: int = 1024
    hidden: int = 128          # global hidden size
    ffn_mult: int = 4
    n_heads: int = 4           # global head count (split over tp)
    layers_per_stage: int = 2  # pp stages each run this many blocks
    n_experts: int = 2         # per ep rank (MoE block replaces last ffn)
    seq_len: int = 64          # global sequence length (split over sp)
    microbatch: int = 2        # per-dp-rank microbatch size
    n_micro: int = 2           # microbatches per step (pipeline depth)
    lr: float = 1e-3
    use_moe: bool = True
    optimizer: str = "adam"    # "adam" (fused-kernel rule) | "sgd"
    beta1: float = 0.9
    beta2: float = 0.999
    adam_eps: float = 1e-8
    # int8-wire ring all-reduce for the dp gradient sync
    # (collective.all_reduce_quantized, EQuARX direction / the
    # reference's DGC bandwidth lever) — opt-in: ~4x less gradient
    # traffic at a bounded quantization error; exact psum by default.
    # Kept for back-compat: equivalent to grad_sync="quantized".
    quantized_grad_allreduce: bool = False
    # dp gradient sync plan (parallel.overlap.sync_tree):
    #   "exact"     — per-leaf lax.pmean (the default, no bucketing)
    #   "quantized" — bucketed int8/int4 ring (grad_bits wire width)
    #   "overlap"   — bucketed exact reduce; the per-bucket collectives
    #                 are independent so XLA interleaves them with the
    #                 remaining backward compute inside the one
    #                 shard_map program
    grad_sync: str = "exact"
    grad_bits: int = 8
    grad_bucket_bytes: int = 4 << 20
    # flat parameter arena (optimizer/arena.py layout, dp/sp-only meshes):
    # the whole f32 param tree lives in ONE contiguous buffer; the loss fn
    # differentiates the buffer itself so the gradient materializes flat —
    # no per-leaf concat before the dp sync and one fused adam dispatch.
    # Requires tp == pp == ep == 1 (sharded params can't share a
    # replicated buffer); ignored with a warning otherwise.
    flat_arena: bool = False
    # planner rule set (parallel.planner): a tuple of (regex, spec)
    # rules — spec as PartitionSpec or spec_to_lists form — that
    # overrides the hand-written init_params specs. None keeps the
    # hand layout. Must be a tuple (hashable) so configs stay usable
    # as dict keys; planner.MeshPlan(rules, mesh).spec_for drives the
    # placement.
    mesh_plan: tuple = None
    # activation rematerialization (memory_plan policy names): "dots"
    # or "full" wraps every transformer block in jax.checkpoint under
    # that policy, so the pipeline's backward recomputes block
    # activations instead of storing them — same math, ~one extra
    # forward of flops per block; XLA may refuse the recomputed ops so
    # losses track the stored-activation run to float rounding, not
    # guaranteed bit-for-bit (the jit.to_static surface IS bit-exact).
    remat: str = None


def factorize_mesh(n_devices):
    """Assign devices to (dp, pp, tp, sp, ep): peel factors of 2 in a
    fixed priority so any power-of-two count exercises multiple axes."""
    sizes = {"dp": 1, "pp": 1, "tp": 1, "sp": 1, "ep": 1}
    rest = n_devices
    for axis in ("dp", "pp", "tp", "sp", "ep"):
        if rest % 2 == 0 and rest > 1:
            sizes[axis] *= 2
            rest //= 2
    # fold any remainder into dp
    sizes["dp"] *= rest
    return sizes


def make_mesh(n_devices=None, devices=None, sizes=None):
    """Build the 5-axis mesh. sizes overrides the default factorization
    (e.g. {"dp": 2, "sp": 2, "ep": 2} to exercise the sequence/expert
    axes on 8 devices)."""
    devices = devices if devices is not None else jax.devices()
    n = n_devices or len(devices)
    if sizes is None:
        sizes = factorize_mesh(n)
    else:
        full = {"dp": 1, "pp": 1, "tp": 1, "sp": 1, "ep": 1}
        full.update(sizes)
        sizes = full
        total = int(np.prod(list(sizes.values())))
        if total != n:
            raise ValueError(f"mesh sizes {sizes} use {total} devices, "
                             f"have {n}")
    names = ("dp", "pp", "tp", "sp", "ep")
    arr = np.asarray(devices[:n]).reshape([sizes[a] for a in names])
    return Mesh(arr, names), sizes


# ---------------------------------------------------------------------------
# parameter init (per-device LOCAL shards built under shard_map-compatible
# global specs: we build GLOBAL arrays and device_put with NamedShardings)

def init_params(cfg: MegatronConfig, mesh: Mesh, seed=0, plan=None):
    """Global parameter pytree + its PartitionSpecs. tp splits: qkv/ffn1
    column-wise, out/ffn2 row-wise (Megatron); pp stacks stages; ep stacks
    experts. `plan` (a parallel.planner.MeshPlan, or cfg.mesh_plan rules
    resolved by the caller) replaces the hand specs with rule-matched
    ones — the planner's reproduction target is bit-identity with the
    hand layout."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    pp, tp, ep = sizes["pp"], sizes["tp"], sizes["ep"]
    h = cfg.hidden
    ffn = h * cfg.ffn_mult
    rng = np.random.RandomState(seed)

    def w(*shape, scale=None):
        scale = scale or 1.0 / np.sqrt(shape[-2] if len(shape) >= 2 else h)
        return (rng.randn(*shape) * scale).astype("f4")

    L = cfg.layers_per_stage
    nh = cfg.n_heads
    hd = h // nh
    params = {
        "embed": w(cfg.vocab_size, h, scale=0.02),
        "pos": w(cfg.seq_len, h, scale=0.02),
        # stage-stacked block params: leading axis pp, then per-stage
        # layers. QKV carries an explicit heads axis so tp shards HEADS —
        # naively column-splitting a [q|k|v]-packed matrix would hand rank 0
        # all of Q plus part of K.
        "qkv_w": w(pp, L, h, 3, nh, hd, scale=1.0 / np.sqrt(h)),
        "qkv_b": np.zeros((pp, L, 3, nh, hd), "f4"),
        "attn_out_w": w(pp, L, nh, hd, h, scale=1.0 / np.sqrt(h)),
        "attn_out_b": np.zeros((pp, L, h), "f4"),
        "ln1_w": np.ones((pp, L, h), "f4"),
        "ln1_b": np.zeros((pp, L, h), "f4"),
        "ffn1_w": w(pp, L, h, ffn),
        "ffn1_b": np.zeros((pp, L, ffn), "f4"),
        "ffn2_w": w(pp, L, ffn, h),
        "ffn2_b": np.zeros((pp, L, h), "f4"),
        "ln2_w": np.ones((pp, L, h), "f4"),
        "ln2_b": np.zeros((pp, L, h), "f4"),
        "lnf_w": np.ones((h,), "f4"),
        "lnf_b": np.zeros((h,), "f4"),
    }
    if cfg.use_moe:
        # expert-stacked MoE ffn on the LAST stage (router replicated)
        params["moe_router"] = w(h, ep * cfg.n_experts, scale=0.02)
        params["moe_w1"] = w(ep, cfg.n_experts, h, ffn)
        params["moe_w2"] = w(ep, cfg.n_experts, ffn, h)

    specs = {
        "embed": P(None, None),
        "pos": P(None, None),
        "qkv_w": P("pp", None, None, None, "tp", None),
        "qkv_b": P("pp", None, None, "tp", None),
        "attn_out_w": P("pp", None, "tp", None, None),
        "attn_out_b": P("pp", None, None),
        "ln1_w": P("pp", None, None), "ln1_b": P("pp", None, None),
        "ffn1_w": P("pp", None, None, "tp"),
        "ffn1_b": P("pp", None, "tp"),
        "ffn2_w": P("pp", None, "tp", None),
        "ffn2_b": P("pp", None, None),
        "ln2_w": P("pp", None, None), "ln2_b": P("pp", None, None),
        "lnf_w": P(None), "lnf_b": P(None),
    }
    if cfg.use_moe:
        specs["moe_router"] = P(None, None)
        specs["moe_w1"] = P("ep", None, None, None)
        specs["moe_w2"] = P("ep", None, None, None)

    if plan is not None:
        specs = {k: plan.spec_for(k, np.shape(v))
                 for k, v in params.items()}

    placed = {
        k: jax.device_put(jnp.asarray(v), NamedSharding(mesh, specs[k]))
        for k, v in params.items()
    }
    return placed, specs


# ---------------------------------------------------------------------------
# Megatron f/g collective pair: the key to correct manual-SPMD gradients.
# f: forward identity, backward psum — placed where a REPLICATED activation
#    enters a tensor-split region (column-parallel entry), so the partial
#    cotangents coming back from each rank's weight slice are summed and
#    every rank sees the COMPLETE gradient for the replicated upstream.
# g: forward psum, backward identity — row-parallel exit.
# With these in place, replicated parameters (layer norms, embeddings)
# receive identical, complete gradients on every rank of the axis, and
# sharded parameters receive exactly their local-slice gradients — no
# after-the-fact reduction guessing.

def _make_fg(axis_name):
    @jax.custom_vjp
    def f(x):
        return x

    def f_fwd(x):
        return x, None

    def f_bwd(_, ct):
        return (lax.psum(ct, axis_name),)

    f.defvjp(f_fwd, f_bwd)

    @jax.custom_vjp
    def g(x):
        return lax.psum(x, axis_name)

    def g_fwd(x):
        return lax.psum(x, axis_name), None

    def g_bwd(_, ct):
        return (ct,)

    g.defvjp(g_fwd, g_bwd)
    return f, g


# ---------------------------------------------------------------------------
# the per-device compute (runs INSIDE shard_map: all axes are bound)

def _ln(x, w, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * w + b


def _ring_attention(q, k, v, causal=True):
    """flash-style ring attention over the sp axis (local S/sp blocks)."""
    from .ring_attention import _ring_attention_impl
    return _ring_attention_impl(q, k, v, "sp", causal, None)


def _block(x, p, li, cfg):
    """One transformer block on LOCAL tensors. x: [mb, s_local, h].
    Megatron column/row parallel over tp with the f/g collective pair;
    row-parallel biases are added AFTER the psum (adding before would scale
    them by the tp size)."""
    f_tp, g_tp = _make_fg("tp")
    # attention — head-parallel over tp
    xa = _ln(x, p["ln1_w"][li], p["ln1_b"][li])
    xa = f_tp(xa)  # column-parallel entry
    wqkv = p["qkv_w"][li]           # [h, 3, nh_local, hd]
    qkv = jnp.einsum("bsh,hknd->bsknd", xa, wqkv) + p["qkv_b"][li]
    q = qkv[:, :, 0].transpose(0, 2, 1, 3)  # [mb, nh_local, s, hd]
    k = qkv[:, :, 1].transpose(0, 2, 1, 3)
    v = qkv[:, :, 2].transpose(0, 2, 1, 3)
    ctx = _ring_attention(q, k, v, causal=True)  # [mb, nh_local, s, hd]
    attn = g_tp(jnp.einsum("bnsd,ndh->bsh", ctx,
                           p["attn_out_w"][li]))  # row-parallel exit
    attn = attn + p["attn_out_b"][li]
    x = x + attn
    # ffn
    xf = _ln(x, p["ln2_w"][li], p["ln2_b"][li])
    xf = f_tp(xf)
    ff = jax.nn.gelu(xf @ p["ffn1_w"][li] + p["ffn1_b"][li])
    ff = g_tp(ff @ p["ffn2_w"][li])
    ff = ff + p["ffn2_b"][li]
    return x + ff


def _scale_grad(x, factor):
    """Forward identity, backward ct*factor — used to correct the ep-fold
    overcounting of expert-weight gradients (tokens are replicated over ep,
    so every rank's local loss reaches each expert through the all_to_all
    transpose; one copy's worth is the true gradient)."""
    @jax.custom_vjp
    def s(x):
        return x

    def s_fwd(x):
        return x, None

    def s_bwd(_, ct):
        return (jax.tree_util.tree_map(lambda c: c * factor, ct),)

    s.defvjp(s_fwd, s_bwd)
    return s(x)


def _moe_ffn(x, p, cfg):
    """Expert-parallel MoE ffn: top-1 routing + all_to_all over ep.
    x: [mb, s, h] -> same."""
    ep = _axis_size("ep")
    n_exp_local = cfg.n_experts
    n_exp = ep * n_exp_local
    mb, s, h = x.shape
    tokens = x.reshape(mb * s, h)
    logits = tokens @ p["moe_router"]  # [T, n_exp]
    gate = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(gate, axis=-1)  # [T]
    top_gate = jnp.max(gate, axis=-1)[:, None]
    # capacity-bucketed dispatch: each token goes to its expert's bucket
    cap = max(1, (mb * s) // n_exp * 2)
    # position of each token within its expert bucket
    onehot = jax.nn.one_hot(expert, n_exp, dtype=jnp.int32)  # [T, E]
    pos_in_exp = jnp.cumsum(onehot, axis=0) * onehot  # 1-based
    pos = jnp.sum(pos_in_exp, axis=-1) - 1  # [T]
    keep = (pos >= 0) & (pos < cap)
    # build dispatch buffer [E, cap, h] (E = global expert count)
    buf = jnp.zeros((n_exp, cap, h), x.dtype)
    buf = buf.at[expert, jnp.clip(pos, 0, cap - 1)].add(
        jnp.where(keep[:, None], tokens, 0.0))
    # route buckets to the rank owning each expert: split the dest-rank
    # axis, receive a sender-rank axis in the same place
    buf = buf.reshape(ep, n_exp_local, cap, h)
    expert_in = lax.all_to_all(buf, "ep", split_axis=0, concat_axis=0,
                               tiled=True)
    expert_in = expert_in.reshape(ep, n_exp_local, cap, h)
    # run local experts over every sender's bucket (expert weights carry a
    # 1/ep grad scale — see _scale_grad)
    w1 = _scale_grad(p["moe_w1"], 1.0 / ep)
    w2 = _scale_grad(p["moe_w2"], 1.0 / ep)

    def run_expert(e, t):  # t: [ep(sender), cap, h]
        hdn = jax.nn.gelu(t @ w1[e])
        return hdn @ w2[e]
    outs = jnp.stack([run_expert(e, expert_in[:, e])
                      for e in range(n_exp_local)], axis=1)
    # route results back: sender axis -> dest-rank axis again
    outs = lax.all_to_all(outs.reshape(ep, n_exp_local, cap, h), "ep",
                          split_axis=0, concat_axis=0, tiled=True)
    outs = outs.reshape(n_exp, cap, h)
    # gather tokens back
    back = outs[expert, jnp.clip(pos, 0, cap - 1)]
    back = jnp.where(keep[:, None], back, 0.0) * top_gate
    return x + back.reshape(mb, s, h)


def _stage_fn(x, stage_params, cfg, is_last):
    blk = None
    if cfg.remat is not None and cfg.remat != "none":
        from ..memory_plan import checkpoint_policy
        pol = checkpoint_policy(cfg.remat)
        # per-block checkpoint: the backward replays one block at a
        # time, so peak activation memory is one block's worth (plus
        # the saved block inputs) instead of layers_per_stage worths
        blk = jax.checkpoint(
            functools.partial(_block, cfg=cfg), policy=pol,
            static_argnums=(2,))
    for li in range(cfg.layers_per_stage):
        x = blk(x, stage_params, li) if blk is not None \
            else _block(x, stage_params, li, cfg)
    if is_last and cfg.use_moe:
        x = _moe_ffn(x, stage_params, cfg)
    return x


def _pipeline(x_micro, p_local, cfg):
    """GPipe over pp via ppermute: x_micro [n_micro, mb, s_local, h].
    Device at pp-rank r runs stage r; activations ride the ring."""
    n = _axis_size("pp")
    r = lax.axis_index("pp")
    n_micro = x_micro.shape[0]
    T = n_micro + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]
    is_last = r == n - 1

    def tick(carry, t):
        buf, outputs = carry
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        x_in = jnp.where(r == 0, x_micro[mb_idx], buf)
        y = _stage_fn(x_in, p_local, cfg,
                      is_last=False)  # moe applied after pipeline
        valid = (t - r >= 0) & (t - r < n_micro)
        y = jnp.where(valid, y, jnp.zeros_like(y))
        out_idx = jnp.clip(t - (n - 1), 0, n_micro - 1)
        write = is_last & (t - (n - 1) >= 0)
        outputs = outputs.at[out_idx].set(
            jnp.where(write, y, outputs[out_idx]))
        buf_next = lax.ppermute(y, "pp", perm)
        return (buf_next, outputs), None

    buf0 = jnp.zeros_like(x_micro[0])
    out0 = jnp.zeros_like(x_micro)
    (_, outputs), _ = lax.scan(tick, (buf0, out0), jnp.arange(T))
    # Replicate final outputs to every pp rank (loss computed everywhere).
    # MUST be the g-collective, not a raw psum: with check_vma off, the
    # transpose of a raw psum re-psums the already-replicated cotangent and
    # every upstream gradient gets multiplied by the pp size.
    _, g_pp = _make_fg("pp")
    outputs = g_pp(jnp.where(is_last, outputs, jnp.zeros_like(outputs)))
    return outputs


def _loss_fn(params_local, tokens, cfg):
    """Per-device loss. tokens: [n_micro, mb, s_local+?]... tokens are the
    LOCAL slice [n_micro, mb, s_local] of input ids; labels are the shifted
    ids (computed globally before sharding — here next-token within the
    local block for simplicity of the dryrun)."""
    sp = _axis_size("sp")
    sp_r = lax.axis_index("sp")
    s_local = tokens.shape[-1]
    h = cfg.hidden

    # embedding (replicated table, local positions offset by sp rank).
    # f_pp: the pipeline injects this only on pp rank 0, so the injection
    # gradient exists only there — psum on the backward pass hands the
    # complete embed/pos gradient to every pp rank, keeping the replicated
    # tables in sync.
    f_pp, _ = _make_fg("pp")
    pos_idx = sp_r * s_local + jnp.arange(s_local)
    x = params_local["embed"][tokens] + params_local["pos"][pos_idx]
    x = f_pp(x)

    # pipeline over stacked stage params: shard_map gives each pp rank its
    # stage slice with leading dim 1 — drop it
    stage_params = {k: v[0] for k, v in params_local.items()
                    if k not in ("embed", "pos", "lnf_w", "lnf_b",
                                 "moe_router", "moe_w1", "moe_w2")}
    if cfg.use_moe:
        stage_params["moe_router"] = params_local["moe_router"]
        stage_params["moe_w1"] = params_local["moe_w1"][0]
        stage_params["moe_w2"] = params_local["moe_w2"][0]

    y = _pipeline(x, stage_params, cfg)
    if cfg.use_moe:
        y = _moe_ffn(y.reshape(-1, *y.shape[2:]), stage_params, cfg
                     ).reshape(y.shape)
    y = _ln(y, params_local["lnf_w"], params_local["lnf_b"])
    logits = jnp.einsum("...h,vh->...v", y, params_local["embed"])

    # next-token loss. The label of a local block's LAST position is the
    # FIRST token of the next sp shard — fetched with one ppermute over the
    # sp ring (a roll within the local block would pair sequence-boundary
    # tokens with wrong labels). Only the globally-last position has no
    # label.
    logp = jax.nn.log_softmax(logits, axis=-1)
    first_tok = tokens[..., :1]
    sp_perm = [(i, (i - 1) % sp) for i in range(sp)]  # rank r+1 -> r
    next_first = lax.ppermute(first_tok, "sp", sp_perm)
    labels = jnp.concatenate([tokens[..., 1:], next_first], axis=-1)
    picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    is_last_sp = (sp_r == sp - 1)
    mask = jnp.ones_like(picked).at[..., -1].set(
        jnp.where(is_last_sp, 0.0, 1.0))
    # global token-weighted mean: psum numerator/denominator over the axes
    # that split tokens (sp), then average over dp
    num = lax.psum(-jnp.sum(picked * mask), "sp")
    den = lax.psum(jnp.sum(mask), "sp")
    loss = num / jnp.maximum(den, 1.0)
    loss = lax.pmean(loss, "dp")
    return loss


def _build_flat_train_step(cfg: MegatronConfig, mesh: Mesh, params):
    """flat_arena=True path: every (replicated) param leaf lives in one
    contiguous f32 buffer. The loss fn differentiates the BUFFER — slices
    and reshapes are views XLA resolves in-register, and the transpose
    writes each leaf's cotangent straight into one flat gradient, so the
    dp sync and the adam update both run on a single 1-D array with zero
    gather/concat traffic. state = {"flat", "opt": {"m", "v"}, "t"};
    step.layout / step.unpack recover the per-leaf view."""
    keys = sorted(params)
    layout, off = [], 0
    for k in keys:
        n = int(np.prod(params[k].shape))
        layout.append((k, off, n, tuple(params[k].shape)))
        off += n
    total = off
    pad = (-total) % 128  # lane-align so the fused flat kernel is eligible
    flat0 = jnp.concatenate(
        [jnp.ravel(params[k]).astype(jnp.float32) for k in keys]
        + ([jnp.zeros((pad,), jnp.float32)] if pad else []))
    buf_n = total + pad
    flat0 = jax.device_put(flat0, NamedSharding(mesh, P()))
    state = {"flat": flat0,
             "opt": {"m": jnp.zeros_like(flat0),
                     "v": jnp.zeros_like(flat0)},
             "t": jnp.zeros((), jnp.int32)}
    state_spec = {"flat": P(), "opt": {"m": P(), "v": P()}, "t": P()}

    # bucket bounds: contiguous lane-aligned slices of the arena, sized by
    # grad_bucket_bytes — the scheduler's bucket plan degenerates to plain
    # index arithmetic on a flat buffer
    per = max(128, (max(1, int(cfg.grad_bucket_bytes)) // 4 // 128) * 128)
    bounds = [(i, min(i + per, buf_n)) for i in range(0, buf_n, per)]

    def unpack(flat):
        return {k: flat[o:o + n].reshape(shape)
                for k, o, n, shape in layout}

    def device_fn(state, tokens_local):
        def lf(flat):
            return _loss_fn(unpack(flat), tokens_local, cfg)
        loss, flat_g = jax.value_and_grad(lf)(state["flat"])
        mode = cfg.grad_sync
        if cfg.quantized_grad_allreduce and mode == "exact":
            mode = "quantized"  # legacy knob
        if mode == "exact":
            flat_g = lax.pmean(flat_g, "dp")
        else:
            from .overlap import sync_arena_flat
            flat_g = sync_arena_flat(flat_g, bounds, axis_name="dp",
                                     mode=mode, bits=cfg.grad_bits)
        flat_g = lax.pmean(flat_g, "sp")
        t = state["t"] + 1
        tf = t.astype(jnp.float32)
        b1p = jnp.power(cfg.beta1, tf)
        b2p = jnp.power(cfg.beta2, tf)
        from ..ops.pallas.fused_adam import adam_step_flat
        new_flat, new_m, new_v = adam_step_flat(
            state["flat"], flat_g, state["opt"]["m"], state["opt"]["v"],
            cfg.lr, b1p, b2p, beta1=cfg.beta1, beta2=cfg.beta2,
            eps=cfg.adam_eps)
        return ({"flat": new_flat, "opt": {"m": new_m, "v": new_v},
                 "t": t}, loss)

    token_spec = P(None, "dp", "sp")
    from .collective import shard_map_compat
    jstep = jax.jit(
        shard_map_compat(device_fn, mesh=mesh,
                         in_specs=(state_spec, token_spec),
                         out_specs=(state_spec, P()),
                         check_vma=False),
        donate_argnums=(0,))

    def step(state, tokens):
        return jstep(state, tokens)
    step.layout = tuple(layout)
    step.unpack = unpack
    return state, step


# configs (by repr — always hashable, even when mesh_plan carries
# unhashable spec forms) that have already warned about the flat-arena
# fallback. Every fallback still counts in arena.flat_fallback so the
# planner and dashboards see the rate; only the first one per config
# warns.
_flat_fallback_warned = set()


def _warn_flat_fallback(cfg):
    from .. import monitor as _monitor
    _monitor.counter("arena.flat_fallback").inc()
    key = repr(cfg)
    if key in _flat_fallback_warned:
        return
    _flat_fallback_warned.add(key)
    import warnings
    warnings.warn(
        "MegatronConfig.flat_arena requires tp == pp == ep == 1 and "
        "optimizer='adam' (sharded params can't share one replicated "
        "buffer); falling back to the per-leaf path. Counted in "
        "arena.flat_fallback; this config will not warn again.",
        RuntimeWarning, stacklevel=3)


def build_train_step(cfg: MegatronConfig, mesh: Mesh):
    """Returns (state, step_fn). step_fn(state, tokens) -> (state, loss).
    state = {"params", "opt", "t"}; tokens: GLOBAL [n_micro, batch,
    seq_len] int32.

    The update rule is the REAL optimizer compute path (reference: fleet
    distributed_optimizer wrapping Adam/SGD): "adam" runs the same fused
    Pallas adam kernel Optimizer.Adam uses (ops/pallas/fused_adam.py) on
    each param's local shard, slot state sharded exactly like its param.

    cfg.flat_arena=True switches dp/sp-only meshes to the flat parameter
    arena layout (see _build_flat_train_step); state then carries "flat"
    instead of "params"."""
    plan = None
    if cfg.mesh_plan is not None:
        from .planner import MeshPlan
        plan = (cfg.mesh_plan if isinstance(cfg.mesh_plan, MeshPlan)
                else MeshPlan(cfg.mesh_plan, mesh=mesh))
    params, specs = init_params(cfg, mesh, plan=plan)

    if cfg.flat_arena:
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        if (sizes["tp"] == sizes["pp"] == sizes["ep"] == 1
                and cfg.optimizer == "adam"):
            return _build_flat_train_step(cfg, mesh, params)
        _warn_flat_fallback(cfg)

    pspec_tree = {k: specs[k] for k in params}
    if cfg.optimizer == "adam":
        opt0 = {k: {"m": jnp.zeros_like(v), "v": jnp.zeros_like(v)}
                for k, v in params.items()}
        opt_spec = {k: {"m": pspec_tree[k], "v": pspec_tree[k]}
                    for k in params}
    elif cfg.optimizer == "sgd":
        opt0, opt_spec = {}, {}
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    state = {"params": params, "opt": opt0,
             "t": jnp.zeros((), jnp.int32)}
    state_spec = {"params": pspec_tree, "opt": opt_spec, "t": P()}

    def _adam_update(p, g, slots, b1p, b2p):
        from ..ops.pallas.fused_adam import adam_step
        new_p, m, v = adam_step(p, g, slots["m"], slots["v"], cfg.lr,
                                b1p, b2p, beta1=cfg.beta1, beta2=cfg.beta2,
                                eps=cfg.adam_eps)
        return new_p, {"m": m, "v": v}

    def device_fn(state, tokens_local):
        params_local = state["params"]

        def lf(p):
            return _loss_fn(p, tokens_local, cfg)
        loss, grads = jax.value_and_grad(lf)(params_local)
        # dp/sp gradient reduction: replicated params need their grads
        # summed over every axis that splits the *batch/sequence*, i.e. the
        # reference's c_allreduce on NCCL — here psum over dp and sp (tp/pp/
        # ep-sharded params already got their grads via their own psums in
        # the forward transpose).
        mode = cfg.grad_sync
        if cfg.quantized_grad_allreduce and mode == "exact":
            mode = "quantized"  # legacy knob
        if mode == "exact":
            grads = jax.tree_util.tree_map(
                lambda g: lax.pmean(lax.pmean(g, "dp"), "sp"), grads)
        else:
            # bucketed (optionally quantized-ring) dp sync with
            # op="mean" — the mean happens inside the collective, no
            # hand-division by the axis size here
            from .overlap import sync_tree
            grads = sync_tree(
                grads, axis_name="dp", mode=mode, bits=cfg.grad_bits,
                bucket_bytes=cfg.grad_bucket_bytes, op="mean",
                extra_mean_axes=("sp",))
        t = state["t"] + 1
        if cfg.optimizer == "adam":
            tf = t.astype(jnp.float32)
            b1p = jnp.power(cfg.beta1, tf)
            b2p = jnp.power(cfg.beta2, tf)
            from ..ops import pallas as _P
            if _P.enabled("fused_adam_multi"):
                # same multi-tensor rule as Optimizer.Adam: one dispatch
                # over every LOCAL shard (slot state sharded like params)
                from ..ops.pallas.fused_adam import fused_adam_update_multi
                keys = list(params_local)
                nps, nms, nvs = fused_adam_update_multi(
                    [params_local[k] for k in keys],
                    [grads[k] for k in keys],
                    [state["opt"][k]["m"] for k in keys],
                    [state["opt"][k]["v"] for k in keys],
                    cfg.lr, b1p, b2p, beta1=cfg.beta1, beta2=cfg.beta2,
                    eps=cfg.adam_eps)
                new_params = dict(zip(keys, nps))
                new_opt = {k: {"m": m, "v": v}
                           for k, m, v in zip(keys, nms, nvs)}
            else:
                new_params, new_opt = {}, {}
                for k in params_local:
                    new_params[k], new_opt[k] = _adam_update(
                        params_local[k], grads[k], state["opt"][k], b1p,
                        b2p)
        else:
            new_params = jax.tree_util.tree_map(
                lambda p, g: p - cfg.lr * g, params_local, grads)
            new_opt = state["opt"]
        # `loss` is already the GLOBAL token-weighted mean: _loss_fn psums
        # num/den over sp and pmeans over dp, so every rank holds the same
        # value and out_spec P() is sound without further collectives
        return {"params": new_params, "opt": new_opt, "t": t}, loss

    # tokens: [n_micro, batch, seq]: batch over dp, seq over sp
    token_spec = P(None, "dp", "sp")

    from .collective import shard_map_compat
    step = jax.jit(
        shard_map_compat(
            device_fn, mesh=mesh,
            in_specs=(state_spec, token_spec),
            out_specs=(state_spec, P()),
            check_vma=False),
        donate_argnums=(0,))
    return state, step
