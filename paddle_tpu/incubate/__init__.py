"""paddle_tpu.incubate (reference: python/paddle/incubate — hapi +
complex). Complex arithmetic rides jnp's native complex dtypes, so the
reference's separate ComplexVariable kernel set collapses into the
ordinary ops."""
from .. import hapi  # noqa: F401
from . import complex  # noqa: F401
from . import data_generator  # noqa: F401
