"""incubate.data_generator — the CTR data-generator protocol (reference:
python/paddle/fluid/incubate/data_generator/__init__.py:21 DataGenerator,
MultiSlotDataGenerator, MultiSlotStringDataGenerator).

Users subclass and implement ``generate_sample(line)`` returning a
generator of (slot_name, values) tuples; ``run_from_stdin`` /
``run_from_memory`` emit the MultiSlot text protocol consumed by the
dataset feeders (and by the reference's C++ DataFeed — the wire format is
kept byte-compatible so existing ETL pipelines keep working)."""
from __future__ import annotations

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    """reference: data_generator/__init__.py:21."""

    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    # -- user hooks ---------------------------------------------------------
    def generate_sample(self, line):
        """Override: return a generator yielding one or more samples,
        each a list/tuple of (slot_name, value_list) pairs."""
        raise NotImplementedError(
            "implement generate_sample(self, line) in your subclass")

    def generate_batch(self, samples):
        """Override optionally: batch-level postprocess; yields samples."""
        for s in samples:
            yield s

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    # -- drivers ------------------------------------------------------------
    def run_from_stdin(self):
        """Read lines from stdin, write protocol lines to stdout (the
        shape MapReduce-style ETL invokes)."""
        batch = []
        for line in sys.stdin:
            for sample in self._samples_of(line):
                batch.append(sample)
                if len(batch) >= self.batch_size_:
                    self._flush(batch)
                    batch = []
        if batch:
            self._flush(batch)

    def run_from_memory(self):
        """Self-test driver: generate_sample(None) repeatedly."""
        batch = []
        for sample in self._samples_of(None):
            batch.append(sample)
            if len(batch) >= self.batch_size_:
                self._flush(batch)
                batch = []
        if batch:
            self._flush(batch)

    # -- internals ----------------------------------------------------------
    def _samples_of(self, line):
        gen = self.generate_sample(line)
        if gen is None:
            return
        for sample in gen() if callable(gen) else gen:
            if sample is not None:
                yield sample

    def _flush(self, batch):
        for sample in self.generate_batch(batch):
            sys.stdout.write(self._gen_str(sample))

    def _gen_str(self, line):
        raise NotImplementedError


class MultiSlotDataGenerator(DataGenerator):
    """Numeric slots: ``<n> v1 .. vn`` per slot, space-joined
    (reference MultiSlotDataGenerator._gen_str)."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError("sample must be a list of "
                             "(slot_name, values) pairs")
        parts = []
        for _name, values in line:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    """String slots: same framing, values passed through as strings."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError("sample must be a list of "
                             "(slot_name, values) pairs")
        parts = []
        for _name, values in line:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"
