"""incubate.complex (reference: python/paddle/incubate/complex — a
parallel op set for ComplexVariable). jnp handles complex64/128 natively:
these wrappers exist for API parity and simply call the regular ops,
which accept complex inputs."""
from ..ops.math import matmul, kron, trace, sum, multiply, divide  # noqa
from ..ops.manip import reshape, transpose  # noqa: F401


def elementwise_add(x, y, axis=-1, name=None):
    return x + y


def elementwise_sub(x, y, axis=-1, name=None):
    return x - y


def elementwise_mul(x, y, axis=-1, name=None):
    return x * y


def elementwise_div(x, y, axis=-1, name=None):
    return x / y
