"""paddle_tpu.distributed — multi-host launch/env.

TPU-native rebuild of reference python/paddle/distributed/launch.py +
fluid.dygraph parallel init: instead of spawning one proc per GPU and
wiring NCCL ids, each TPU host runs the same program and
`jax.distributed.initialize` joins the pod (coordinator from env).
"""
from __future__ import annotations

import os

import jax

from ..parallel.env import ParallelEnv
from ..parallel import collective, fleet as _fleet_mod
from ..parallel.collective import all_reduce, all_gather, broadcast, barrier

_initialized = False


def init_parallel_env(coordinator_address=None, num_processes=None,
                      process_id=None):
    """reference: paddle.distributed.init_parallel_env / launch.py env
    wiring. Single-host: no-op (the mesh covers local devices). Multi-host:
    jax.distributed.initialize with coordinator from args or env
    (COORDINATOR_ADDRESS / PADDLE_TRAINER_ENDPOINTS[0])."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    addr = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if addr is None:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        addr = eps.split(",")[0] if eps else None
    nproc = num_processes or int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = process_id if process_id is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", "0"))
    if addr and nproc > 1:
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=nproc, process_id=pid)
    _initialized = True
    return ParallelEnv()


def get_rank():
    return jax.process_index()


def get_world_size():
    return jax.process_count()


def spawn(func, args=(), nprocs=1, **kwargs):
    """reference: paddle.distributed.spawn. On TPU the runtime is
    single-controller SPMD — one process drives all local chips — so spawn
    degenerates to a direct call (parallelism comes from the mesh)."""
    return func(*args)


class launch:
    """Placeholder namespace mirroring `python -m paddle.distributed.launch`;
    on TPU pods each host starts the same script (GKE/tpu-vm convention)."""
    pass
from . import utils  # noqa: F401,E402
from . import cloud_utils  # noqa: F401,E402
