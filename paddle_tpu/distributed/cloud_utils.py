"""paddle.distributed.cloud_utils parity (reference:
python/paddle/distributed/cloud_utils.py) — cluster description from
PADDLE_* cloud environment variables."""
import os

from .utils import get_cluster, get_logger, get_trainers_num  # noqa: F401

logger = get_logger(20, "root")


def get_cloud_cluster(args_node_ips=None, args_node_ip=None,
                      args_port=None, selected_accelerators=None):
    """reference cloud_utils.py:20 — derive the cluster from the cloud
    env (PADDLE_TRAINERS / POD_IP / PADDLE_PORT), falling back to the
    passed args."""
    node_ips = os.getenv("PADDLE_TRAINERS", args_node_ips or "127.0.0.1")
    if isinstance(node_ips, str):
        node_ips = node_ips.split(",")
    node_ip = os.getenv("POD_IP", args_node_ip or node_ips[0])
    port = int(os.getenv("PADDLE_PORT", args_port or 8071))
    accs = selected_accelerators or [0]
    ports = [port + i for i in range(len(accs))]
    return get_cluster(node_ips, node_ip, ports, accs)
