"""paddle.distributed.fs_wrapper parity (reference:
python/paddle/distributed/fs_wrapper.py) — filesystem abstraction the
fleet checkpoint utilities write through. LocalFS is fully functional;
BDFS (the reference's Baidu-HDFS client wrapper) has no reachable
backend here and raises with direction instead of half-working."""
import abc
import os
import shutil

__all__ = ["FS", "LocalFS", "BDFS"]


class FS(abc.ABC):
    """reference fs_wrapper.py:FS — the abstract surface."""

    @abc.abstractmethod
    def list_dirs(self, fs_path):
        ...

    @abc.abstractmethod
    def ls_dir(self, fs_path):
        ...

    @abc.abstractmethod
    def stat(self, fs_path):
        ...

    @abc.abstractmethod
    def upload(self, local_path, fs_path):
        ...

    @abc.abstractmethod
    def download(self, fs_path, local_path):
        ...

    @abc.abstractmethod
    def mkdir(self, fs_path):
        ...

    @abc.abstractmethod
    def mv(self, fs_src_path, fs_dst_path):
        ...

    @abc.abstractmethod
    def rmr(self, fs_path):
        ...

    @abc.abstractmethod
    def rm(self, fs_path):
        ...

    @abc.abstractmethod
    def delete(self, fs_path):
        ...

    @abc.abstractmethod
    def need_upload_download(self):
        ...


class LocalFS(FS):
    """reference fs_wrapper.py:LocalFS — the local filesystem."""

    def list_dirs(self, fs_path):
        if not self.stat(fs_path):
            return []
        return [d for d in os.listdir(fs_path)
                if os.path.isdir(os.path.join(fs_path, d))]

    def ls_dir(self, fs_path):
        return os.listdir(fs_path) if self.stat(fs_path) else []

    def stat(self, fs_path):
        return os.path.exists(fs_path)

    def upload(self, local_path, fs_path):
        self.mv(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def mkdir(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def mv(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def rmr(self, fs_path):
        shutil.rmtree(fs_path, ignore_errors=True)

    def rm(self, fs_path):
        if os.path.exists(fs_path):
            os.remove(fs_path)

    def delete(self, fs_path):
        if not self.stat(fs_path):
            return
        if os.path.isdir(fs_path):
            self.rmr(fs_path)
        else:
            self.rm(fs_path)

    def need_upload_download(self):
        return False


class BDFS(FS):
    """reference fs_wrapper.py:BDFS — wraps a configured HDFS client.
    No such client exists in this environment; constructing one is an
    explicit error (checkpointing to shared storage goes through orbax
    / io.save with a mounted path instead)."""

    def __init__(self, hdfs_name=None, hdfs_ugi=None, time_out=20 * 60,
                 sleep_inter=1000):
        raise RuntimeError(
            "BDFS wraps the reference's Baidu-HDFS client, which is not "
            "present. Use LocalFS over a mounted/shared path, or orbax "
            "sharded checkpoints (paddle_tpu.io) for distributed "
            "storage.")

    # abstract-method stubs so the class is well-formed
    def list_dirs(self, fs_path):  # pragma: no cover
        ...

    ls_dir = stat = upload = download = mkdir = mv = rmr = rm = delete = \
        need_upload_download = list_dirs
