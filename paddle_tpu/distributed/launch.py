"""paddle_tpu.distributed.launch — multi-host launch CLI.

TPU-native rebuild of reference python/paddle/distributed/launch.py. The
reference spawns one worker process per GPU and wires NCCL endpoints; on a
TPU pod each HOST runs one process that owns its local chips, so launch
degenerates to: set the coordinator env, call jax.distributed.initialize,
exec the training script. Usage:

    # one invocation per host (pod):
    python -m paddle_tpu.distributed.launch \
        --coordinator 10.0.0.1:8476 --num_hosts 4 --host_id 0 train.py ...

    # or reference-style local spawn (N processes on THIS machine, each a
    # jax.distributed participant — cross-process collectives ride the
    # same code path a pod's DCN does):
    python -m paddle_tpu.distributed.launch --nproc_per_node 2 train.py

Single-host (the common case, incl. this repo's CI): just runs the script.
"""
from __future__ import annotations

import argparse
import os
import runpy
import socket
import subprocess
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--coordinator", default=None,
                   help="coordinator address host:port (multi-host)")
    p.add_argument("--num_hosts", type=int, default=1)
    p.add_argument("--host_id", type=int, default=None)
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="spawn N local worker processes (reference "
                        "launch.py behavior); each becomes one "
                        "jax.distributed process")
    p.add_argument("script", help="training script to run")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_local(args):
    """Reference-style local fan-out: N child processes, auto coordinator,
    failure of any child fails the launch FAST (a dead rank would leave
    the others blocked in the jax.distributed rendezvous forever, so the
    parent polls all children and tears the group down on the first bad
    exit)."""
    import time

    if os.environ.get("PALLAS_AXON_POOL_IPS") or os.environ.get(
            "TPU_NAME"):
        if os.environ.get("JAX_PLATFORMS", "").lower() != "cpu":
            raise SystemExit(
                "--nproc_per_node > 1 on a TPU host: libtpu is "
                "single-owner per process — a TPU pod runs ONE process "
                "per host (use --coordinator/--num_hosts/--host_id, one "
                "launch per host). Set JAX_PLATFORMS=cpu to fan out CPU "
                "worker processes on this machine.")
    port = _free_port()
    procs = []
    for rank in range(args.nproc_per_node):
        env = dict(os.environ)
        env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["PADDLE_TRAINERS_NUM"] = str(args.nproc_per_node)
        env["PADDLE_TRAINER_ID"] = str(rank)
        env["PADDLE_LOCAL_RANK"] = str(rank)
        cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
               "--coordinator", f"127.0.0.1:{port}",
               "--num_hosts", str(args.nproc_per_node),
               "--host_id", str(rank), args.script] + args.script_args
        procs.append(subprocess.Popen(cmd, env=env))
    try:
        while True:
            codes = [p.poll() for p in procs]
            bad = next((c for c in codes if c not in (None, 0)), None)
            if bad is not None:
                raise SystemExit(bad)
            if all(c == 0 for c in codes):
                return
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()


def main(argv=None):
    args = parse_args(argv)
    if args.nproc_per_node > 1:
        if args.coordinator is not None or args.num_hosts != 1 or \
                args.host_id is not None:
            raise SystemExit(
                "--nproc_per_node cannot combine with --coordinator/"
                "--num_hosts/--host_id: the process model is one "
                "jax.distributed participant per process — either local "
                "fan-out (--nproc_per_node alone) or one launch per host "
                "(--coordinator/--num_hosts/--host_id)")
        _spawn_local(args)
        return
    if args.coordinator and args.num_hosts > 1:
        os.environ["COORDINATOR_ADDRESS"] = args.coordinator
        os.environ["PADDLE_TRAINERS_NUM"] = str(args.num_hosts)
        if args.host_id is not None:
            os.environ["PADDLE_TRAINER_ID"] = str(args.host_id)
        from . import init_parallel_env
        init_parallel_env()
    sys.argv = [args.script] + args.script_args
    runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
