"""paddle_tpu.distributed.launch — multi-host launch CLI.

TPU-native rebuild of reference python/paddle/distributed/launch.py. The
reference spawns one worker process per GPU and wires NCCL endpoints; on a
TPU pod each HOST runs one process that owns its local chips, so launch
degenerates to: set the coordinator env, call jax.distributed.initialize,
exec the training script. Usage:

    python -m paddle_tpu.distributed.launch \
        --coordinator 10.0.0.1:8476 --num_hosts 4 --host_id 0 train.py ...

Single-host (the common case, incl. this repo's CI): just runs the script.
"""
from __future__ import annotations

import argparse
import os
import runpy
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--coordinator", default=None,
                   help="coordinator address host:port (multi-host)")
    p.add_argument("--num_hosts", type=int, default=1)
    p.add_argument("--host_id", type=int, default=None)
    p.add_argument("script", help="training script to run")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.coordinator and args.num_hosts > 1:
        os.environ["COORDINATOR_ADDRESS"] = args.coordinator
        os.environ["PADDLE_TRAINERS_NUM"] = str(args.num_hosts)
        if args.host_id is not None:
            os.environ["PADDLE_TRAINER_ID"] = str(args.host_id)
        from . import init_parallel_env
        init_parallel_env()
    sys.argv = [args.script] + args.script_args
    runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
