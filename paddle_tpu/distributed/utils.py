"""paddle.distributed.utils parity (reference:
python/paddle/distributed/utils.py — Cluster/Pod/Trainer descriptors and
the launch helpers). The TPU launch path delegates process management to
`jax.distributed` (one process per host); these classes describe the
topology for ported tooling."""
from __future__ import annotations

import logging
import os
import socket
from contextlib import closing


class Trainer:
    """reference distributed/utils.py:131."""

    def __init__(self):
        self.accelerators = []
        self.endpoint = None
        self.rank = None

    def __str__(self):
        return (f"accelerators:{self.accelerators} endpoint:{self.endpoint}"
                f" rank:{self.rank}")


class Pod:
    """reference distributed/utils.py:162 — one host's process group."""

    def __init__(self):
        self.rank = None
        self.id = None
        self.addr = None
        self.port = None
        self.trainers = []

    def __str__(self):
        return (f"rank:{self.rank} id:{self.id} addr:{self.addr} "
                f"port:{self.port} visible_accelerators:"
                f"{[str(t) for t in self.trainers]}")


class Cluster:
    """reference distributed/utils.py:55."""

    def __init__(self, hdfs=None):
        self.job_server = None
        self.pods = []
        self.hdfs = hdfs

    def trainers_nranks(self):
        return len(self.trainers_endpoints())

    def trainers_endpoints(self):
        eps = []
        for pod in self.pods:
            for t in pod.trainers:
                eps.append(t.endpoint)
        return eps

    def pods_endpoints(self):
        return [f"{p.addr}:{p.port}" for p in self.pods]


class JobServer:
    def __init__(self):
        self.endpoint = None


class Hdfs:
    def __init__(self):
        self.hdfs_ugi = None
        self.hdfs_name = None
        self.hdfs_path = None

    def is_valid(self):
        return (self.hdfs_ugi is not None and self.hdfs_name is not None
                and self.hdfs_path is not None)


def get_logger(log_level=20, name="root"):
    """reference distributed/utils.py:217."""
    logger = logging.getLogger(name)
    logger.setLevel(log_level)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s-%(levelname)s: %(message)s"))
        logger.addHandler(h)
    return logger


def get_cluster(node_ips, node_ip, paddle_ports, selected_accelerators):
    """reference distributed/utils.py:230 — build the Cluster/Pod/Trainer
    description from host lists."""
    cluster = Cluster()
    rank = 0
    for pod_id, ip in enumerate(node_ips):
        pod = Pod()
        pod.rank = pod_id
        pod.id = pod_id
        pod.addr = ip
        pod.port = paddle_ports[0] if paddle_ports else 8071
        for i, acc in enumerate(selected_accelerators):
            t = Trainer()
            t.accelerators = [acc]
            port = paddle_ports[i] if i < len(paddle_ports) else \
                pod.port + i
            t.endpoint = f"{ip}:{port}"
            t.rank = rank
            rank += 1
            pod.trainers.append(t)
        cluster.pods.append(pod)
    return cluster, cluster.pods[node_ips.index(node_ip)]


def get_host_name_ip():
    """reference distributed/utils.py:281."""
    try:
        host = socket.gethostname()
        return host, socket.gethostbyname(socket.getfqdn(host))
    except OSError:
        return None


def find_free_ports(num):
    """reference distributed/utils.py:307."""
    ports = set()
    step = 0
    while len(ports) < num:
        with closing(socket.socket(socket.AF_INET,
                                   socket.SOCK_STREAM)) as s:
            s.bind(("", 0))
            ports.add(s.getsockname()[1])
        step += 1
        if step > 400:
            return None
    return ports


def add_arguments(argname, type, default, help, argparser, **kwargs):
    """reference distributed/utils.py:290 — argparse helper."""
    argparser.add_argument(
        "--" + argname, default=default, type=type,
        help=help + f" Default: {default}.", **kwargs)


def terminate_local_procs(procs):
    """reference distributed/utils.py:252."""
    for p in procs:
        proc = getattr(p, "proc", p)
        if proc is not None and proc.poll() is None:
            proc.terminate()


class TrainerProc:
    def __init__(self):
        self.proc = None
        self.log_fn = None
        self.rank = None
        self.cmd = None


def get_trainers_num():
    """reference distributed/cloud_utils.py:79."""
    return int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
