"""paddle_tpu.serving.sampling — vectorized slot-level token sampling
and the speculative accept-prefix rule.

Two design constraints drive everything here, both inherited from the
decode engine's shape discipline (serving/generate.py):

* **Batch-shaped knobs, zero new executables.** Temperature / top-k /
  top-p / seed are *data*, not trace constants: they enter the fused
  decode step as ``[slots]``-shaped arrays, so a batch mixing greedy
  and sampled sequences at different temperatures runs the SAME
  executable that pure-greedy traffic does. ``temperature <= 0`` means
  greedy (argmax) for that row; ``top_k <= 0`` and ``top_p == 1.0``
  disable their filters. Nothing about a request's sampling config can
  mint a trace.

* **Counter-based keys, not a key stream.** The key for every random
  decision is derived statelessly as::

      fold_in(PRNGKey(request_seed), position * N_SALTS + salt)

  where ``position`` is the token's *generation index* (0 = the token
  the prefill emits) and ``salt`` picks the decision kind
  (:data:`SALT_TOKEN` for the token draw, :data:`SALT_ACCEPT` for the
  speculative accept test, :data:`SALT_RESID` for the residual
  resample). Because the key is a pure function of
  ``(seed, position, salt)``, a sequence's token stream is
  bit-reproducible no matter which tick admitted it, which replica ran
  it, how it was batched, or whether failover re-prefilled it from
  scratch — the property the failover/requeue and hedging paths lean
  on now that decode is no longer greedy-only.

The speculative primitives (:func:`accept_prefix`) implement the
standard draft-verify rule: accept draft proposal ``d_i ~ q_i`` while
``u_i * q_i(d_i) <= p_i(d_i)`` and resample the first rejected
position from the normalized residual ``max(p - q, 0)``. Per position
the emitted marginal is ``q(x) * min(1, p(x)/q(x)) + P(reject) *
resid(x) = p(x)``, so the emitted stream is *distributionally exact*
against non-speculative sampling of the target — and because the
proposal draw at generation index ``g`` consumes exactly the
``(seed, g, SALT_TOKEN)`` key the non-speculative path would, a
self-draft (q == p) reproduces the non-speculative stream token for
token. tests/test_spec_decode.py carries the chi-squared proof
obligation; docs/serving.md states the guarantee.
"""
from __future__ import annotations

import numpy as np

# Filtered-out logits get this, not -inf: -inf arithmetic breeds NaNs
# under XLA (0 * -inf in masked softmax backward paths) while exp(-1e30)
# is exactly 0.0 in float32.
NEG = -1e30

# Salt per random-decision kind; the per-position counter is
# position * N_SALTS + salt, so decision kinds never collide and
# positions stay independent.
SALT_TOKEN = 0       # the token draw itself (sampled decode + proposals)
SALT_ACCEPT = 1      # speculative accept test u_i
SALT_RESID = 2       # residual resample at the first rejected position
N_SALTS = 4          # room to grow without re-keying history


class SamplingParams:
    """One request's decode-sampling config.

    ``temperature <= 0`` selects greedy (argmax) decode and the other
    knobs are ignored. ``top_k <= 0`` disables the top-k filter;
    ``top_p`` must sit in (0, 1] and ``1.0`` disables the nucleus
    filter. ``seed`` is the per-request PRNG root — two requests with
    the same prompt, params, and seed produce bit-identical streams on
    any replica; ``None`` lets the engine assign a fresh one at
    ``make_request`` time (recorded on the request so failover and
    hedge shadows replay identically).
    """

    __slots__ = ("temperature", "top_k", "top_p", "seed")

    def __init__(self, temperature=0.0, top_k=0, top_p=1.0, seed=None):
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        if not (0.0 < self.top_p <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if seed is not None:
            seed = int(seed)
            if seed < 0:
                raise ValueError(f"seed must be >= 0, got {seed}")
        self.seed = seed

    @property
    def greedy(self):
        return self.temperature <= 0.0

    def __eq__(self, other):
        return (isinstance(other, SamplingParams)
                and self.temperature == other.temperature
                and self.top_k == other.top_k
                and self.top_p == other.top_p
                and self.seed == other.seed)

    def __repr__(self):
        return (f"SamplingParams(temperature={self.temperature}, "
                f"top_k={self.top_k}, top_p={self.top_p}, "
                f"seed={self.seed})")


GREEDY = SamplingParams()


def resolve(sampling=None, seed=None):
    """Normalize the ``sampling=`` submit knob into
    :class:`SamplingParams`: None (greedy), a dict of knob overrides,
    or a ready-made params object. ``seed=`` overrides the params'
    own seed either way."""
    if sampling is None:
        params = SamplingParams()
    elif isinstance(sampling, SamplingParams):
        params = SamplingParams(sampling.temperature, sampling.top_k,
                                sampling.top_p, sampling.seed)
    elif isinstance(sampling, dict):
        params = SamplingParams(**sampling)
    else:
        raise TypeError(
            f"sampling must be None, a dict, or SamplingParams — "
            f"got {type(sampling).__name__}")
    if seed is not None:
        params.seed = int(seed)
    return params


# ---------------------------------------------------------------------------
# counter-based keys (all jit-safe, vectorized over the slot axis)


def keys_for(seeds, positions, salt):
    """``[S]`` PRNG keys, one per slot: a pure function of
    ``(seed, position, salt)`` — no stream, no state."""
    import jax
    import jax.numpy as jnp
    counters = (positions.astype(jnp.uint32) * np.uint32(N_SALTS)
                + np.uint32(salt))

    def one(s, c):
        return jax.random.fold_in(jax.random.PRNGKey(s), c)

    return jax.vmap(one)(seeds.astype(jnp.uint32), counters)


def uniform_for(seeds, positions, salt):
    """One U(0,1) per entry; ``seeds`` and ``positions`` broadcast to a
    common shape first (used as ``[S, k]`` by the accept rule)."""
    import jax
    import jax.numpy as jnp
    seeds = jnp.asarray(seeds)
    positions = jnp.asarray(positions)
    shape = jnp.broadcast_shapes(seeds.shape, positions.shape)
    s_flat = jnp.broadcast_to(seeds, shape).reshape(-1)
    p_flat = jnp.broadcast_to(positions, shape).reshape(-1)
    keys = keys_for(s_flat, p_flat, salt)
    u = jax.vmap(lambda k: jax.random.uniform(k, ()))(keys)
    return u.reshape(shape)


# ---------------------------------------------------------------------------
# the filter pipeline


def filter_logits(logits, temperature, top_k, top_p):
    """Apply temperature / top-k / top-p per row of ``logits [S, V]``;
    all three knobs are ``[S]`` arrays. Returns filtered logits where
    excluded tokens sit at :data:`NEG`.

    Row semantics:

    * ``temperature <= 0`` — greedy: the row collapses to a one-hot of
      its argmax (ties break to the lowest token id, matching
      ``jnp.argmax``), making ``sample`` deterministic and the
      speculative accept test exact.
    * ``top_k <= 0`` — keep all ``V``; ``top_k == 1`` is greedy by
      construction. Ties *at the k boundary* resolve by sort order
      (value-descending, then lowest token id), so the kept set is
      deterministic.
    * ``top_p == 1.0`` — nucleus filter off (plain temperature). The
      nucleus is the shortest sorted prefix with cumulative mass
      ``>= top_p``; the top-1 token always survives.

    The sort-based filter body runs under a batch-wide ``lax.cond``:
    when NO row asks for top-k or top-p (greedy and plain-temperature
    traffic — the overwhelmingly common batch), the full-vocab sort,
    cumsum, and scatter are skipped at runtime while the executable
    stays one and the same. This is what keeps the speculative draft
    scan (which re-filters every proposal) from paying ``k`` sorts per
    tick for knobs nobody set.
    """
    import jax
    import jax.numpy as jnp
    s, v = logits.shape
    temperature = temperature.astype(jnp.float32)
    greedy = temperature <= 0.0
    t = jnp.where(greedy, 1.0, temperature)
    z = (logits / t[:, None]).astype(jnp.float32)

    def _apply_filters(zz):
        # one descending sort drives both filters; lax.top_k breaks
        # ties by lowest index, which is what makes the "ties"
        # semantics stable
        svals, sidx = jax.lax.top_k(zz, v)
        k_eff = jnp.where(top_k <= 0, v, jnp.clip(top_k, 1, v))
        in_k = jnp.arange(v)[None, :] < k_eff[:, None]
        kz = jnp.where(in_k, svals, NEG)

        # nucleus in sorted space: keep ranks whose *exclusive*
        # cumulative mass is < p — rank 0 has exclusive mass 0, so the
        # top token always survives even at tiny p
        probs = jax.nn.softmax(kz, axis=-1)
        cum_excl = jnp.cumsum(probs, axis=-1) - probs
        keep = in_k & (cum_excl < top_p[:, None])
        filt_sorted = jnp.where(keep, kz, NEG)

        rows = jnp.arange(s)[:, None]
        return jnp.full((s, v), NEG, jnp.float32).at[rows, sidx].set(
            filt_sorted)

    filtering = jnp.any(((top_k > 0) & (top_k < v)) | (top_p < 1.0))
    filt = jax.lax.cond(filtering, _apply_filters, lambda zz: zz, z)

    am = jnp.argmax(z, axis=-1)
    onehot = jnp.arange(v)[None, :] == am[:, None]
    greedy_filt = jnp.where(onehot, 0.0, NEG)
    return jnp.where(greedy[:, None], greedy_filt, filt)


def probs_from_filtered(filtered):
    """Normalized distribution over the surviving tokens (greedy rows
    come out one-hot)."""
    import jax
    return jax.nn.softmax(filtered, axis=-1)


def sample_from_filtered(filtered, seeds, positions, salt=SALT_TOKEN):
    """Gumbel-max draw per row of ``filtered [S, V]`` under the
    counter key ``(seed, position, salt)``. A greedy (one-hot) row
    returns its argmax regardless of the noise — greedy requests
    consume no effective randomness."""
    import jax
    import jax.numpy as jnp
    v = filtered.shape[-1]
    keys = keys_for(jnp.asarray(seeds), jnp.asarray(positions), salt)
    g = jax.vmap(
        lambda k: jax.random.gumbel(k, (v,), jnp.float32))(keys)
    return jnp.argmax(filtered + g, axis=-1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# the speculative accept-prefix rule


def accept_prefix(p_probs, q_probs, proposals, seeds, pos0):
    """The draft-verify accept rule, vectorized over slots.

    Parameters
    ----------
    p_probs : ``[S, k+1, V]`` — the *target* model's filtered
        distributions at generation indices ``pos0 .. pos0+k`` (the
        verify step evaluates one position past the last proposal; that
        trailing distribution is unused here but rides along so the
        verify executable stays single-output-shape).
    q_probs : ``[S, k, V]`` — the *draft* distributions the proposals
        were drawn from.
    proposals : ``[S, k]`` int32 — draft tokens ``d_1 .. d_k``;
        proposal ``i`` was drawn with the ``(seed, pos0+i,
        SALT_TOKEN)`` key.
    seeds / pos0 : ``[S]`` — per-slot seed and the generation index of
        the first proposal.

    Returns
    -------
    ``(n_accepted [S], resampled [S])`` — the accepted-prefix length
    ``a`` in ``0..k``, and the residual-resampled token for generation
    index ``pos0 + a``. When ``a == k`` (full accept) the resampled
    token is a don't-care: the engine emits the k proposals and keeps
    ``d_k`` as the next decode input — no bonus token is drawn, which
    is what keeps the draft and target arenas in lockstep.

    Accept proposal ``i`` iff ``u_i * q_i(d_i) <= p_i(d_i)`` with
    ``u_i`` from the ``(seed, pos0+i, SALT_ACCEPT)`` key; the first
    reject resamples from ``normalize(max(p - q, 0))`` under
    ``SALT_RESID`` (falling back to ``p`` itself if the residual
    underflows to zero mass, e.g. q == p in float32).
    """
    import jax
    import jax.numpy as jnp
    s, k, v = q_probs.shape
    rows = jnp.arange(s)
    cols = jnp.arange(k)[None, :]
    pos = pos0[:, None] + cols                           # [S, k]

    u = uniform_for(seeds[:, None], pos, SALT_ACCEPT)    # [S, k]
    p_at = p_probs[rows[:, None], cols, proposals]       # p_i(d_i)
    q_at = q_probs[rows[:, None], cols, proposals]       # q_i(d_i)
    ok = u * q_at <= p_at
    # accepted-prefix length: leading run of True
    a = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)

    j = jnp.minimum(a, k - 1)                            # reject index
    pj = p_probs[rows, j]                                # [S, V]
    qj = q_probs[rows, j]
    resid = jnp.maximum(pj - qj, 0.0)
    mass = jnp.sum(resid, axis=-1, keepdims=True)
    resid = jnp.where(mass > 0.0, resid / mass, pj)
    resid_logits = jnp.where(resid > 0.0, jnp.log(resid), NEG)
    resampled = sample_from_filtered(resid_logits, seeds, pos0 + j,
                                     salt=SALT_RESID)
    return a, resampled
