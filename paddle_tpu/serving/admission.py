"""paddle_tpu.serving.admission — backpressure, SLAs, and blast radius.

An online endpoint fails three ways a training loop never sees:

* **Overload.** An unbounded queue converts overload into unbounded
  latency for *everyone*. The controller bounds queue depth — but a
  binary full/not-full reject degrades *everything equally*, which is
  the wrong shape for real traffic. Admission is a **shed ladder**
  instead: as the queue fills (and, independently, when the live
  ``slo.*`` goodput window dips below its floor) low-priority classes
  are shed first with a retryable :class:`ShedError` carrying a
  ``retry_after_ms`` hint, then the effective max batch shrinks so
  latency stays bounded, and only at the top rung does everyone get
  :class:`QueueFullError` (itself a :class:`ShedError`, so every
  overload error is retryable-with-backoff). High-priority traffic
  keeps its SLA while the endpoint degrades, instead of everyone
  failing a little.
* **Stale work.** A request past its deadline is pure waste: the caller
  is gone, but executing it still burns a batch slot. Deadlines are
  checked **at dequeue** (:meth:`AdmissionController.sweep_expired`),
  so an expired request is resolved with :class:`DeadlineExpired` and
  never occupies a slot in the batch it would have ridden.
* **Poison.** One malformed request inside a coalesced batch fails the
  whole executable call. The error path is classified with
  ``resilience.retry.RetryPolicy``: transient failures retry the batch
  (bounded, backed off); terminal failures re-run the batch
  request-by-request (:meth:`AdmissionController.isolate`) so exactly
  the poisoned request's future carries the exception and every
  innocent neighbour still resolves.
"""
from __future__ import annotations

import time

from ..resilience.deadline import Deadline
from ..resilience.retry import RetryPolicy
from . import metrics

#: Priority classes, lower number = more important. ``submit(...,
#: priority=)`` accepts either the name or the number.
PRIORITIES = {"high": 0, "normal": 1, "low": 2}


def resolve_priority(priority):
    """Accept 'high'/'normal'/'low' or an int; default 'normal'."""
    if priority is None:
        return PRIORITIES["normal"]
    if isinstance(priority, str):
        try:
            return PRIORITIES[priority]
        except KeyError:
            raise ValueError(
                f"unknown priority {priority!r}; expected one of "
                f"{sorted(PRIORITIES)}") from None
    return int(priority)


class ShedError(RuntimeError):
    """The admission ladder shed this request. Transient by contract —
    ``RetryPolicy.is_transient`` sees ``.transient`` — and carries a
    ``retry_after_ms`` hint that ``retry_call`` honours as a floor on
    its backoff delay, so a retrying caller naturally backs off harder
    the deeper the ladder it was shed from."""

    transient = True

    def __init__(self, msg, retry_after_ms=25.0, level=1, priority=None):
        super().__init__(msg)
        self.retry_after_ms = float(retry_after_ms)
        self.level = int(level)
        self.priority = priority

    @property
    def retry_after_s(self):
        return self.retry_after_ms / 1e3


class QueueFullError(ShedError):
    """Top rung of the shed ladder: the serving queue is at
    ``max_queue_depth`` and even high-priority traffic is rejected.
    Raised synchronously from ``submit()`` — no future is created."""

    def __init__(self, msg, retry_after_ms=25.0, level=3, priority=None):
        super().__init__(msg, retry_after_ms=retry_after_ms, level=level,
                         priority=priority)


class DeadlineExpired(TimeoutError):
    """Set on a request's future when its SLA deadline passed before a
    batch slot opened (the request was dropped at dequeue, unexecuted)."""


class AdmissionController:
    """Enqueue-time backpressure + dequeue-time SLA + failure triage.

    ``default_deadline_ms`` stamps a deadline on every request that
    didn't bring its own; ``None`` means requests without explicit
    deadlines never expire. ``retry_policy`` classifies batch-execution
    failures (transient → retry, terminal → isolate); the default is a
    fast two-attempt policy suited to in-process serving.
    """

    #: queue-depth fractions at which ladder levels 1..3 engage
    SHED_LEVELS = (0.5, 0.75, 0.9)
    #: ladder level -> lowest priority still admitted (smaller = more
    #: important). Level 1 sheds 'low', level 2 sheds 'normal'+'low';
    #: level 3 (and the hard cap) rejects everyone via QueueFullError.
    _MIN_SHED_PRIORITY = {1: 2, 2: 1, 3: 1}

    def __init__(self, max_queue_depth=256, default_deadline_ms=None,
                 retry_policy=None, shed=True, shed_levels=None,
                 slo_goodput_floor=0.90, retry_after_ms=25.0):
        if max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {max_queue_depth}")
        self.max_queue_depth = int(max_queue_depth)
        self.default_deadline_ms = default_deadline_ms
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=2, base_delay=0.01, max_delay=0.2)
        self.shed = bool(shed)
        self.shed_levels = tuple(shed_levels) if shed_levels is not None \
            else self.SHED_LEVELS
        self.slo_goodput_floor = slo_goodput_floor
        self.retry_after_ms = float(retry_after_ms)
        # SLO window reads are cached briefly: admission runs per
        # submit, the 60s goodput window doesn't move that fast
        self._slo_cache = (0.0, 0)   # (checked_at, slo_escalation)
        # optional observer (the engine's stats dict): called with
        # "rejected" / "expired" / "poisoned" / "shed"
        self.on_event = None

    def _note(self, event):
        if self.on_event is not None:
            self.on_event(event)

    # -- the shed ladder ---------------------------------------------------

    def _slo_escalation(self, now=None):
        """+1 ladder level while the live slo.goodput window sits below
        the floor (with enough submissions in the window to mean it)."""
        if self.slo_goodput_floor is None:
            return 0
        now = time.monotonic() if now is None else now
        checked, esc = self._slo_cache
        if now - checked <= 0.25:
            return esc
        goodput, submitted = metrics.goodput_window(now)
        esc = 1 if (goodput is not None and submitted >= 20
                    and goodput < self.slo_goodput_floor) else 0
        self._slo_cache = (now, esc)
        return esc

    def shed_level(self, depth):
        """Current ladder rung: 0 (admit all) .. 3 (reject all), from
        queue-depth fraction plus the SLO escalation."""
        if not self.shed:
            return 0
        frac = depth / self.max_queue_depth
        level = 0
        for i, threshold in enumerate(self.shed_levels):
            if frac >= threshold:
                level = i + 1
        return min(level + self._slo_escalation(), 3)

    def _retry_after(self, level):
        return self.retry_after_ms * (2 ** (max(level, 1) - 1))

    def effective_max_batch(self, max_batch, depth):
        """Ladder rung 2 halves the largest batch the picker may build,
        rung 3 quarters it — bounded service latency is the lever that
        keeps already-admitted high-priority work inside its SLA."""
        level = self.shed_level(depth)
        if level >= 3:
            return max(1, max_batch // 4)
        if level == 2:
            return max(1, max_batch // 2)
        return max_batch

    # -- enqueue ----------------------------------------------------------

    def admit(self, request, depth):
        """Called under the queue lock before enqueue. Walks the shed
        ladder (priority shed → reject-with-retry-after) before the
        hard capacity check; otherwise stamps the default deadline on
        an undeadlined request."""
        tr = getattr(request, "trace", None)
        if depth >= self.max_queue_depth:
            metrics.record_reject()
            self._note("rejected")
            if tr is not None:
                # the request trace outlives this synchronous reject: a
                # caller that retries hands the same context back via
                # submit(trace=), keeping one record per logical request
                tr.shed(level=3, retry_after_ms=self._retry_after(3))
            raise QueueFullError(
                f"serving queue full ({depth}/{self.max_queue_depth} "
                f"requests waiting)",
                retry_after_ms=self._retry_after(3))
        level = self.shed_level(depth)
        if level:
            prio = getattr(request, "priority", 1)
            min_shed = self._MIN_SHED_PRIORITY.get(min(level, 3), 2)
            if level >= 3 or prio >= min_shed:
                ra = self._retry_after(level)
                metrics.record_shed(prio, level, ra)
                self._note("shed")
                if tr is not None:
                    tr.shed(level=level, retry_after_ms=ra)
                raise ShedError(
                    f"request shed at ladder level {level} "
                    f"(priority={prio}, queue {depth}/"
                    f"{self.max_queue_depth}); retry after {ra:.0f}ms",
                    retry_after_ms=ra, level=level, priority=prio)
        if request.deadline is None and self.default_deadline_ms is not None:
            request.deadline = Deadline.after_ms(self.default_deadline_ms)

    # -- dequeue ----------------------------------------------------------

    @staticmethod
    def is_expired(request, now=None):
        return request.deadline is not None and request.deadline.expired(now)

    def expire(self, request):
        """Resolve an expired request's future (called after it was
        removed from the queue, before any batch slot was assigned)."""
        metrics.record_expired()
        self._note("expired")
        request.resolve_exception(DeadlineExpired(
            f"deadline expired {-request.deadline.remaining() * 1e3:.1f}ms "
            f"ago before a batch slot opened"))

    # -- failure triage ----------------------------------------------------

    def isolate(self, requests, run_one, batch_error):
        """Terminal (or retry-exhausted) batch failure: re-run each
        request on its own so one poisoned request fails only its own
        future. ``run_one(request)`` must execute AND resolve the
        request; any exception it raises is routed to that request's
        future here."""
        metrics.record_isolated(len(requests))
        for r in requests:
            try:
                run_one(r)
            except BaseException as e:  # noqa: BLE001 - routed to future
                metrics.record_poisoned(error=repr(e))
                self._note("poisoned")
                e.__context__ = batch_error
                r.resolve_exception(e)
