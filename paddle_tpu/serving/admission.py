"""paddle_tpu.serving.admission — backpressure, SLAs, and blast radius.

An online endpoint fails three ways a training loop never sees:

* **Overload.** An unbounded queue converts overload into unbounded
  latency for *everyone*. The controller bounds queue depth and
  fast-rejects at submit time (:class:`QueueFullError`) — the caller
  learns in microseconds and can shed load or retry elsewhere.
* **Stale work.** A request past its deadline is pure waste: the caller
  is gone, but executing it still burns a batch slot. Deadlines are
  checked **at dequeue** (:meth:`AdmissionController.sweep_expired`),
  so an expired request is resolved with :class:`DeadlineExpired` and
  never occupies a slot in the batch it would have ridden.
* **Poison.** One malformed request inside a coalesced batch fails the
  whole executable call. The error path is classified with
  ``resilience.retry.RetryPolicy``: transient failures retry the batch
  (bounded, backed off); terminal failures re-run the batch
  request-by-request (:meth:`AdmissionController.isolate`) so exactly
  the poisoned request's future carries the exception and every
  innocent neighbour still resolves.
"""
from __future__ import annotations

from ..resilience.deadline import Deadline
from ..resilience.retry import RetryPolicy
from . import metrics


class QueueFullError(RuntimeError):
    """Fast-reject: the serving queue is at ``max_queue_depth``. Raised
    synchronously from ``submit()`` — no future is created."""


class DeadlineExpired(TimeoutError):
    """Set on a request's future when its SLA deadline passed before a
    batch slot opened (the request was dropped at dequeue, unexecuted)."""


class AdmissionController:
    """Enqueue-time backpressure + dequeue-time SLA + failure triage.

    ``default_deadline_ms`` stamps a deadline on every request that
    didn't bring its own; ``None`` means requests without explicit
    deadlines never expire. ``retry_policy`` classifies batch-execution
    failures (transient → retry, terminal → isolate); the default is a
    fast two-attempt policy suited to in-process serving.
    """

    def __init__(self, max_queue_depth=256, default_deadline_ms=None,
                 retry_policy=None):
        if max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {max_queue_depth}")
        self.max_queue_depth = int(max_queue_depth)
        self.default_deadline_ms = default_deadline_ms
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=2, base_delay=0.01, max_delay=0.2)
        # optional observer (the engine's stats dict): called with
        # "rejected" / "expired" / "poisoned"
        self.on_event = None

    def _note(self, event):
        if self.on_event is not None:
            self.on_event(event)

    # -- enqueue ----------------------------------------------------------

    def admit(self, request, depth):
        """Called under the queue lock before enqueue. Raises
        :class:`QueueFullError` at capacity; otherwise stamps the
        default deadline on an undeadlined request."""
        if depth >= self.max_queue_depth:
            metrics.record_reject()
            self._note("rejected")
            raise QueueFullError(
                f"serving queue full ({depth}/{self.max_queue_depth} "
                f"requests waiting)")
        if request.deadline is None and self.default_deadline_ms is not None:
            request.deadline = Deadline.after_ms(self.default_deadline_ms)

    # -- dequeue ----------------------------------------------------------

    @staticmethod
    def is_expired(request, now=None):
        return request.deadline is not None and request.deadline.expired(now)

    def expire(self, request):
        """Resolve an expired request's future (called after it was
        removed from the queue, before any batch slot was assigned)."""
        metrics.record_expired()
        self._note("expired")
        request.resolve_exception(DeadlineExpired(
            f"deadline expired {-request.deadline.remaining() * 1e3:.1f}ms "
            f"ago before a batch slot opened"))

    # -- failure triage ----------------------------------------------------

    def isolate(self, requests, run_one, batch_error):
        """Terminal (or retry-exhausted) batch failure: re-run each
        request on its own so one poisoned request fails only its own
        future. ``run_one(request)`` must execute AND resolve the
        request; any exception it raises is routed to that request's
        future here."""
        metrics.record_isolated(len(requests))
        for r in requests:
            try:
                run_one(r)
            except BaseException as e:  # noqa: BLE001 - routed to future
                metrics.record_poisoned(error=repr(e))
                self._note("poisoned")
                e.__context__ = batch_error
                r.resolve_exception(e)
