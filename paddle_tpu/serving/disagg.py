"""paddle_tpu.serving.disagg — disaggregated prefill/decode serving.

One engine doing both prefill and decode (``generate.GenerateEngine``)
couples two workloads with opposite resource shapes: prefill is a
compute-bound burst whose latency IS the user's TTFT, decode is a
steady memory-bound drip whose throughput IS the fleet's tokens/s.
Coupled, a burst of long prompts stalls every live stream's next token,
and scaling for one SLO overprovisions the other. This module splits
them into two independently-scaled pools:

* :class:`PrefillPool` — replicas of a lean :class:`PrefillEngine` that
  run the *same* bucketed prefill executables as the single engine and
  produce a **KV segment** (the ``KVCachePool.export_slot`` transport
  format) plus the request's first sampled token;
* :class:`DecodePool` — a ``MultiDecodeEngine`` whose
  :class:`~paddle_tpu.serving.generate.GenerateEngine` replicas are
  built with ``kv_import=True``: a handoff lands through
  ``KVCachePool.import_slot`` on a pre-compiled insert executable, and
  a drained decode replica's sequences migrate *with their KV*
  (``disown_inflight(export_kv=True)``) and resume mid-stream;
* :class:`DisaggServer` — the front door: admission at the prefill
  pool, a shared :class:`~paddle_tpu.serving.prefix_cache.PrefixCache`
  in front of prefill, and the explicit, *priced* handoff between the
  pools — ``planned_ms = kv_bytes / link_bandwidth()`` from the PR 12
  comm model, recorded as ``serving.handoff.{bytes,ms,queue_depth}``.

Bit-parity is the design invariant: the decode replica seats a handoff
with the exact host state single-engine prefill would have left
(``tokens=[first]``, ``length=prompt_len``, ``note_length`` ledger), so
every subsequent counter-PRNG key — a pure function of ``(request seed,
generation index)`` — is identical and the stream matches the
single-engine oracle byte for byte, through prefix hits and mid-stream
drains included.

Each pool autoscales on its own SLO via its own
:class:`~paddle_tpu.serving.supervisor.ServingSupervisor`: prefill on
``slo.ttft_p99_ms`` / queue depth (``ttft_ceiling_ms`` /
``queue_depth_ceiling``), decode on ``slo.tokens_per_s``
(``tokens_floor``). Breakers, hang failover, probes, and drains extend
per-pool unchanged — a hung prefill replica fails its queue over to
peers exactly as a hung decode replica does.
"""
from __future__ import annotations

import collections
import threading
import time

import numpy as np

from .. import monitor as _monitor
from ..io.bucketing import grow_buckets, next_bucket
from ..resilience import faults as _faults
from ..resilience.deadline import Deadline
from .admission import AdmissionController, resolve_priority
from .generate import (DecodeRequest, GenerateEngine, MultiDecodeEngine,
                       replicate_decode)
from .kv_cache import bytes_per_token, _leaves
from .multi import MultiDeviceEngine
from .prefix_cache import PrefixCache
from . import metrics
from . import reqtrace
from . import sampling as sampling_mod


class PrefillEngine:
    """Prompt ingest over one model replica: pops requests, consults
    the shared prefix cache, runs the bucketed prefill executable on a
    miss, and hands a ``(request, segment, first_token)`` triple to the
    pool's ``on_segment`` callback — the engine never owns a KV arena
    or a decode loop.

    Exposes the full ``MultiDeviceEngine`` supervision surface
    (heartbeat / probe / steal_pending / disown_inflight / requeue /
    warmup / close), so breakers, hang failover, and restart work on a
    prefill replica exactly as they do on a decode replica. A disowned
    in-flight request re-runs its prefill on the adopting replica —
    prefill is a pure function of the prompt, so the retried segment is
    identical.

    Executables: one ``("prefill", bucket)`` per prompt bucket — the
    SAME jitted body as ``GenerateEngine._get_prefill`` (kv, sampled
    first token, last-position logits) — plus one ``("psample",)``
    that re-runs the identical filter+sample math on *cached* logits,
    so a prefix hit samples its own first token (its own seed, counter
    index 0) without minting a prompt-shaped executable.
    """

    def __init__(self, model, prompt_buckets=None, max_len=512,
                 page=64, factor=2.0, queue_depth=256, deadline_ms=None,
                 shed=True, slo_goodput_floor=0.90, start=True,
                 replica_id=None, on_outcome=None, sampling=None,
                 cache=None, on_segment=None):
        import jax
        self._jax = jax
        self.model = model
        self.replica_id = replica_id
        self.on_outcome = on_outcome
        self.weights_version = 0
        self.cache = cache                  # shared PrefixCache or None
        self.on_segment = on_segment        # f(req, segment, first, hit)
        self.default_sampling = sampling_mod.resolve(sampling)
        family = grow_buckets(page, factor, max_len)
        self.max_len = int(family[-1])
        if prompt_buckets is None:
            self.prompt_buckets = tuple(family)
        else:
            pb = tuple(sorted({int(b) for b in prompt_buckets}))
            if not pb or pb[-1] > self.max_len:
                raise ValueError(
                    f"prompt_buckets {pb} must be non-empty and within "
                    f"max_len={self.max_len}")
            self.prompt_buckets = pb
        self._leaf_list = _leaves(model.kv_spec())
        self._per_token = bytes_per_token(model.kv_spec())
        self.admission = AdmissionController(
            max_queue_depth=queue_depth, default_deadline_ms=deadline_ms,
            shed=shed, slo_goodput_floor=slo_goodput_floor)
        self.admission.on_event = self._admission_event
        self._queue = collections.deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._current = None            # in-flight request (disownable)
        self._inflight_t0 = None
        self._exec = {}
        self._trace_count = 0
        self._stats_lock = threading.Lock()
        self._stats = {"submitted": 0, "completed": 0, "failed": 0,
                       "rejected": 0, "expired": 0, "shed": 0,
                       "prefills": 0, "prefill_tokens": 0,
                       "prefix_hits": 0, "prefix_misses": 0,
                       "compiles": 0}
        self._running = False
        self._closed = False
        self._draining = False
        self._thread = None
        self._last_progress = time.monotonic()
        self._last_ok_t = time.monotonic()
        if start:
            self.start()

    # -- client surface ----------------------------------------------------

    def make_request(self, prompt, max_new_tokens=32, eos_token=None,
                     deadline_ms=None, priority=None, trace=None,
                     sampling=None, seed=None):
        """Same validation and seed discipline as
        ``GenerateEngine.make_request`` — the request built here rides
        unchanged through handoff, so everything failover or the decode
        pool needs (resolved sampling, concrete seed, trace) is fixed
        at the front door."""
        arr = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if arr.size < 1:
            raise ValueError("empty prompt")
        if arr.size > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt of {arr.size} tokens exceeds the largest prefill "
                f"bucket {self.prompt_buckets[-1]} — raise max_len / "
                f"prompt_buckets")
        m = int(max_new_tokens)
        if m < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {m}")
        if arr.size + m > self.max_len:
            raise ValueError(
                f"prompt {arr.size} + max_new_tokens {m} exceeds the KV "
                f"arena max_len={self.max_len}")
        deadline = (Deadline.after_ms(deadline_ms)
                    if deadline_ms is not None else None)
        prio = resolve_priority(priority)
        if sampling is None and seed is None:
            params = sampling_mod.resolve(self.default_sampling)
        else:
            params = sampling_mod.resolve(sampling, seed=seed)
        if params.seed is None:
            from .generate import _fresh_seed
            params.seed = 0 if params.greedy else _fresh_seed()
        return DecodeRequest(arr, m, eos_token=eos_token,
                             deadline=deadline, priority=prio,
                             sampling=params,
                             trace=reqtrace.attach(
                                 trace, kind="decode", priority=prio,
                                 replica=self.replica_id,
                                 version=self.weights_version))

    def submit_request(self, req, admit=True):
        """The disaggregated topology's ONE admission point: the shed
        ladder runs here, before any prefill work — a request shed at
        the front door has consumed nothing."""
        with self._cond:
            if self._closed:
                raise RuntimeError("prefill engine is closed")
            if admit:
                self.admission.admit(req, len(self._queue))
            self._queue.append(req)
            depth = len(self._queue)
            self._cond.notify()
        metrics.record_submit(1)
        metrics.record_queue_depth(depth)
        if req.trace is not None:
            req.trace.hop("enqueue", replica=self.replica_id)
        with self._stats_lock:
            self._stats["submitted"] += 1
        return req.future

    def depth(self):
        with self._lock:
            return len(self._queue)

    # -- executables -------------------------------------------------------

    def _get_prefill(self, bucket):
        key = ("prefill", bucket)
        fn = self._exec.get(key)
        if fn is not None:
            return fn
        jax = self._jax
        prefill_fn = self.model.prefill_fn

        def prefill(state, tokens, lengths, temps, top_ks, top_ps,
                    seeds, positions):
            self._trace_count += 1
            kv, last_logits = prefill_fn(state, tokens, lengths)
            filt = sampling_mod.filter_logits(last_logits, temps,
                                              top_ks, top_ps)
            first = sampling_mod.sample_from_filtered(filt, seeds,
                                                      positions)
            return kv, first, last_logits

        fn = jax.jit(prefill)
        self._exec[key] = fn
        self._note_compile(f"prefill[L={bucket}]")
        return fn

    def _get_psample(self):
        """First-token sampling over CACHED logits: the same
        filter+sample ops the fused prefill runs, applied to the
        logits a previous prefill stored — with the hitting request's
        own knobs, seed, and generation index 0. Tiny (``[1, V]``),
        bucket-free, minted once."""
        key = ("psample",)
        fn = self._exec.get(key)
        if fn is not None:
            return fn
        jax = self._jax

        def psample(logits, temps, top_ks, top_ps, seeds, positions):
            self._trace_count += 1
            filt = sampling_mod.filter_logits(logits, temps, top_ks,
                                              top_ps)
            return sampling_mod.sample_from_filtered(filt, seeds,
                                                     positions)

        fn = jax.jit(psample)
        self._exec[key] = fn
        self._note_compile("psample")
        return fn

    def _note_compile(self, what):
        metrics.record_decode_compile(1, what=what)
        with self._stats_lock:
            self._stats["compiles"] += 1

    def executables(self):
        return len(self._exec), self._trace_count

    @staticmethod
    def _sampling_args(n):
        import jax.numpy as jnp
        return (jnp.zeros((n,), jnp.float32),
                jnp.zeros((n,), jnp.int32),
                jnp.ones((n,), jnp.float32),
                jnp.zeros((n,), jnp.uint32),
                jnp.zeros((n,), jnp.int32))

    def warmup(self, *_signatures):
        """Mint every executable this replica can need: one prefill per
        prompt bucket plus the psample body. Returns the number
        compiled; steady-state traffic (hits AND misses) then runs with
        zero fresh traces."""
        import jax.numpy as jnp
        before = len(self._exec)
        state = self.model.state
        samp_1 = self._sampling_args(1)
        with _monitor.trace.span("serving.prefill_warmup",
                                 buckets=len(self.prompt_buckets)):
            for lb in self.prompt_buckets:
                _kv, first, _logits = self._get_prefill(lb)(
                    state, jnp.zeros((1, lb), jnp.int32),
                    jnp.ones((1,), jnp.int32), *samp_1)
                self._jax.block_until_ready(first)
            tok = self._get_psample()(
                jnp.zeros((1, int(self.model.vocab)), jnp.float32),
                *samp_1)
            self._jax.block_until_ready(tok)
        return len(self._exec) - before

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        with self._lock:
            if self._running or self._closed:
                return
            self._running = True
            self._draining = False
            self._thread = threading.Thread(
                target=self._worker, name="paddle_tpu-serving-prefill",
                daemon=True)
            self._thread.start()

    def close(self, drain=True, timeout=None):
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._running = False
            self._draining = bool(drain)
            self._cond.notify_all()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            if timeout is None:
                timeout = 10.0 if drain else 5.0
            t.join(timeout)
        leftovers = []
        with self._cond:
            leftovers.extend(self._queue)
            self._queue.clear()
        for r in leftovers:
            r.resolve_exception(RuntimeError("prefill engine closed"))

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()

    # -- supervision surface (the MultiDeviceEngine contract) --------------

    def heartbeat(self, now=None):
        now = time.monotonic() if now is None else now
        with self._lock:
            depth = len(self._queue)
            t0 = self._inflight_t0
            active = 1 if self._current is not None else 0
        return {
            "queue_depth": depth,
            "inflight_age_s": None if t0 is None else now - t0,
            "inflight_token": t0,
            "last_progress_age_s": now - self._last_progress,
            "last_ok_age_s": now - self._last_ok_t,
            "active": active,
        }

    def probe(self, timeout_s=1.0):
        """Half-open test traffic: one smallest-bucket prefill on a
        side thread (the worker may be the wedged thing)."""
        import jax.numpy as jnp
        lb = self.prompt_buckets[0]
        if ("prefill", lb) not in self._exec:
            return None
        done = threading.Event()
        err = []

        def _go():
            try:
                fn = self._exec[("prefill", lb)]
                _kv, first, _logits = fn(
                    self.model.state, jnp.zeros((1, lb), jnp.int32),
                    jnp.ones((1,), jnp.int32), *self._sampling_args(1))
                self._jax.block_until_ready(first)
            except BaseException as e:   # noqa: BLE001 - probe verdict
                err.append(e)
            finally:
                done.set()

        threading.Thread(target=_go, daemon=True,
                         name="paddle_tpu-prefill-probe").start()
        ok = done.wait(timeout_s) and not err
        if ok:
            self._last_ok_t = time.monotonic()
        return bool(ok)

    def steal_pending(self):
        with self._cond:
            taken = list(self._queue)
            self._queue.clear()
        metrics.record_queue_depth(0)
        return taken

    def disown_inflight(self, export_kv=False):
        """Failover: hand the in-flight request (if any) to the caller.
        Prefill is a pure function of the prompt — the adopting replica
        re-runs it and produces an identical segment; if this replica's
        wedged dispatch ever completes, the ownership check in
        :meth:`_process` discards its result. ``export_kv`` is accepted
        for surface parity (nothing is resident here to export)."""
        with self._lock:
            req = self._current
            self._current = None
            self._inflight_t0 = None
        if req is None or req.future.done():
            return []
        return [req]

    def requeue(self, requests):
        if not requests:
            return
        for r in requests:
            tr = getattr(r, "trace", None)
            if tr is not None:
                tr.to("queue")
                tr.hop("requeue", replica=self.replica_id)
        with self._cond:
            if self._closed:
                for r in requests:
                    r.resolve_exception(
                        RuntimeError("prefill engine closed"))
                return
            for r in reversed(requests):
                self._queue.appendleft(r)
            depth = len(self._queue)
            self._cond.notify()
        metrics.record_queue_depth(depth)

    def _note_outcome(self, ok, exc=None):
        if ok:
            self._last_ok_t = time.monotonic()
        cb = self.on_outcome
        if cb is not None:
            try:
                cb(ok, exc)
            except Exception:   # noqa: BLE001 - observer must not kill
                pass            # the worker

    def _admission_event(self, event):
        key = {"rejected": "rejected", "expired": "expired",
               "poisoned": "failed", "shed": "shed"}.get(event)
        if key is not None:
            with self._stats_lock:
                self._stats[key] += 1

    def stats(self):
        with self._stats_lock:
            s = dict(self._stats)
        s["queue_depth"] = self.depth()
        s["executables"] = len(self._exec)
        s["traces"] = self._trace_count
        return s

    # -- the worker loop ---------------------------------------------------

    def _pop_next_locked(self, now):
        expired = []
        while self._queue:
            best_i, best_p = 0, self._queue[0].priority
            for i, r in enumerate(self._queue):
                if r.priority < best_p:
                    best_i, best_p = i, r.priority
            r = self._queue[best_i]
            del self._queue[best_i]
            if self.admission.is_expired(r, now):
                expired.append(r)
                continue
            return r, expired
        return None, expired

    def _worker(self):
        while True:
            now = time.monotonic()
            with self._cond:
                req, expired = self._pop_next_locked(now)
                depth = len(self._queue)
                if req is None and not expired:
                    if not self._running:
                        if self._draining and self._queue:
                            continue
                        return
                    self._cond.wait(0.05)
                    continue
            metrics.record_queue_depth(depth)
            for r in expired:
                self.admission.expire(r)
            if req is None:
                continue
            self._process(req)
            self._last_progress = time.monotonic()

    def _process(self, req):
        """One request: prefix lookup → (hit: psample cached logits |
        miss: bucketed prefill + cache insert) → first token →
        ``on_segment`` handoff. Ownership-checked against
        ``disown_inflight`` so a hung dispatch's late completion is
        discarded rather than double-delivered."""
        import jax.numpy as jnp
        with self._lock:
            self._current = req
            self._inflight_t0 = time.monotonic()
        tr = req.trace
        try:
            if tr is not None:
                tr.to("prefix_lookup")
            key = entry = None
            hit = False
            if self.cache is not None:
                key, entry = self.cache.lookup(req.prompt)
                hit = entry is not None
                if tr is not None:
                    tr.note_prefix(hit)
            sp = req.sampling
            samp = (jnp.asarray([sp.temperature], jnp.float32),
                    jnp.asarray([sp.top_k], jnp.int32),
                    jnp.asarray([sp.top_p], jnp.float32),
                    jnp.asarray([sp.seed or 0], jnp.uint32),
                    jnp.zeros((1,), jnp.int32))
            if hit:
                if _faults.enabled():
                    _faults.maybe_serving_fault(self.replica_id, site="prefill")
                first = int(np.asarray(self._get_psample()(
                    jnp.asarray(entry.logits), *samp))[0])
                segment = entry.segment
                # keep the entry pinned until the stream resolves: the
                # decode replica reads the leaves at seat time (and a
                # drain may re-import them later)
                req.future.add_done_callback(
                    lambda _f, c=self.cache, k=key: c.release(k))
                with self._stats_lock:
                    self._stats["prefix_hits"] += 1
            else:
                if tr is not None:
                    tr.to("prefill")
                if _faults.enabled():
                    _faults.maybe_serving_fault(self.replica_id, site="prefill")
                t0 = time.monotonic()
                p = int(req.prompt.size)
                bucket = next_bucket(p, self.prompt_buckets)
                tokens = np.zeros((1, bucket), np.int32)
                tokens[0, :p] = req.prompt
                kv, first_dev, logits = self._get_prefill(bucket)(
                    self.model.state, jnp.asarray(tokens),
                    jnp.asarray([p], jnp.int32), *samp)
                first = int(np.asarray(first_dev)[0])
                leaves = {name: np.asarray(kv[name][0])
                          for name, _tail, _dt in self._leaf_list}
                seg_bytes = sum(int(a.nbytes) for a in leaves.values())
                expected = self._per_token * bucket
                if seg_bytes != expected:
                    raise AssertionError(
                        f"prefill segment {seg_bytes} B != spec-priced "
                        f"{expected} B ({self._per_token} B/token x "
                        f"bucket {bucket})")
                segment = {"length": p, "pad": bucket,
                           "bytes": seg_bytes, "leaves": leaves}
                ms = (time.monotonic() - t0) * 1e3
                metrics.record_prefill(p, ms, bucket)
                with self._stats_lock:
                    self._stats["prefills"] += 1
                    self._stats["prefill_tokens"] += p
                    if self.cache is not None:
                        self._stats["prefix_misses"] += 1
                if self.cache is not None and key is not None:
                    self.cache.insert(key, segment, np.asarray(logits))
        except BaseException as e:   # noqa: BLE001 - to the future
            with self._lock:
                mine = self._current is req
                if mine:
                    self._current = None
                    self._inflight_t0 = None
            self._note_outcome(False, e)
            if mine:
                with self._stats_lock:
                    self._stats["failed"] += 1
                req.resolve_exception(e)
            return
        # ownership check BEFORE delivery: a disowned request was
        # already adopted (and re-prefilled) elsewhere — dropping the
        # stale result here is what makes one hang produce one handoff
        with self._lock:
            mine = self._current is req
            if mine:
                self._current = None
                self._inflight_t0 = None
        if not mine:
            return
        self._note_outcome(True)
        # the TTFT moment: prefill (or the cached-logits sample)
        # produced the stream's first real token
        if tr is not None:
            tr.first_token()
        with self._stats_lock:
            self._stats["completed"] += 1
        if self.on_segment is not None:
            try:
                self.on_segment(req, segment, first, hit)
            except BaseException as e:   # noqa: BLE001 - to the future
                with self._stats_lock:
                    self._stats["failed"] += 1
                req.resolve_exception(e)
        else:
            # standalone use (tests): resolve with the first token
            req.resolve_result(np.asarray([first], np.int32))


# ---------------------------------------------------------------------------
# the two pools


class PrefillPool(MultiDeviceEngine):
    """Breaker-aware fan-out over :class:`PrefillEngine` replicas —
    the same supervision spine as every other fleet (hang failover,
    probes, restart, scaling), with prefill's own SLO driving the
    scaling when the owner wires a supervisor with ``ttft_ceiling_ms``
    / ``queue_depth_ceiling``."""

    def __init__(self, model, devices=None, **kwargs):
        kwargs.setdefault("hedge_ms", 0)    # a prefill is not hedgeable
        #                                     work: it owns no slot, and
        #                                     re-running it is failover's
        #                                     job, not the tail's
        super().__init__(model, devices=devices, **kwargs)

    def _replicate(self, model, devices):
        return replicate_decode(model, devices)

    def _new_engine(self, model, index, on_outcome):
        return PrefillEngine(model, replica_id=index,
                             on_outcome=on_outcome,
                             **self._engine_kwargs)


class DecodePool(MultiDecodeEngine):
    """The decode side of the split: ``GenerateEngine`` replicas built
    with ``kv_import=True`` (warmup covers every capacity-family insert
    pad, so any segment lands compile-free), presets admitted without
    re-running the shed ladder, and drain migration carrying KV so a
    drained replica's streams resume mid-flight on the adopter."""

    def __init__(self, model, devices=None, **kwargs):
        kwargs["kv_import"] = True
        super().__init__(model, devices=devices, **kwargs)

    def submit_preset(self, req):
        """Land a handoff: the request already passed admission at the
        prefill pool's front door and carries its ``preset`` payload —
        route it to a healthy decode replica, ladder not re-run."""
        rep = self._pick_replica()
        if req.trace is not None:
            req.trace.hop("handoff", replica=rep.index)
            # close the handoff stage *before* the enqueue: once the
            # request is in the replica's deque its worker may seat it
            # (to("decode")) concurrently, and a later to("queue") here
            # would steal decode time back into queue. From this point
            # the wait is slot wait, not transport.
            req.trace.to("queue")
        fut = rep.engine.submit_request(req, admit=False)
        with self._hedge_lock:
            self._submitted += 1
        return fut, rep

    def _disown(self, replica):
        # drain/failover migration carries each sequence's KV segment +
        # emitted tokens: the adopter seats via _seat_preset and the
        # stream continues at the same generation index, bit-identical
        return replica.engine.disown_inflight(export_kv=True)


# ---------------------------------------------------------------------------
# the server


class DisaggServer:
    """The disaggregated topology, assembled: a shared
    :class:`PrefixCache`, a :class:`PrefillPool`, a :class:`DecodePool`,
    the priced handoff between them, and one supervisor per pool
    scaling on that pool's own SLO.

    Capacity planning rule of thumb (docs/serving.md): size the
    prefill:decode replica ratio to ``mean_prompt_tokens x arrival_rate
    / (prefill_tokens_per_s)`` vs ``mean_stream_tokens x arrival_rate /
    (decode_tokens_per_s x slots)`` — the pools saturate independently,
    which is the point of the split.

    Parameters mirror :class:`GenerateEngine` where they share meaning;
    both pools are forced onto one ``(page, factor, max_len,
    prompt_buckets)`` family so every prefill bucket has a pre-compiled
    decode-side insert executable.
    """

    def __init__(self, model, prefill_replicas=1, decode_replicas=1,
                 prefill_devices=None, decode_devices=None, slots=8,
                 page=64, factor=2.0, max_len=512, prompt_buckets=None,
                 queue_depth=256, deadline_ms=None, sampling=None,
                 prefix_cache=True, prefix_budget_bytes=64 * 1024 * 1024,
                 link_gbps=None, supervise=True,
                 supervisor_interval_s=0.25, inflight_timeout_ms=None,
                 prefill_inflight_timeout_ms=None,
                 decode_inflight_timeout_ms=None,
                 ttft_ceiling_ms=None, queue_depth_ceiling=None,
                 tokens_floor=None, prefill_initial_active=None,
                 decode_initial_active=None):
        import jax
        from ..parallel.planner import link_bandwidth
        devs = jax.local_devices()
        if prefill_devices is None:
            prefill_devices = [devs[i % len(devs)]
                               for i in range(int(prefill_replicas))]
        if decode_devices is None:
            decode_devices = [devs[i % len(devs)]
                              for i in range(int(decode_replicas))]
        family = grow_buckets(page, factor, max_len)
        if prompt_buckets is None:
            prompt_buckets = tuple(family)
        self.prompt_buckets = tuple(sorted({int(b)
                                            for b in prompt_buckets}))
        self.spec = model.kv_spec()
        self._kv_per_token = bytes_per_token(self.spec)
        self._link_bw = link_bandwidth(link_gbps)   # bytes/s
        self.prefix = (PrefixCache(self.spec,
                                   budget_bytes=prefix_budget_bytes)
                       if prefix_cache else None)
        self._lock = threading.Lock()
        self._handoffs = 0
        self._handoff_bytes = 0
        # supervision is wired EXPLICITLY per pool (below) so each
        # scales on its own SLO; the pools' built-in supervisors stay
        # off to avoid a second control loop per pool
        # hang detection is tuned per pool: a prefill dispatch is one
        # bounded executable call (tight timeouts are safe) while a
        # loaded decode tick stretches under CPU contention — one
        # shared aggressive timeout would false-positive the decode
        # fleet into failover
        if prefill_inflight_timeout_ms is None:
            prefill_inflight_timeout_ms = inflight_timeout_ms
        if decode_inflight_timeout_ms is None:
            decode_inflight_timeout_ms = inflight_timeout_ms
        self.prefill_pool = PrefillPool(
            model, devices=prefill_devices, supervise=False,
            inflight_timeout_ms=prefill_inflight_timeout_ms,
            initial_active=prefill_initial_active,
            # engine kwargs ↓
            prompt_buckets=self.prompt_buckets, max_len=max_len,
            page=page, factor=factor, queue_depth=queue_depth,
            deadline_ms=deadline_ms, sampling=sampling,
            cache=self.prefix, on_segment=self._handoff)
        self.decode_pool = DecodePool(
            model, devices=decode_devices, supervise=False,
            inflight_timeout_ms=decode_inflight_timeout_ms,
            initial_active=decode_initial_active,
            # engine kwargs ↓
            slots=slots, page=page, factor=factor, max_len=max_len,
            prompt_buckets=self.prompt_buckets,
            queue_depth=queue_depth, sampling=sampling)
        self.prefill_supervisor = None
        self.decode_supervisor = None
        if supervise:
            from .supervisor import ServingSupervisor
            # prefill scales ONLY on its own SLO (TTFT / queue depth):
            # goodput_floor 0 disables the generic branch for this
            # pool. With no ceiling configured the pool has no scale-UP
            # path either, so scaling is off entirely — otherwise the
            # idle scale-down would be a one-way ratchet that strands
            # the pool at min_replicas before traffic arrives.
            self.prefill_supervisor = ServingSupervisor(
                self.prefill_pool, interval_s=supervisor_interval_s,
                goodput_floor=0.0, ttft_ceiling_ms=ttft_ceiling_ms,
                queue_depth_ceiling=queue_depth_ceiling,
                scale=(ttft_ceiling_ms is not None
                       or queue_depth_ceiling is not None))
            # decode scales ONLY on its own SLO (tokens/s). Goodput is
            # an end-to-end signal spanning both pools — early in a
            # burst it reads 0 (submits recorded, nothing finished yet)
            # and would mis-attribute prefill backlog to decode — so
            # the generic branch is off here too.
            self.decode_supervisor = ServingSupervisor(
                self.decode_pool, interval_s=supervisor_interval_s,
                goodput_floor=0.0, tokens_floor=tokens_floor,
                scale=tokens_floor is not None)

    # -- client surface ----------------------------------------------------

    def submit(self, prompt, max_new_tokens=32, eos_token=None,
               deadline_ms=None, priority=None, trace=None,
               sampling=None, seed=None):
        """One sequence through the split topology. The future resolves
        to the generated token ids — identical, byte for byte, to what
        a single ``GenerateEngine`` returns for the same seeds."""
        rep = self.prefill_pool._pick_replica()
        req = rep.engine.make_request(
            prompt, max_new_tokens=max_new_tokens, eos_token=eos_token,
            deadline_ms=deadline_ms, priority=priority, trace=trace,
            sampling=sampling, seed=seed)
        return rep.engine.submit_request(req)

    def run(self, prompt, max_new_tokens=32, eos_token=None,
            deadline_ms=None, timeout=None, priority=None,
            sampling=None, seed=None):
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           eos_token=eos_token, deadline_ms=deadline_ms,
                           priority=priority, sampling=sampling,
                           seed=seed).result(timeout)

    # -- the handoff -------------------------------------------------------

    def _handoff(self, req, segment, first, hit):
        """Prefill (or a prefix hit) produced a segment: price the
        transfer against the PR 12 comm model, record it, and land the
        request on a decode replica as a ``preset``. Runs on the
        prefill replica's worker thread."""
        t0 = time.perf_counter()
        tr = req.trace
        if tr is not None:
            # handoff_ms covers pricing + routing + enqueue; the stage
            # closes in submit_preset (to("queue")) so decode-slot wait
            # is blamed on queue, not the link
            tr.to("handoff")
        nbytes = int(segment["bytes"])
        planned_ms = nbytes / self._link_bw * 1e3
        req.preset = {"segment": segment,
                      "tokens": [int(first)],
                      "last_token": int(first),
                      "prompt_len": int(segment["length"])}
        depth = self.decode_pool.depth() \
            if hasattr(self.decode_pool, "depth") \
            else sum(r.engine.depth()
                     for r in self.decode_pool._replicas if r.active)
        _fut, _rep = self.decode_pool.submit_preset(req)
        actual_ms = (time.perf_counter() - t0) * 1e3
        metrics.record_handoff(nbytes, planned_ms, actual_ms,
                               queue_depth=depth)
        with self._lock:
            self._handoffs += 1
            self._handoff_bytes += nbytes

    def planned_handoff_ms(self, prompt_len):
        """What the comm model predicts one handoff costs for a prompt
        of this length: per-token KV spec bytes × the prompt's bucket,
        over the link bandwidth. The smoke gate asserts recorded
        handoff bytes equal this arithmetic exactly."""
        pad = next_bucket(int(prompt_len), self.prompt_buckets)
        nbytes = self._kv_per_token * pad
        return nbytes, nbytes / self._link_bw * 1e3

    # -- lifecycle ---------------------------------------------------------

    def warmup(self):
        """Warm both pools (all prefill buckets, all decode
        executables including every capacity-family insert pad).
        Returns total fresh executables."""
        n = self.prefill_pool.warmup()
        n += self.decode_pool.warmup()
        return n

    def drain_decode_replica(self, index, reason="drain"):
        """Graceful drain of one decode replica: its live sequences
        migrate WITH their KV (``export_kv=True``) and resume
        mid-stream on peers."""
        return self.decode_pool.drain_replica(index, reason=reason)

    def close(self, drain=True, timeout=10.0):
        for sup in (self.prefill_supervisor, self.decode_supervisor):
            if sup is not None:
                sup.stop()
        if drain:
            # prefill first: stop producing new handoffs, then let the
            # decode pool run its seated streams dry
            self.prefill_pool.close(drain=True, timeout=timeout)
            self.decode_pool.drain_wait(timeout_s=timeout)
            self.decode_pool.close(drain=True, timeout=timeout)
        else:
            self.prefill_pool.close(drain=False)
            self.decode_pool.close(drain=False)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- observability -----------------------------------------------------

    def stats(self):
        with self._lock:
            handoffs = self._handoffs
            handoff_bytes = self._handoff_bytes
        out = {
            "prefill": self.prefill_pool.stats(),
            "decode": self.decode_pool.stats(),
            "handoffs": handoffs,
            "handoff_bytes": handoff_bytes,
            "kv_bytes_per_token": self._kv_per_token,
            "link_bandwidth_bps": self._link_bw,
        }
        if self.prefix is not None:
            out["prefix"] = self.prefix.stats()
        return out

    def health(self, now=None):
        return {"prefill": self.prefill_pool.health(now),
                "decode": self.decode_pool.health(now)}
