"""paddle_tpu.serving.reqtrace — request-scoped tracing + SLO attribution.

The serving spine (PRs 14-15) answers fleet questions — goodput windows,
hedge rates, failover counts — but nothing can answer "why was THIS
request slow?". This module mints one :class:`RequestTrace` per logical
request at ``submit()`` time and rides it through every thread handoff
the spine performs: the batcher drain thread, the hedge timer, a
supervisor failover, and the GenerateEngine tick loop. On completion it
emits exactly one ``serving.request`` JSONL record that decomposes the
request's lifetime into blame-assigned stages:

* ``queue_ms``        — waiting in an admission queue
* ``shed_retry_ms``   — time between a shed and the caller's resubmit
* ``assemble_ms``     — coalesce + pad on the drain thread
* ``execute_ms``      — device execution (all attempts)
* ``retry_backoff_ms``— sleeping between transient-fault retries
* ``scatter_ms``      — host transfer + row split + future resolution
* ``prefill_ms``      — decode-engine prompt prefill
* ``prefix_lookup_ms``— disaggregated serving's prefix-cache probe
* ``handoff_ms``      — prefill→decode KV transfer + decode-slot wait
* ``decode_ms``       — wall time from first token to completion
* ``hedge_ms``        — lag between the primary submit and the winning
                        hedge shadow's dispatch

plus ``ttft_ms`` / ``tpot_ms`` as first-class fields — the two numbers
generative serving is actually judged on (time-to-first-token,
time-per-output-token).

Design rules:

* **Exactly once.** The terminal record rides the idempotent future
  funnel: ``Request.resolve_*`` only finalizes when its underlying
  ``set_result``/``set_exception`` actually WON the race. A hedge shadow
  and its primary share one context; whichever resolves first emits the
  record, the loser's attempt is swallowed with its
  ``InvalidStateError``.
* **Audited attribution.** Stages are boundary-derived (each ``to()``
  transition credits the elapsed interval to the PREVIOUS stage), so
  ``stage_sum_ms`` equals the measured end-to-end latency by
  construction; ``recon`` (their ratio) is emitted on every record and
  the request_smoke gate fails if it drifts past 5%.
* **One flag check when disabled.** ``new_trace()`` returns None unless
  the monitor is enabled; every instrumentation site in the spine is a
  single ``req.trace is None`` test.
"""
from __future__ import annotations

import collections
import itertools
import os
import threading
import time

from .. import monitor as _monitor
from ..monitor import trace as _trace

_MONO = time.monotonic
_ids = itertools.count(1)

#: gap between a winning attempt's dispatch and the request's birth,
#: blamed by how that attempt came to exist
_GAP_STAGE = {"hedge": "hedge", "retry": "shed_retry"}

#: reconciliation tolerance the smoke gate audits against
RECON_TOL = 0.05


def _exemplar_cap():
    try:
        return max(1, int(os.environ.get("PADDLE_TPU_REQ_EXEMPLARS", "8")))
    except ValueError:
        return 8


# -- exemplar rings + recent-record buffer ----------------------------------

_lock = threading.Lock()
_worst_ttft = []            # records sorted desc by ttft_ms, capped
_worst_tpot = []            # records sorted desc by tpot_ms, capped
_recent = collections.deque(maxlen=512)


def _remember(rec):
    cap = _exemplar_cap()
    with _lock:
        _recent.append(rec)
        for key, ring in (("ttft_ms", _worst_ttft),
                          ("tpot_ms", _worst_tpot)):
            v = rec.get(key)
            if v is None:
                continue
            ring.append(rec)
            ring.sort(key=lambda r: -(r.get(key) or 0.0))
            del ring[cap:]


def exemplars():
    """The slow-request block for /snapshot and flight_record(): the N
    worst completed waterfalls by ttft and by tpot (full stage
    breakdowns + hop lineage, already JSON-safe)."""
    with _lock:
        return {"cap": _exemplar_cap(),
                "worst_ttft": list(_worst_ttft),
                "worst_tpot": list(_worst_tpot)}


def recent(n=None):
    """The last completed ``serving.request`` records (newest last)."""
    with _lock:
        out = list(_recent)
    return out if n is None else out[-int(n):]


def reset():
    """Clear exemplar rings + the recent buffer (tests, fresh runs)."""
    with _lock:
        del _worst_ttft[:]
        del _worst_tpot[:]
        _recent.clear()


# -- the per-request context ------------------------------------------------

class RequestTrace:
    """Shared identity of one logical request: id, birth time, hop
    lineage, and the done-latch that makes the terminal record unique
    across every attempt (primary, hedge shadows, shed retries)."""

    __slots__ = ("rid", "fid", "kind", "priority", "t0", "lock", "done",
                 "hops", "sheds", "attempts", "flow_open", "record_")

    def __init__(self, kind="serve", priority=1):
        n = next(_ids)
        self.rid = f"{os.getpid()}-{n}"
        self.fid = n                      # numeric flow-event id
        self.kind = kind
        self.priority = priority
        self.t0 = _MONO()
        self.lock = threading.Lock()
        self.done = False
        self.hops = []
        self.sheds = 0
        self.attempts = 0
        self.flow_open = False
        self.record_ = None

    def attempt(self, origin="submit", replica=None, version=None):
        """Mint one dispatch attempt (primary submit, hedge shadow, or
        post-shed retry). The attempt IS what rides on ``req.trace``.
        ``version`` stamps the serving fleet's weights version — the
        audit trail a rolling hot-swap leaves on every record."""
        with self.lock:
            self.attempts += 1
        return Attempt(self, origin, replica, version)

    def hop(self, kind, replica=None, **fields):
        """Record one lineage hop (enqueue/hedge/failover/requeue/shed)
        with a relative timestamp; bounded so a requeue loop can't grow
        the record without limit."""
        entry = {"hop": kind, "t_ms": round((_MONO() - self.t0) * 1e3, 3)}
        if replica is not None:
            entry["replica"] = replica
        if fields:
            entry.update(fields)
        with self.lock:
            if len(self.hops) < 64:
                self.hops.append(entry)

    def note_shed(self, level=None, retry_after_ms=None):
        with self.lock:
            self.sheds += 1
        self.hop("shed", level=level, retry_after_ms=retry_after_ms)

    def record(self):
        """The finalized ``serving.request`` record, or None while the
        request is still in flight."""
        return self.record_


class Attempt:
    """One dispatch timeline within a :class:`RequestTrace` — a stage
    state machine where ``to(stage)`` credits the elapsed interval to
    the stage being LEFT, so the breakdown sums to wall time by
    construction. ``req.trace`` holds the Attempt (None = disabled)."""

    __slots__ = ("ctx", "origin", "replica", "version", "t_start", "stage",
                 "t_mark", "stages", "t_first", "n_tokens", "spec_proposed",
                 "spec_accepted", "prefix_hit")

    def __init__(self, ctx, origin, replica, version=None):
        now = _MONO()
        self.ctx = ctx
        self.origin = origin
        self.replica = replica
        self.version = version
        self.t_start = now
        self.stage = "queue"
        self.t_mark = now
        self.stages = {}
        self.t_first = None
        self.n_tokens = None
        self.spec_proposed = 0
        self.spec_accepted = 0
        # None = request never consulted a prefix cache (single-engine
        # path); True/False = disaggregated lookup verdict
        self.prefix_hit = None

    # -- stage machine ------------------------------------------------------

    def to(self, stage, now=None):
        """Enter ``stage``, crediting the time since the last transition
        to the stage being left. No-op once the context has finalized —
        a disowned attempt waking up on a hung replica can't corrupt the
        already-emitted record."""
        ctx = self.ctx
        with ctx.lock:
            if ctx.done:
                return
            if now is None:
                now = _MONO()
            self.stages[self.stage] = (self.stages.get(self.stage, 0.0)
                                       + (now - self.t_mark))
            self.stage = stage
            self.t_mark = now

    def first_token(self):
        """The TTFT moment: prompt prefill produced a real token. A
        failover re-prefill overwrites it — TTFT is honest about when
        the first token that COUNTED arrived."""
        now = _MONO()
        self.to("decode", now)
        self.t_first = now

    def note_tokens(self, n):
        """``tokens`` (and the ``tpot_ms`` derived from it) count
        *accepted* tokens — the ones the caller actually receives. A
        speculative engine's rejected draft proposals never land here;
        they show in the ``accept_rate`` field instead."""
        self.n_tokens = int(n)

    def note_spec(self, proposed, accepted):
        """Per-tick speculative tally for this sequence: draft tokens
        offered vs accepted by the verify step."""
        self.spec_proposed += int(proposed)
        self.spec_accepted += int(accepted)

    def note_prefix(self, hit):
        """Disaggregated prefill's prefix-cache verdict for this
        request (stamped once at lookup; rides to the terminal
        record's ``prefix_hit`` field)."""
        self.prefix_hit = bool(hit)

    def shed(self, level=None, retry_after_ms=None):
        self.ctx.note_shed(level, retry_after_ms)

    def hop(self, kind, replica=None, **fields):
        self.ctx.hop(kind, replica=replica, **fields)

    # -- the terminal record ------------------------------------------------

    def finalize(self, outcome, error=None):
        """Emit the one terminal record — called from ``resolve_*`` only
        when the future transition actually won. Returns the record, or
        None if another attempt already finalized the context."""
        ctx = self.ctx
        now = _MONO()
        with ctx.lock:
            if ctx.done:
                return None
            ctx.done = True
            # credit the residual of the open stage, so even a request
            # that dies waiting in queue reconciles exactly
            self.stages[self.stage] = (self.stages.get(self.stage, 0.0)
                                       + (now - self.t_mark))
            # the lag between the request's birth and this attempt's
            # dispatch: hedge delay, shed backoff, or (for the primary)
            # plain queue time
            gap = self.t_start - ctx.t0
            if gap > 0:
                label = _GAP_STAGE.get(self.origin, "queue")
                self.stages[label] = self.stages.get(label, 0.0) + gap
            hops = list(ctx.hops)
            attempts = ctx.attempts
            sheds = ctx.sheds

        e2e_ms = (now - ctx.t0) * 1e3
        stage_sum_ms = sum(self.stages.values()) * 1e3
        tokens = self.n_tokens
        ttft_ms = tpot_ms = None
        if outcome == "ok":
            if ctx.kind == "decode":
                if self.t_first is not None:
                    ttft_ms = (self.t_first - ctx.t0) * 1e3
                    if tokens is not None and tokens > 1:
                        tpot_ms = (now - self.t_first) * 1e3 / (tokens - 1)
            else:
                # a fixed-shape request's single answer IS its first
                # token: ttft == e2e, and tpot is undefined
                ttft_ms = e2e_ms

        rec = {
            "rid": ctx.rid,
            "reqkind": ctx.kind,
            "outcome": outcome,
            "priority": ctx.priority,
            "origin": self.origin,
            "replica": self.replica,
            "attempts": attempts,
            "sheds": sheds,
            "tokens": tokens,
            "e2e_ms": round(e2e_ms, 3),
            "ttft_ms": round(ttft_ms, 3) if ttft_ms is not None else None,
            "tpot_ms": round(tpot_ms, 3) if tpot_ms is not None else None,
            "stage_sum_ms": round(stage_sum_ms, 3),
            "recon": (round(stage_sum_ms / e2e_ms, 4) if e2e_ms > 0
                      else 1.0),
            "hops": hops,
        }
        if self.version is not None:
            rec["weights_version"] = self.version
        if self.prefix_hit is not None:
            rec["prefix_hit"] = self.prefix_hit
        for stage, secs in self.stages.items():
            rec[f"{stage}_ms"] = round(secs * 1e3, 3)
        if self.spec_proposed:
            rec["spec_proposed"] = self.spec_proposed
            rec["spec_accepted"] = self.spec_accepted
            rec["accept_rate"] = round(
                self.spec_accepted / self.spec_proposed, 4)
        if error is not None:
            rec["error"] = error
        ctx.record_ = rec

        if _monitor.enabled():
            _monitor.counter("serving.request_records").inc()
            if abs(rec["recon"] - 1.0) > RECON_TOL:
                _monitor.counter("serving.request_recon_fail").inc()
            _monitor.emit(kind="serving.request", **rec)
            if outcome == "ok":
                from . import metrics
                metrics.record_request_slo(ttft_ms, tpot_ms)
        _remember(rec)
        if _trace.enabled():
            with _trace.span("serving.request_done", rid=ctx.rid,
                             outcome=outcome):
                _trace.flow_end("serving.req", ctx.fid)
        return rec


# -- spine-facing helpers ---------------------------------------------------

def new_trace(kind="serve", priority=1):
    """Mint the per-request context at submit() — None unless the
    monitor is enabled (the ONE flag check the disabled path pays)."""
    if not _monitor.enabled():
        return None
    return RequestTrace(kind, priority)


def attach(trace, kind="serve", priority=1, replica=None, version=None):
    """The make_request() entry point: mint a fresh context (trace=None)
    or a retry attempt on an existing one (trace=RequestTrace from a
    shed caller re-submitting). Returns the Attempt to ride on
    ``req.trace``, or None when tracing is off. ``version`` is the
    serving engine's current weights version (stamped into the terminal
    record)."""
    if trace is None:
        ctx = new_trace(kind, priority)
        return None if ctx is None else ctx.attempt("submit", replica,
                                                    version)
    if isinstance(trace, Attempt):
        trace = trace.ctx
    return trace.attempt("retry", replica, version)


def transition(requests, stage, flow=False):
    """Batch-wide stage transition from the drain thread; optionally
    drop a flow-event breadcrumb inside the caller's enclosing span so
    Perfetto draws the cross-thread hop arrow."""
    for r in requests:
        tr = r.trace
        if tr is not None:
            tr.to(stage)
            if flow:
                flow_mark(tr)


def flow_mark(att, terminal=False):
    """Emit the request's flow event on the current thread (ph "s" the
    first time its context is seen, "t" after, "f" at terminal). Must be
    called inside an open span for Perfetto to anchor the arrow."""
    if att is None or not _trace.enabled():
        return
    ctx = att.ctx if isinstance(att, Attempt) else att
    if terminal:
        _trace.flow_end("serving.req", ctx.fid)
        return
    if not ctx.flow_open:
        ctx.flow_open = True
        _trace.flow_start("serving.req", ctx.fid, rid=ctx.rid)
    else:
        _trace.flow_step("serving.req", ctx.fid)


__all__ = ["RequestTrace", "Attempt", "new_trace", "attach", "transition",
           "flow_mark", "exemplars", "recent", "reset", "RECON_TOL"]
