"""paddle_tpu.serving.metrics — the serving tier's observability surface.

Every record_* helper is a no-op while the monitor is disabled (the
framework's zero-cost-when-off discipline); with ``monitor.enable()``
the serving pipeline shows up as:

* ``serving.requests`` / ``serving.rows``    — submitted requests and
  their total example rows
* ``serving.qps``        — completed requests/sec, gauge over a rolling
  window (:data:`QPS_WINDOW_S`)
* ``serving.queue_depth`` — requests waiting, gauge set at every
  enqueue/dequeue edge
* ``serving.batches``    — coalesced batches executed
* ``serving.batch_fill`` — histogram: requests coalesced per batch
  (> 1 means dynamic batching is actually amortizing dispatch)
* ``serving.batch_occupancy`` — histogram: real rows ÷ bucket rows
  (the ``io.bucketing.batch_mask`` mean — how much MXU work is real
  vs. pad)
* ``serving.pad_rows``   — pad rows shipped to the device
* ``serving.latency_ms`` — histogram: submit→resolve per request
* ``serving.rejected``   — fast-rejects at a full queue
* ``serving.deadline_expired`` — requests dropped at dequeue past SLA
* ``serving.compiles``   — executables minted by the serving path
  (warmup included; steady state must hold this flat)
* ``serving.retries`` / ``serving.isolated`` / ``serving.poisoned`` —
  transient batch retries, batches re-run request-by-request after a
  terminal failure, and the requests that individually failed

Resilience series (the self-healing layer):

* ``serving.shed`` — requests shed by the admission ladder (below the
  top-rung ``serving.rejected``); ``serving.shed_level`` gauge is the
  ladder rung currently in force
* ``serving.breaker_state.<replica>`` — per-replica breaker gauge
  (0 = closed, 1 = half_open, 2 = open); ``serving.breaker_open`` /
  ``serving.breaker_closed`` count the transitions
* ``serving.hedged`` / ``serving.hedge_wins`` — straggler re-dispatches
  and how many beat the primary
* ``serving.failover`` — batches re-dispatched off a tripped replica
* ``serving.replica_hung`` / ``serving.replica_restarts`` — supervision
  verdicts and the restarts they caused
* ``serving.active_replicas`` — gauge, replicas currently taking
  traffic (the supervisor's scaling output)

SLO rollups (published by the telemetry sampler via
:func:`publish_rollups`, rolling :data:`SLO_WINDOW_S` window):

* ``slo.goodput``  — completions within deadline ÷ submissions
* ``slo.p50_ms`` / ``slo.p99_ms`` — service-latency percentiles
* ``slo.ttft_p50_ms`` / ``slo.ttft_p99_ms`` — time-to-first-token
  percentiles (fed per-request by the reqtrace terminal records; for
  fixed-shape requests ttft == service latency)
* ``slo.tpot_p50_ms`` / ``slo.tpot_p99_ms`` — time-per-output-token
  percentiles (multi-token decode requests only)
* ``slo.window_submitted`` / ``slo.window_within_sla`` — the raw
  window tallies behind the ratio

Request-scoped records (``serving.reqtrace``): each completed request
emits exactly one ``serving.request`` JSONL record with a stage-blamed
latency breakdown; ``serving.ttft_ms`` / ``serving.tpot_ms`` histograms
(and every serving latency histogram) use :data:`LATENCY_BUCKETS_MS` —
log-spaced decode-scale bounds from 1 µs to 10 s.

``serving.qps`` decays to 0 when traffic stops: the sampler calls
:func:`qps_now` each tick, which sweeps stale window entries instead
of waiting for a next completion that never comes.

Generative-decode series (the continuous-batching engine):

* ``serving.decode.ticks`` / ``serving.decode.tokens`` — fused decode
  steps executed and tokens they produced
* ``serving.decode.slot_occupancy`` — gauge + histogram: active slots ÷
  total slots per tick (continuous batching's whole point is holding
  this near 1.0 under churn)
* ``serving.decode.prefill_tokens`` / ``serving.decode.prefill_ms`` —
  prompt tokens ingested and per-prefill latency histogram
* ``serving.decode.step_ms`` — per-tick decode latency histogram
* ``serving.decode.prefill_ratio`` — gauge: prefill time ÷ (prefill +
  decode) time over the rolling window (how much of the engine is
  spent ingesting prompts vs. emitting tokens)
* ``serving.decode.compiles`` — executables minted by the decode path
  (prefill buckets + decode step + cache grows; zero growth after
  warmup is a smoke gate)
* ``serving.decode.cache_bytes`` / ``serving.decode.cache_capacity`` /
  ``serving.decode.cache_headroom`` — KV-pool footprint, its current
  length bucket, and worst-case headroom vs the PR 12 memory model's
  device budget
* ``serving.decode.cache_grows`` — capacity steps along the bucket
  family
* ``slo.tokens_per_s`` / ``slo.decode_p99_ms`` — rolling decode SLO
  window (:data:`TOKENS_WINDOW_S`) the supervisor scales replicas off

Speculative-decode series (draft-model verify loop; every token series
above counts **accepted** tokens only — rejected draft proposals never
inflate ``serving.decode.tokens`` or ``slo.tokens_per_s``):

* ``serving.decode.draft_steps`` — draft-model autoregressive steps
  (k per speculative tick)
* ``serving.decode.verify_steps`` — batched target verify steps (one
  per speculative tick)
* ``serving.decode.spec_proposed`` / ``serving.decode.spec_accepted``
  — draft proposals offered vs accepted by the accept-prefix rule
* ``serving.decode.accept_rate`` — gauge: accepted ÷ proposed over the
  rolling :data:`TOKENS_WINDOW_S` window (the health signal for a
  draft/target pairing — a cold draft shows up here first)
* ``serving.decode.spec_tokens_per_step`` — gauge: accepted tokens
  (resample included) per verify step over the window; the speculative
  multiplier actually realized, upper-bounded by ``spec_k``
* ``serving.decode.rollbacks`` / ``serving.decode.rollback_tokens`` —
  KV-ledger truncations after verify rejects (optimistically written
  positions beyond the accepted prefix), target and draft arenas
  combined; the draft arena's footprint publishes under
  ``serving.decode.draft_cache_bytes`` / ``..draft_cache_capacity``

Disaggregated-serving series (prefill pool → decode pool; PR 20):

* ``serving.handoff.bytes`` — gauge: the last planned KV transfer's
  exact payload (``bytes_per_token(spec) × prompt bucket``);
  ``serving.handoff.bytes_total`` accumulates them
* ``serving.handoff.ms`` — histogram: measured handoff latency
  (transfer + decode-slot queueing); ``serving.handoff.planned_ms``
  gauge is the link-model prediction (``bytes / link_bandwidth()``)
* ``serving.handoff.queue_depth`` — gauge: segments waiting for a
  decode slot at plan time
* ``serving.prefix.hits`` / ``serving.prefix.misses`` — prefix-cache
  verdicts; ``serving.prefix.hit_rate`` gauge over the rolling
  :data:`TOKENS_WINDOW_S` window
* ``serving.prefix.lookup_ms`` — histogram: cache probe latency
* ``serving.prefix.bytes`` / ``serving.prefix.entries`` /
  ``serving.prefix.budget_bytes`` — resident cache footprint vs its
  ``fits_budget``-style byte budget; ``serving.prefix.evictions``
  counts LRU victims

Span sites (``monitor.trace``): ``serving.enqueue``,
``serving.batch_assemble``, ``serving.execute``, ``serving.scatter``,
``serving.warmup`` — the Perfetto view of queue→batch→MXU.
"""
from __future__ import annotations

import collections
import threading
import time

from .. import monitor as _monitor
from ..io.bucketing import batch_mask

#: rolling window for the serving.qps gauge
QPS_WINDOW_S = 10.0
#: rolling window for the slo.* goodput / latency-percentile gauges
SLO_WINDOW_S = 60.0

#: decode-scale latency bounds for every serving histogram: log-spaced
#: (x~2.15 per step) from 1 µs to 10 s, so a p99 on single-token decode
#: ticks (sub-ms) and a p99 on long-prompt prefills (hundreds of ms)
#: both resolve instead of collapsing into one default bucket
LATENCY_BUCKETS_MS = tuple(round(10.0 ** (e / 3.0), 6)
                           for e in range(-9, 13))

_qps_lock = threading.Lock()
_qps_window = collections.deque()   # (t_monotonic, n_completed)

_slo_lock = threading.Lock()
_slo_submits = collections.deque()  # t_monotonic per submitted request
_slo_done = collections.deque()     # (t, latency_ms|None, within_sla)
_slo_ttft = collections.deque()     # (t, ttft_ms) per completed request
_slo_tpot = collections.deque()     # (t, tpot_ms) per multi-token req


def record_submit(n_rows):
    if _monitor.enabled():
        _monitor.counter("serving.requests").inc()
        _monitor.counter("serving.rows").inc(int(n_rows))
        now = time.monotonic()
        with _slo_lock:
            _slo_submits.append(now)
            _sweep(_slo_submits, now, SLO_WINDOW_S, key=lambda t: t)


def record_queue_depth(depth):
    if _monitor.enabled():
        _monitor.gauge("serving.queue_depth").set(int(depth))


def record_reject():
    if _monitor.enabled():
        _monitor.counter("serving.rejected").inc()
        _monitor.emit(kind="serving", event="rejected")


def record_expired():
    if _monitor.enabled():
        _monitor.counter("serving.deadline_expired").inc()
        _monitor.emit(kind="serving", event="deadline_expired")
        now = time.monotonic()
        with _slo_lock:
            # an expired request is a completed-OUTSIDE-SLA outcome for
            # goodput; it has no service latency to histogram
            _slo_done.append((now, None, False))
            _sweep(_slo_done, now, SLO_WINDOW_S)


def record_batch(real_rows, bucket_rows, n_requests):
    if not _monitor.enabled():
        return
    _monitor.counter("serving.batches").inc()
    _monitor.histogram("serving.batch_fill").observe(float(n_requests))
    occupancy = float(batch_mask(real_rows, bucket_rows).mean())
    _monitor.histogram("serving.batch_occupancy").observe(occupancy)
    if bucket_rows > real_rows:
        _monitor.counter("serving.pad_rows").inc(int(bucket_rows - real_rows))


def record_completed(n_requests, latencies_ms, within_sla=None):
    """Per-batch completion: latency histogram per request, the rolling
    QPS gauge, and the slo.* window. ``within_sla`` is a per-request
    bool list (completed before its deadline; None = no deadlines in
    play, every completion counts as within)."""
    if not _monitor.enabled():
        return
    h = _monitor.histogram("serving.latency_ms",
                           buckets=LATENCY_BUCKETS_MS)
    for ms in latencies_ms:
        h.observe(float(ms))
    now = time.monotonic()
    with _qps_lock:
        _qps_window.append((now, int(n_requests)))
        _set_qps_locked(now)
    with _slo_lock:
        for i, ms in enumerate(latencies_ms):
            ok = True if within_sla is None else bool(within_sla[i])
            _slo_done.append((now, float(ms), ok))
        _sweep(_slo_done, now, SLO_WINDOW_S)


def record_request_slo(ttft_ms=None, tpot_ms=None):
    """One completed request's generative SLO sample, fed by the
    reqtrace terminal record: time-to-first-token and (multi-token
    requests only) time-per-output-token, rolled into the live windows
    behind ``slo.ttft_*`` / ``slo.tpot_*`` and histogrammed on the
    decode-scale bounds."""
    if not _monitor.enabled():
        return
    now = time.monotonic()
    with _slo_lock:
        if ttft_ms is not None:
            _slo_ttft.append((now, float(ttft_ms)))
            _sweep(_slo_ttft, now, SLO_WINDOW_S)
        if tpot_ms is not None:
            _slo_tpot.append((now, float(tpot_ms)))
            _sweep(_slo_tpot, now, SLO_WINDOW_S)
    if ttft_ms is not None:
        _monitor.histogram("serving.ttft_ms",
                           buckets=LATENCY_BUCKETS_MS).observe(
            float(ttft_ms))
    if tpot_ms is not None:
        _monitor.histogram("serving.tpot_ms",
                           buckets=LATENCY_BUCKETS_MS).observe(
            float(tpot_ms))


def _sweep(dq, now, horizon, key=lambda item: item[0]):
    """Drop window entries older than ``horizon`` (callers hold the
    window's lock)."""
    while dq and now - key(dq[0]) > horizon:
        dq.popleft()


def _set_qps_locked(now):
    _sweep(_qps_window, now, QPS_WINDOW_S)
    if not _qps_window:
        _monitor.gauge("serving.qps").set(0.0)
        return 0.0
    total = sum(k for _, k in _qps_window)
    elapsed = max(now - _qps_window[0][0], 0.5)
    val = round(total / elapsed, 3)
    _monitor.gauge("serving.qps").set(val)
    return val


def qps_now(now=None):
    """Sweep the rolling window and re-publish ``serving.qps`` from
    what's left — when traffic stops, the stale entries age out HERE
    instead of waiting for a next completion that never comes, so the
    gauge decays to 0. Called by the telemetry sampler each tick; safe
    to call from anywhere."""
    if not _monitor.enabled():
        return 0.0
    now = time.monotonic() if now is None else now
    with _qps_lock:
        return _set_qps_locked(now)


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def slo_rollup(now=None):
    """Rolling-window SLO accounting over the last :data:`SLO_WINDOW_S`
    seconds: ``goodput`` = completions within deadline ÷ submissions
    (expired requests count against it; an in-flight backlog does too,
    which is the honest reading under overload), plus p50/p99 service
    latency. Returns the dict and, when the monitor is enabled,
    publishes it as ``slo.*`` gauges."""
    now = time.monotonic() if now is None else now
    with _slo_lock:
        _sweep(_slo_submits, now, SLO_WINDOW_S, key=lambda t: t)
        _sweep(_slo_done, now, SLO_WINDOW_S)
        _sweep(_slo_ttft, now, SLO_WINDOW_S)
        _sweep(_slo_tpot, now, SLO_WINDOW_S)
        submitted = len(_slo_submits)
        done = list(_slo_done)
        ttfts = sorted(v for _, v in _slo_ttft)
        tpots = sorted(v for _, v in _slo_tpot)
    ok = sum(1 for _, _, w in done if w)
    lats = sorted(ms for _, ms, _ in done if ms is not None)
    out = {"window_s": SLO_WINDOW_S, "submitted": submitted,
           "completed": len(lats), "within_sla": ok,
           "goodput": (ok / submitted) if submitted else None,
           "p50_ms": _percentile(lats, 0.50),
           "p99_ms": _percentile(lats, 0.99),
           "ttft_p50_ms": _percentile(ttfts, 0.50),
           "ttft_p99_ms": _percentile(ttfts, 0.99),
           "tpot_p50_ms": _percentile(tpots, 0.50),
           "tpot_p99_ms": _percentile(tpots, 0.99)}
    if _monitor.enabled():
        for key in ("goodput", "p50_ms", "p99_ms", "ttft_p50_ms",
                    "ttft_p99_ms", "tpot_p50_ms", "tpot_p99_ms"):
            if out[key] is not None:
                _monitor.gauge(f"slo.{key}").set(out[key])
        _monitor.gauge("slo.window_submitted").set(submitted)
        _monitor.gauge("slo.window_within_sla").set(ok)
    return out


def publish_rollups(now=None):
    """One sampler tick's worth of derived series: the decaying
    ``serving.qps`` gauge plus the ``slo.*`` rollup (decode window
    included when decode traffic exists)."""
    qps_now(now)
    out = slo_rollup(now)
    out["decode"] = decode_rollup(now)
    return out


def reset_windows():
    """Empty every rolling window (test isolation)."""
    with _qps_lock:
        _qps_window.clear()
    with _slo_lock:
        _slo_submits.clear()
        _slo_done.clear()
        _slo_ttft.clear()
        _slo_tpot.clear()
    with _decode_lock:
        _tokens_window.clear()
        _decode_steps.clear()
        _prefill_steps.clear()
        _spec_window.clear()
        _prefix_window.clear()


def record_compiles(n=1):
    if _monitor.enabled():
        _monitor.counter("serving.compiles").inc(int(n))


def record_retry(where=""):
    if _monitor.enabled():
        _monitor.counter("serving.retries").inc()
        _monitor.emit(kind="serving", event="retry", where=where)


def record_isolated(n_requests):
    if _monitor.enabled():
        _monitor.counter("serving.isolated").inc(int(n_requests))
        _monitor.emit(kind="serving", event="isolated",
                      requests=int(n_requests))


def record_poisoned(error=""):
    if _monitor.enabled():
        _monitor.counter("serving.poisoned").inc()
        _monitor.emit(kind="serving", event="poisoned", error=error)


def goodput_window(now=None):
    """Cheap read of the slo window for control loops: (goodput|None,
    submitted). Unlike :func:`slo_rollup` this publishes nothing and
    skips the latency sort — it's called from the admission hot path.
    The window only fills while the monitor is enabled, so SLO-driven
    shedding (like the rest of the slo plane) needs ``monitor.enable()``."""
    now = time.monotonic() if now is None else now
    with _slo_lock:
        _sweep(_slo_submits, now, SLO_WINDOW_S, key=lambda t: t)
        _sweep(_slo_done, now, SLO_WINDOW_S)
        submitted = len(_slo_submits)
        ok = sum(1 for _, _, w in _slo_done if w)
    return ((ok / submitted) if submitted else None), submitted


# -- resilience series ------------------------------------------------------

#: ``draining`` is a routing state, not a breaker state — a draining
#: replica is healthy but refusing new work while it finishes (or
#: migrates) what it holds; /healthz and the gauges must not read it
#: as ``open``
_BREAKER_STATE_NUM = {"closed": 0, "half_open": 1, "open": 2,
                      "draining": 3}


def record_shed(priority, level, retry_after_ms):
    if _monitor.enabled():
        _monitor.counter("serving.shed").inc()
        _monitor.gauge("serving.shed_level").set(int(level))
        _monitor.emit(kind="serving", event="shed", priority=priority,
                      level=int(level), retry_after_ms=float(retry_after_ms))


def record_shed_level(level):
    if _monitor.enabled():
        _monitor.gauge("serving.shed_level").set(int(level))


def record_breaker_transition(name, old, new, reason=""):
    if _monitor.enabled():
        _monitor.gauge(f"serving.breaker_state.{name}").set(
            _BREAKER_STATE_NUM.get(new, -1))
        if new == "open":
            _monitor.counter("serving.breaker_open").inc()
        elif new == "closed":
            _monitor.counter("serving.breaker_closed").inc()
        _monitor.emit(kind="serving", event="breaker", name=name,
                      old=old, new=new, reason=reason)


def clear_replica_series(replica):
    """Source-scoped stale-gauge hygiene: drop the per-replica gauges a
    closed or restarted replica left behind (``serving.breaker_state.
    <replica>`` and anything under ``serving.replica.<replica>.``) so a
    dead replica's last breaker state can't linger in rollups forever.
    The fleet aggregator's staleness TTL handles the cross-process
    copy; this handles the in-process registry. Returns how many
    metrics were dropped."""
    if not _monitor.enabled():
        return 0
    reg = _monitor.registry()
    removed = int(reg.remove(f"serving.breaker_state.{replica}"))
    removed += reg.clear_prefix(f"serving.replica.{replica}.")
    if removed:
        _monitor.emit(kind="serving", event="replica_series_cleared",
                      replica=replica, removed=removed)
    return removed


def assert_mergeable_latency_histograms(registry=None):
    """Every ``*_ms`` serving/slo histogram in the registry must carry
    exactly :data:`LATENCY_BUCKETS_MS` bounds — the invariant that
    makes fleet bucket-wise merge legal. Raises AssertionError naming
    the offender; returns the checked names (mergeability is asserted,
    not assumed — tests/test_fleet.py and the telemetry smoke both
    call this)."""
    reg = registry if registry is not None else _monitor.registry()
    checked = []
    for name in reg.names():
        if not (name.startswith(("serving.", "slo."))
                and name.endswith("_ms")):
            continue
        m = reg.get(name)
        if m is None or m.kind != "histogram":
            continue
        if tuple(m.buckets) != tuple(LATENCY_BUCKETS_MS):
            raise AssertionError(
                f"histogram {name!r} registered with "
                f"{len(m.buckets)} non-standard bounds — fleet merge "
                f"needs LATENCY_BUCKETS_MS ({len(LATENCY_BUCKETS_MS)} "
                "bounds)")
        checked.append(name)
    return checked


def record_hedge(replica=None):
    if _monitor.enabled():
        _monitor.counter("serving.hedged").inc()
        _monitor.emit(kind="serving", event="hedged", replica=replica)


def record_hedge_win(replica=None):
    if _monitor.enabled():
        _monitor.counter("serving.hedge_wins").inc()
        _monitor.emit(kind="serving", event="hedge_win", replica=replica)


def record_failover(replica, n_requests):
    if _monitor.enabled():
        _monitor.counter("serving.failover").inc()
        _monitor.emit(kind="serving", event="failover", replica=replica,
                      requests=int(n_requests))


def record_replica_hung(replica, age_s):
    if _monitor.enabled():
        _monitor.counter("serving.replica_hung").inc()
        _monitor.emit(kind="serving", event="replica_hung",
                      replica=replica, inflight_age_s=round(float(age_s), 3))


def record_replica_restart(replica):
    if _monitor.enabled():
        _monitor.counter("serving.replica_restarts").inc()
        _monitor.emit(kind="serving", event="replica_restart",
                      replica=replica)


def record_active_replicas(n):
    if _monitor.enabled():
        _monitor.gauge("serving.active_replicas").set(int(n))


def record_lifecycle(event, **fields):
    """Serving lifecycle ledger (``serving.lifecycle.*``): drains,
    undrains, weight swaps, refused publishes — the events /snapshot
    replays to explain a fleet's zero-downtime history."""
    if _monitor.enabled():
        _monitor.counter(f"serving.lifecycle.{event}").inc()
        _monitor.emit(kind="serving", event="lifecycle",
                      lifecycle=event, **fields)


def record_weights_version(version):
    if _monitor.enabled():
        _monitor.gauge("serving.weights_version").set(int(version))


def record_supervisor(decision, **fields):
    """Planner-style decision record: a ledger event the monitor JSONL
    (and /snapshot) can replay to explain why the fleet changed shape."""
    if _monitor.enabled():
        _monitor.counter("serving.supervisor_decisions").inc()
        _monitor.emit(kind="serving", event="supervisor",
                      decision=decision, **fields)


# -- generative decode series -----------------------------------------------

#: rolling window for the slo.tokens_per_s / slo.decode_p99_ms gauges —
#: shorter than SLO_WINDOW_S because token throughput is the supervisor's
#: fast control signal (a 60s window would lag a traffic step by a minute)
TOKENS_WINDOW_S = 15.0

_decode_lock = threading.Lock()
_tokens_window = collections.deque()   # (t_monotonic, n_tokens)
_decode_steps = collections.deque()    # (t, step_ms)
_prefill_steps = collections.deque()   # (t, prefill_ms)
_spec_window = collections.deque()     # (t, proposed, accepted, emitted)
_prefix_window = collections.deque()   # (t, hit: bool)


def record_decode_tick(active_slots, total_slots, n_tokens, step_ms):
    """One fused decode step: ``n_tokens`` emitted across
    ``active_slots`` live sequences in ``step_ms``."""
    occupancy = (float(active_slots) / float(total_slots)
                 if total_slots else 0.0)
    now = time.monotonic()
    with _decode_lock:
        _tokens_window.append((now, int(n_tokens)))
        _decode_steps.append((now, float(step_ms)))
        _sweep(_tokens_window, now, TOKENS_WINDOW_S)
        _sweep(_decode_steps, now, TOKENS_WINDOW_S)
    if not _monitor.enabled():
        return
    _monitor.counter("serving.decode.ticks").inc()
    _monitor.counter("serving.decode.tokens").inc(int(n_tokens))
    _monitor.gauge("serving.decode.slot_occupancy").set(round(occupancy, 4))
    _monitor.histogram("serving.decode.occupancy_hist").observe(occupancy)
    _monitor.histogram("serving.decode.step_ms",
                       buckets=LATENCY_BUCKETS_MS).observe(float(step_ms))


def record_prefill(n_tokens, prefill_ms, bucket):
    """One prefill executable run: a ``bucket``-length prompt ingest."""
    now = time.monotonic()
    with _decode_lock:
        _prefill_steps.append((now, float(prefill_ms)))
        _sweep(_prefill_steps, now, TOKENS_WINDOW_S)
    if not _monitor.enabled():
        return
    _monitor.counter("serving.decode.prefills").inc()
    _monitor.counter("serving.decode.prefill_tokens").inc(int(n_tokens))
    _monitor.histogram("serving.decode.prefill_ms",
                       buckets=LATENCY_BUCKETS_MS).observe(
        float(prefill_ms))
    _monitor.emit(kind="serving", event="prefill", tokens=int(n_tokens),
                  bucket=int(bucket), ms=round(float(prefill_ms), 3))


def record_decode_compile(n=1, what=""):
    """An executable minted by the decode path. Counted both in the
    decode-local series (the zero-growth-after-warmup smoke gate) and
    the engine-wide ``serving.compiles``."""
    if _monitor.enabled():
        _monitor.counter("serving.decode.compiles").inc(int(n))
        _monitor.counter("serving.compiles").inc(int(n))
        if what:
            _monitor.emit(kind="serving", event="decode_compile", what=what)


def record_cache(cache_bytes, capacity, headroom_bytes=None,
                 limit_bytes=None, label=None):
    """KV-arena footprint gauges; ``label`` namespaces a secondary
    arena (the speculative draft pool publishes under
    ``serving.decode.draft_cache_*``)."""
    if not _monitor.enabled():
        return
    prefix = f"serving.decode.{label}_cache" if label \
        else "serving.decode.cache"
    _monitor.gauge(f"{prefix}_bytes").set(int(cache_bytes))
    _monitor.gauge(f"{prefix}_capacity").set(int(capacity))
    if headroom_bytes is not None:
        _monitor.gauge(f"{prefix}_headroom").set(int(headroom_bytes))
    if limit_bytes is not None:
        _monitor.gauge(f"{prefix}_limit").set(int(limit_bytes))


def record_cache_grow(new_capacity):
    if _monitor.enabled():
        _monitor.counter("serving.decode.cache_grows").inc()
        _monitor.emit(kind="serving", event="cache_grow",
                      capacity=int(new_capacity))


def record_rollback(n_tokens, label=None):
    """A KV-ledger truncation: ``n_tokens`` optimistically-written
    positions past the accepted prefix went dead (speculative verify
    reject)."""
    if _monitor.enabled():
        _monitor.counter("serving.decode.rollbacks").inc()
        _monitor.counter("serving.decode.rollback_tokens").inc(
            int(n_tokens))


def record_spec_tick(proposed, accepted, emitted, draft_steps):
    """One speculative tick across the batch: the draft offered
    ``proposed`` tokens (``draft_steps`` autoregressive draft calls),
    the accept-prefix rule kept ``accepted`` of them, and ``emitted``
    tokens actually landed (accepted prefix + the residual resample;
    these are the ONLY tokens that count toward tokens/s). Fills the
    rolling accept-rate window whether or not the monitor is enabled —
    it's a control signal, like :func:`tokens_window`."""
    now = time.monotonic()
    with _decode_lock:
        _spec_window.append((now, int(proposed), int(accepted),
                             int(emitted)))
        _sweep(_spec_window, now, TOKENS_WINDOW_S)
    if not _monitor.enabled():
        return
    _monitor.counter("serving.decode.draft_steps").inc(int(draft_steps))
    _monitor.counter("serving.decode.verify_steps").inc()
    _monitor.counter("serving.decode.spec_proposed").inc(int(proposed))
    _monitor.counter("serving.decode.spec_accepted").inc(int(accepted))
    rate, per_step = spec_window(now)
    if rate is not None:
        _monitor.gauge("serving.decode.accept_rate").set(round(rate, 4))
    if per_step is not None:
        _monitor.gauge("serving.decode.spec_tokens_per_step").set(
            round(per_step, 3))


def spec_window(now=None):
    """Control-loop read of the speculative window: (accept_rate |
    None, emitted tokens per verify step | None) over the last
    :data:`TOKENS_WINDOW_S` seconds. None means no speculative traffic
    in the window."""
    now = time.monotonic() if now is None else now
    with _decode_lock:
        _sweep(_spec_window, now, TOKENS_WINDOW_S)
        if not _spec_window:
            return None, None
        proposed = sum(p for _, p, _a, _e in _spec_window)
        accepted = sum(a for _, _p, a, _e in _spec_window)
        emitted = sum(e for _, _p, _a, e in _spec_window)
        steps = len(_spec_window)
    rate = (accepted / proposed) if proposed else None
    return rate, emitted / steps


def tokens_window(now=None):
    """Cheap control-loop read: (tokens_per_s | None, decode_p99_ms |
    None) over the last :data:`TOKENS_WINDOW_S` seconds. None means no
    decode traffic in the window — the supervisor must not treat an
    idle engine as a throughput breach. Unlike the slo.* window this
    fills whether or not the monitor is enabled (the engine always
    appends; only the gauges need the monitor)."""
    now = time.monotonic() if now is None else now
    with _decode_lock:
        _sweep(_tokens_window, now, TOKENS_WINDOW_S)
        _sweep(_decode_steps, now, TOKENS_WINDOW_S)
        if not _tokens_window:
            return None, None
        total = sum(k for _, k in _tokens_window)
        elapsed = max(now - _tokens_window[0][0], 0.25)
        steps = sorted(ms for _, ms in _decode_steps)
    return total / elapsed, _percentile(steps, 0.99)


def decode_rollup(now=None):
    """Publish the decode SLO window: ``slo.tokens_per_s``,
    ``slo.decode_p99_ms``, and the rolling prefill/decode time ratio.
    Returns the dict (gauges only when the monitor is enabled)."""
    now = time.monotonic() if now is None else now
    tps, p99 = tokens_window(now)
    with _decode_lock:
        _sweep(_prefill_steps, now, TOKENS_WINDOW_S)
        pf = sorted(ms for _, ms in _prefill_steps)
        prefill_ms = sum(pf)
        decode_ms = sum(ms for _, ms in _decode_steps)
    busy = prefill_ms + decode_ms
    ratio = (prefill_ms / busy) if busy > 0 else None
    accept_rate, spec_per_step = spec_window(now)
    out = {"tokens_per_s": tps, "decode_p99_ms": p99,
           "prefill_p50_ms": _percentile(pf, 0.50),
           "prefill_ratio": ratio,
           "accept_rate": accept_rate,
           "spec_tokens_per_step": spec_per_step}
    if _monitor.enabled():
        if tps is not None:
            _monitor.gauge("slo.tokens_per_s").set(round(tps, 3))
        if p99 is not None:
            _monitor.gauge("slo.decode_p99_ms").set(round(p99, 3))
        if ratio is not None:
            _monitor.gauge("serving.decode.prefill_ratio").set(
                round(ratio, 4))
    return out


# -- disaggregated serving series (handoff + prefix cache) ------------------


def record_handoff(n_bytes, planned_ms, actual_ms, queue_depth=0):
    """One planned prefill→decode KV transfer: ``n_bytes`` is the exact
    spec arithmetic (``bytes_per_token × bucket``), ``planned_ms`` the
    link-model prediction, ``actual_ms`` the measured transfer +
    decode-slot wait."""
    if not _monitor.enabled():
        return
    _monitor.counter("serving.handoff.transfers").inc()
    _monitor.counter("serving.handoff.bytes_total").inc(int(n_bytes))
    _monitor.gauge("serving.handoff.bytes").set(int(n_bytes))
    _monitor.gauge("serving.handoff.planned_ms").set(
        round(float(planned_ms), 6))
    _monitor.gauge("serving.handoff.queue_depth").set(int(queue_depth))
    _monitor.histogram("serving.handoff.ms",
                       buckets=LATENCY_BUCKETS_MS).observe(
        float(actual_ms))
    _monitor.emit(kind="serving", event="handoff", bytes=int(n_bytes),
                  planned_ms=round(float(planned_ms), 6),
                  ms=round(float(actual_ms), 3),
                  queue_depth=int(queue_depth))


def record_prefix_lookup(hit, lookup_ms):
    """One prefix-cache probe. Fills the rolling hit-rate window
    whether or not the monitor is enabled — it's a control signal,
    like :func:`spec_window`."""
    now = time.monotonic()
    with _decode_lock:
        _prefix_window.append((now, bool(hit)))
        _sweep(_prefix_window, now, TOKENS_WINDOW_S)
    if not _monitor.enabled():
        return
    _monitor.counter("serving.prefix.hits" if hit
                     else "serving.prefix.misses").inc()
    _monitor.histogram("serving.prefix.lookup_ms",
                       buckets=LATENCY_BUCKETS_MS).observe(
        float(lookup_ms))
    rate = prefix_window(now)
    if rate is not None:
        _monitor.gauge("serving.prefix.hit_rate").set(round(rate, 4))


def prefix_window(now=None):
    """Rolling prefix hit rate over the last :data:`TOKENS_WINDOW_S`
    seconds, or None with no lookups in the window."""
    now = time.monotonic() if now is None else now
    with _decode_lock:
        _sweep(_prefix_window, now, TOKENS_WINDOW_S)
        if not _prefix_window:
            return None
        hits = sum(1 for _, h in _prefix_window if h)
        total = len(_prefix_window)
    return hits / total


def record_prefix_cache(cache_bytes, entries, budget_bytes=None):
    """Resident prefix-cache footprint gauges (published by the cache
    on every insert/evict edge)."""
    if not _monitor.enabled():
        return
    _monitor.gauge("serving.prefix.bytes").set(int(cache_bytes))
    _monitor.gauge("serving.prefix.entries").set(int(entries))
    if budget_bytes is not None:
        _monitor.gauge("serving.prefix.budget_bytes").set(
            int(budget_bytes))


def record_prefix_evict(n=1, freed_bytes=0):
    if _monitor.enabled():
        _monitor.counter("serving.prefix.evictions").inc(int(n))
        _monitor.emit(kind="serving", event="prefix_evict", n=int(n),
                      freed_bytes=int(freed_bytes))
