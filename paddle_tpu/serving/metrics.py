"""paddle_tpu.serving.metrics — the serving tier's observability surface.

Every record_* helper is a no-op while the monitor is disabled (the
framework's zero-cost-when-off discipline); with ``monitor.enable()``
the serving pipeline shows up as:

* ``serving.requests`` / ``serving.rows``    — submitted requests and
  their total example rows
* ``serving.qps``        — completed requests/sec, gauge over a rolling
  window (:data:`QPS_WINDOW_S`)
* ``serving.queue_depth`` — requests waiting, gauge set at every
  enqueue/dequeue edge
* ``serving.batches``    — coalesced batches executed
* ``serving.batch_fill`` — histogram: requests coalesced per batch
  (> 1 means dynamic batching is actually amortizing dispatch)
* ``serving.batch_occupancy`` — histogram: real rows ÷ bucket rows
  (the ``io.bucketing.batch_mask`` mean — how much MXU work is real
  vs. pad)
* ``serving.pad_rows``   — pad rows shipped to the device
* ``serving.latency_ms`` — histogram: submit→resolve per request
* ``serving.rejected``   — fast-rejects at a full queue
* ``serving.deadline_expired`` — requests dropped at dequeue past SLA
* ``serving.compiles``   — executables minted by the serving path
  (warmup included; steady state must hold this flat)
* ``serving.retries`` / ``serving.isolated`` / ``serving.poisoned`` —
  transient batch retries, batches re-run request-by-request after a
  terminal failure, and the requests that individually failed

Span sites (``monitor.trace``): ``serving.enqueue``,
``serving.batch_assemble``, ``serving.execute``, ``serving.scatter``,
``serving.warmup`` — the Perfetto view of queue→batch→MXU.
"""
from __future__ import annotations

import collections
import threading
import time

from .. import monitor as _monitor
from ..io.bucketing import batch_mask

#: rolling window for the serving.qps gauge
QPS_WINDOW_S = 10.0

_qps_lock = threading.Lock()
_qps_window = collections.deque()   # (t_monotonic, n_completed)


def record_submit(n_rows):
    if _monitor.enabled():
        _monitor.counter("serving.requests").inc()
        _monitor.counter("serving.rows").inc(int(n_rows))


def record_queue_depth(depth):
    if _monitor.enabled():
        _monitor.gauge("serving.queue_depth").set(int(depth))


def record_reject():
    if _monitor.enabled():
        _monitor.counter("serving.rejected").inc()
        _monitor.emit(kind="serving", event="rejected")


def record_expired():
    if _monitor.enabled():
        _monitor.counter("serving.deadline_expired").inc()
        _monitor.emit(kind="serving", event="deadline_expired")


def record_batch(real_rows, bucket_rows, n_requests):
    if not _monitor.enabled():
        return
    _monitor.counter("serving.batches").inc()
    _monitor.histogram("serving.batch_fill").observe(float(n_requests))
    occupancy = float(batch_mask(real_rows, bucket_rows).mean())
    _monitor.histogram("serving.batch_occupancy").observe(occupancy)
    if bucket_rows > real_rows:
        _monitor.counter("serving.pad_rows").inc(int(bucket_rows - real_rows))


def record_completed(n_requests, latencies_ms):
    """Per-batch completion: latency histogram per request + the rolling
    QPS gauge."""
    if not _monitor.enabled():
        return
    h = _monitor.histogram("serving.latency_ms")
    for ms in latencies_ms:
        h.observe(float(ms))
    now = time.monotonic()
    with _qps_lock:
        _qps_window.append((now, int(n_requests)))
        while _qps_window and now - _qps_window[0][0] > QPS_WINDOW_S:
            _qps_window.popleft()
        total = sum(k for _, k in _qps_window)
        elapsed = max(now - _qps_window[0][0], 0.5)
    _monitor.gauge("serving.qps").set(round(total / elapsed, 3))


def record_compiles(n=1):
    if _monitor.enabled():
        _monitor.counter("serving.compiles").inc(int(n))


def record_retry(where=""):
    if _monitor.enabled():
        _monitor.counter("serving.retries").inc()
        _monitor.emit(kind="serving", event="retry", where=where)


def record_isolated(n_requests):
    if _monitor.enabled():
        _monitor.counter("serving.isolated").inc(int(n_requests))
        _monitor.emit(kind="serving", event="isolated",
                      requests=int(n_requests))


def record_poisoned(error=""):
    if _monitor.enabled():
        _monitor.counter("serving.poisoned").inc()
        _monitor.emit(kind="serving", event="poisoned", error=error)
