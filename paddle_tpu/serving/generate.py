"""paddle_tpu.serving.generate — continuous-batching autoregressive
decode.

The serving tier below this module batches *fixed-shape* requests: one
request, one executable call, one future. Generative traffic is a
different animal — a request is a *sequence* that occupies capacity for
hundreds of steps, and sequences join and leave mid-flight. Batching
discipline decides tokens/s/chip (PAPERS.md: Gemma-on-TPU serving), and
the naive discipline — run a batch of sequences to completion, then
admit the next batch — wastes most of the machine: the batch runs as
long as its *longest* member, so average occupancy is roughly
``mean(len) / max(len)`` and every short sequence's slot idles until
the straggler finishes.

**Continuous batching** is the fix, and this engine implements it:

* a fixed-width decode batch of ``slots`` sequences runs **one fused
  decode step per tick** — every tick advances every live sequence by
  one token in a single pre-compiled executable;
* a finished sequence frees its slot *immediately* (a host-side
  bookkeeping write, nothing device-side moves);
* queued requests are admitted into freed slots **at the next tick** —
  there is no drain-the-batch barrier, so occupancy stays near 1.0
  under churn (the ``refill="drain"`` mode *is* the naive baseline,
  kept in-engine so the A/B in scripts/decode_loadgen.py shares every
  executable with the continuous path);
* **prefill and decode are split**: prompt ingest runs as its own
  bucketed executable (flash-attention path — prompts are the long-
  sequence work the Pallas kernel exists for), writes its KV pages into
  the slot's arena, and hands the last-token state to the decode loop.
  Decode steps never pay prompt-shaped work; prefills never stall other
  slots' decode beyond one bucket-sized call.

Shape discipline is the whole game on a compiled runtime: the engine
owns one jitted executable per (kind, bucket) key — ``decode[cap]``,
``prefill[Lb]``, ``insert[Lb, cap]``, ``grow[old→new]`` — where every
bucket comes from a closed :func:`io.bucketing.grow_buckets` family, so
:meth:`GenerateEngine.warmup` can mint *all* of them and steady-state
churn performs **zero** fresh traces (``serving.decode.compiles`` must
stay flat; scripts/decode_smoke.py gates it).

Integration, not a sidecar: requests enter through the PR 14 shed
ladder (:class:`~paddle_tpu.serving.admission.AdmissionController` —
priorities, deadlines, ``ShedError``), completions feed the ``slo.*``
goodput window so the :class:`ServingSupervisor` scales replicas off
decode traffic exactly as it does for fixed-shape traffic (plus the new
``slo.tokens_per_s`` floor), and :class:`MultiDecodeEngine` fans decode
out across breaker-guarded per-device replicas via the same
``MultiDeviceEngine`` machinery (failover, probes, restart).

**Sampling** (PR 17) rides *inside* the fused decode step: temperature
/ top-k / top-p / per-request seed enter as ``[slots]``-shaped arrays
(see serving/sampling.py), so greedy and sampled sequences share one
executable and a request's sampling config can never mint a trace.
Every random draw uses a counter-based key — a pure function of
``(request_seed, generation_index)`` — which makes a sequence's token
stream bit-reproducible across admission order, replica choice,
hedging, and failover re-prefill.

**Speculative decoding** (``draft_model=`` + ``spec_k=``): a cheap
draft model proposes ``k`` tokens autoregressively per tick (one
``lax.scan`` executable over its own :class:`KVCachePool` arena), then
the target verifies all ``k+1`` positions in one chunked step and the
accept-prefix rule (serving/sampling.py) keeps the emitted stream
*distributionally exact* against non-speculative sampling at the same
seeds. Both arenas write optimistically and roll their slot ledgers
back to the accepted prefix — pure host bookkeeping, no device copy.
On full accept the engine emits exactly ``k`` tokens and keeps the
last proposal as the next tick's input (no bonus token), which is what
holds the draft and target arenas in per-slot lockstep with zero
variable-shape catch-up work.

The model contract (duck-typed; :func:`demo_model` is the reference
implementation)::

    model.state        # pytree of device arrays (device_put per replica)
    model.vocab        # int
    model.kv_spec()    # {leaf: (tail_shape, dtype)} per cached token
    model.prefill_fn(state, tokens[B, L], lengths[B])
        -> (kv {leaf: [B, L, *tail]}, last_logits[B, V])
    model.decode_fn(state, tokens[S], kv {leaf: [S, cap, *tail]},
                    lengths[S])
        -> (logits[S, V], entry {leaf: [S, *tail]})
    model.verify_fn(state, tokens[S, C], kv, lengths[S])   # spec targets
        -> (logits[S, C, V], entry {leaf: [S, C, *tail]})

``decode_fn`` attends over ``kv[:, :lengths]`` plus the incoming
token's own K/V; the engine writes that entry at position ``lengths``
and advances the host-side length. ``verify_fn`` is the chunked
generalization (``decode_fn`` is its C == 1 special case): position
``i`` of the chunk attends over the resident history plus chunk
positions ``<= i``, and all C cache entries come back for the engine's
optimistic arena write. Only speculative *targets* need it. All slot
bookkeeping (lengths, last tokens, liveness) lives on the host and
ships as tiny arrays each tick — the only device-resident state is the
KV arena itself, so slot churn never mints an executable.
"""
from __future__ import annotations

import collections
import concurrent.futures
import itertools
import os
import threading
import time

import numpy as np

from .. import monitor as _monitor
from ..io.bucketing import next_bucket
from ..resilience import faults as _faults
from ..resilience.deadline import Deadline
from .admission import AdmissionController, resolve_priority
from .kv_cache import KVCachePool
from .multi import MultiDeviceEngine
from . import metrics
from . import reqtrace
from . import sampling as sampling_mod

_seed_counter = itertools.count(1)


def _fresh_seed():
    """Engine-assigned per-request seed (sampled requests that didn't
    pass one). Unique per process + submit order — and recorded on the
    request, so failover replay and hedge shadows reuse it verbatim."""
    return (os.getpid() * 2654435761 + next(_seed_counter)) & 0x7FFFFFFF


class DecodeRequest:
    """One sequence in flight: a prompt, a generation budget, a future
    resolving to the generated token ids (``np.int32``, EOS included
    when hit). Same resolution idempotence as ``batcher.Request`` so
    failover's first-resolution-wins contract holds."""

    __slots__ = ("prompt", "max_new_tokens", "eos_token", "n",
                 "future", "deadline", "t_enqueue", "priority", "trace",
                 "sampling", "preset")

    def __init__(self, prompt, max_new_tokens, eos_token=None,
                 deadline=None, priority=1, trace=None, sampling=None):
        self.prompt = prompt                    # 1-D int32 host array
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token = eos_token
        # resolved SamplingParams with a concrete seed — the request
        # carries it so failover/hedge replay is bit-identical
        self.sampling = (sampling if sampling is not None
                         else sampling_mod.SamplingParams(seed=0))
        self.n = 1                              # one sequence
        self.future = concurrent.futures.Future()
        self.deadline = deadline
        self.priority = int(priority)
        self.t_enqueue = time.monotonic()
        # reqtrace.Attempt (None = monitor disabled); the winner of the
        # set_* race below — and only the winner — finalizes it, so a
        # hedge shadow and its primary emit one record between them
        self.trace = trace
        # disaggregated-serving payload: a dict of {"segment" (the
        # KVCachePool transport format), "tokens" emitted so far,
        # "last_token", "prompt_len"}. When set, the engine seats the
        # sequence by importing the segment instead of running prefill
        # — the handoff landing AND the KV-carrying drain-migration
        # path. None on the ordinary single-engine path.
        self.preset = None

    def age(self, now=None):
        return (now if now is not None else time.monotonic()) \
            - self.t_enqueue

    def resolve_result(self, value):
        try:
            self.future.set_result(value)
        except concurrent.futures.InvalidStateError:
            return
        if self.trace is not None:
            self.trace.finalize("ok")

    def resolve_exception(self, exc):
        try:
            self.future.set_exception(exc)
        except concurrent.futures.InvalidStateError:
            return
        if self.trace is not None:
            from .admission import DeadlineExpired, ShedError
            outcome = ("expired" if isinstance(exc, DeadlineExpired)
                       else "shed" if isinstance(exc, ShedError)
                       else "error")
            self.trace.finalize(outcome, error=repr(exc))


class _Slot:
    """Host-side state of one decode-batch lane."""

    __slots__ = ("req", "length", "tokens", "last_token", "t_seat")

    def __init__(self):
        self.req = None          # DecodeRequest occupying the lane
        self.length = 0          # tokens resident in the KV arena
        self.tokens = None       # generated so far (list of int)
        self.last_token = 0      # next decode input
        self.t_seat = 0.0        # perf_counter stamp at seating (the
        #                          slot lane's occupancy-interval start)


class GenerateEngine:
    """Continuous-batching decode over one model replica.

    Parameters
    ----------
    model : the decode-model contract above (see :func:`demo_model`).
    slots : decode batch width — sequences served concurrently.
    page / factor / max_len : the KV arena's capacity schedule
        (``grow_buckets(page, factor, max_len)``); ``max_len`` caps
        prompt + generated tokens per sequence.
    prompt_buckets : prefill length buckets (default: the capacity
        family). One prefill executable per bucket; a prompt longer
        than the largest bucket is rejected at submit.
    queue_depth / deadline_ms / shed / slo_goodput_floor : the PR 14
        admission-ladder knobs, identical semantics to
        ``ServingEngine``.
    refill : ``"continuous"`` (default — freed slots refill at the next
        tick) or ``"drain"`` (run-to-completion waves: no admission
        until *every* slot is free — the static-batching baseline the
        loadgen A/Bs against; same executables, different discipline).
    sampling : engine-default :class:`~paddle_tpu.serving.sampling.
        SamplingParams` (or dict) for submits that don't pass their
        own; None = greedy (the PR 15 behavior, bit for bit).
    draft_model : enable speculative decoding — a cheaper model of the
        SAME vocab whose proposals the target verifies. Rides its own
        :class:`KVCachePool` arena on the same page schedule. The
        target model must implement ``verify_fn``.
    spec_k : draft proposals per speculative tick (>= 1); the realized
        multiplier is ``serving.decode.spec_tokens_per_step``.
    start : launch the tick thread now (False = tests drive
        :meth:`tick` manually).
    """

    def __init__(self, model, slots=8, page=64, factor=2.0, max_len=512,
                 prompt_buckets=None, queue_depth=256, deadline_ms=None,
                 refill="continuous", shed=True, slo_goodput_floor=0.90,
                 start=True, replica_id=None, on_outcome=None,
                 sampling=None, draft_model=None, spec_k=4,
                 kv_import=False):
        import jax
        self._jax = jax
        self.model = model
        self.replica_id = replica_id
        # kv_import: this engine receives KV segments (disaggregated
        # handoff landings / KV-carrying drain migration), so warmup
        # must mint insert executables for every CAPACITY-family pad
        # too, not just the prompt buckets — a mid-stream migration's
        # segment is padded to a capacity bucket
        self.kv_import = bool(kv_import)
        # served weights version: bumped by the fleet's rolling
        # hot-swap and stamped into every request's reqtrace record
        self.weights_version = 0
        self.on_outcome = on_outcome
        if refill not in ("continuous", "drain"):
            raise ValueError(
                f"refill must be 'continuous' or 'drain', got {refill!r}")
        self.refill = refill
        self.default_sampling = sampling_mod.resolve(sampling)
        self.pool = KVCachePool(model.kv_spec(), slots, page=page,
                                factor=factor, max_len=max_len)
        self.slots = self.pool.slots
        self.max_len = self.pool.max_len
        self.spec_k = int(spec_k)
        self.draft_model = draft_model
        self.draft_pool = None
        self._draft_state = None
        if draft_model is not None:
            if self.spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
            if int(draft_model.vocab) != int(model.vocab):
                raise ValueError(
                    f"draft vocab {draft_model.vocab} != target vocab "
                    f"{model.vocab} — the accept rule compares "
                    f"distributions over one vocabulary")
            if not hasattr(model, "verify_fn"):
                raise ValueError(
                    "speculative decoding needs model.verify_fn "
                    "(chunked decode) on the TARGET model")
            # the draft arena shares the slot count and page schedule,
            # so _ensure_capacity can grow both pools in lockstep and
            # plan_slots([target_spec, draft_spec], ...) prices the pair
            self.draft_pool = KVCachePool(
                draft_model.kv_spec(), slots, page=page, factor=factor,
                max_len=max_len, label="draft")
            dstate = draft_model.state
            dev = getattr(model, "device", None)
            if dev is not None:
                # fleet replicas share one draft object; pin a state
                # copy next to this replica's target weights
                dstate = jax.device_put(dstate, dev)
            self._draft_state = dstate
        if prompt_buckets is None:
            self.prompt_buckets = tuple(self.pool.seq_buckets)
        else:
            pb = tuple(sorted({int(b) for b in prompt_buckets}))
            if not pb or pb[-1] > self.max_len:
                raise ValueError(
                    f"prompt_buckets {pb} must be non-empty and within "
                    f"max_len={self.max_len}")
            self.prompt_buckets = pb
        self.admission = AdmissionController(
            max_queue_depth=queue_depth, default_deadline_ms=deadline_ms,
            shed=shed, slo_goodput_floor=slo_goodput_floor)
        self.admission.on_event = self._admission_event
        self._queue = collections.deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._slots = [_Slot() for _ in range(self.slots)]
        # Chrome-export resource-lane prefix: one lane per KV slot
        # ("kv.slot3", or "kv1.slot3" inside a fleet)
        self._lane = ("kv" if replica_id is None
                      else f"kv{replica_id}")
        # (kind, *buckets) -> jitted executable; single-writer (the tick
        # thread / warmup), so no lock — reads are atomic dict gets
        self._exec = {}
        # incremented INSIDE jitted bodies at trace time: any retrace —
        # even one that reuses an existing key — moves this counter, so
        # the zero-recompile gate catches dtype/shape drift too
        self._trace_count = 0
        self._stats_lock = threading.Lock()
        self._stats = {"submitted": 0, "completed": 0, "failed": 0,
                       "rejected": 0, "expired": 0, "shed": 0,
                       "ticks": 0, "tokens": 0, "prefills": 0,
                       "prefill_tokens": 0, "compiles": 0, "grows": 0,
                       "draft_steps": 0, "verify_steps": 0,
                       "spec_proposed": 0, "spec_accepted": 0,
                       "kv_imports": 0}
        self._occupancy_sum = 0.0
        self._running = False
        self._closed = False
        self._draining = False
        self._thread = None
        self._tick_t0 = None
        self._last_progress = time.monotonic()
        self._last_ok_t = time.monotonic()
        import weakref
        from ..monitor import sampler as _sampler
        ref = weakref.ref(self)

        def _depth_series():
            eng = ref()
            if eng is None:
                return None
            return {"serving.queue_depth": eng.depth()}

        self._sampler_key = _sampler.register_provider(
            f"serving-generate-{id(self)}", _depth_series)
        if start:
            self.start()

    # -- client surface ----------------------------------------------------

    def make_request(self, prompt, max_new_tokens=32, eos_token=None,
                     deadline_ms=None, priority=None, trace=None,
                     sampling=None, seed=None):
        """Validate one submit into a :class:`DecodeRequest` (not yet
        enqueued — the fleet wrapper builds once, then routes). Pass a
        shed request's ``RequestTrace`` as ``trace=`` when re-submitting
        so the retry folds into the same ``serving.request`` record.

        ``sampling`` is None (engine default; greedy unless the engine
        was built with one), a dict of knobs, or
        :class:`~paddle_tpu.serving.sampling.SamplingParams`; ``seed``
        overrides its per-request seed. A sampled request with no seed
        gets a fresh one HERE, so the request object carries everything
        failover or a hedge shadow needs to replay the exact stream."""
        arr = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if arr.size < 1:
            raise ValueError("empty prompt")
        if arr.size > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt of {arr.size} tokens exceeds the largest prefill "
                f"bucket {self.prompt_buckets[-1]} — raise max_len / "
                f"prompt_buckets")
        m = int(max_new_tokens)
        if m < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {m}")
        if arr.size + m > self.max_len:
            raise ValueError(
                f"prompt {arr.size} + max_new_tokens {m} exceeds the KV "
                f"arena max_len={self.max_len}")
        deadline = (Deadline.after_ms(deadline_ms)
                    if deadline_ms is not None else None)
        prio = resolve_priority(priority)
        if sampling is None and seed is None:
            params = sampling_mod.resolve(self.default_sampling)
        else:
            params = sampling_mod.resolve(sampling, seed=seed)
        if params.seed is None:
            params.seed = 0 if params.greedy else _fresh_seed()
        return DecodeRequest(arr, m, eos_token=eos_token,
                             deadline=deadline, priority=prio,
                             sampling=params,
                             trace=reqtrace.attach(
                                 trace, kind="decode", priority=prio,
                                 replica=self.replica_id,
                                 version=self.weights_version))

    def submit_request(self, req, admit=True):
        """Admit + enqueue; returns the future. Raises ``ShedError`` /
        ``QueueFullError`` from the admission ladder. ``admit=False``
        skips the ladder — for a disaggregated handoff the request was
        admitted once at the prefill pool's front door and must not be
        double-charged (or shed after its prefill already ran)."""
        with self._cond:
            if self._closed:
                raise RuntimeError("decode engine is closed")
            if admit:
                self.admission.admit(req, len(self._queue))
            self._queue.append(req)
            depth = len(self._queue)
            self._cond.notify()
        metrics.record_submit(1)
        metrics.record_queue_depth(depth)
        if req.trace is not None:
            req.trace.hop("enqueue", replica=self.replica_id)
            if _monitor.trace.enabled():
                with _monitor.trace.span("serving.enqueue", depth=depth):
                    reqtrace.flow_mark(req.trace)
        with self._stats_lock:
            self._stats["submitted"] += 1
        return req.future

    def submit(self, prompt, max_new_tokens=32, eos_token=None,
               deadline_ms=None, priority=None, trace=None,
               sampling=None, seed=None):
        """Enqueue one sequence; the future resolves to the generated
        token ids (``np.int32``; the first token comes from the prefill
        itself, EOS — when given and hit — is included and terminal)."""
        return self.submit_request(self.make_request(
            prompt, max_new_tokens=max_new_tokens, eos_token=eos_token,
            deadline_ms=deadline_ms, priority=priority, trace=trace,
            sampling=sampling, seed=seed))

    def run(self, prompt, max_new_tokens=32, eos_token=None,
            deadline_ms=None, timeout=None, priority=None,
            sampling=None, seed=None):
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           eos_token=eos_token,
                           deadline_ms=deadline_ms,
                           priority=priority, sampling=sampling,
                           seed=seed).result(timeout)

    def depth(self):
        with self._lock:
            return len(self._queue)

    # -- executables -------------------------------------------------------
    #
    # Every jitted body bumps _trace_count at TRACE time (the increment
    # is a host side effect, re-executed only when XLA retraces), so
    # executables() exposes both the key count and the honest trace
    # count — the smoke gate pins the latter after warmup.

    @staticmethod
    def _masked_write(jnp, buffers, entry, rows, pos, active, n_slots):
        """Scatter per-slot cache entries at ``pos`` into the arena,
        masked by ``active`` (inactive lanes keep their old rows). The
        mask rides on the scattered VALUES — gather the old rows, blend,
        one scatter — so the whole-arena update stays a single aliasable
        write (the executables donate their arena argument; a masked
        ``jnp.where`` over the full buffer would force two copies)."""
        out = {}
        for name, buf in buffers.items():
            old = buf[rows, pos]
            mask = active.reshape((n_slots,) + (1,) * (old.ndim - 1))
            out[name] = buf.at[rows, pos].set(
                jnp.where(mask, entry[name], old))
        return out

    def _get_decode(self, cap):
        key = ("decode", cap)
        fn = self._exec.get(key)
        if fn is not None:
            return fn
        jax = self._jax
        jnp = jax.numpy
        decode_fn = self.model.decode_fn
        n_slots = self.slots

        def step(state, buffers, tokens, lengths, active,
                 temps, top_ks, top_ps, seeds, positions):
            self._trace_count += 1
            logits, entry = decode_fn(state, tokens, buffers, lengths)
            filt = sampling_mod.filter_logits(logits, temps, top_ks,
                                              top_ps)
            nxt = sampling_mod.sample_from_filtered(filt, seeds,
                                                    positions)
            pos = jnp.minimum(lengths, cap - 1)
            rows = jnp.arange(n_slots)
            out = self._masked_write(jnp, buffers, entry, rows, pos,
                                     active, n_slots)
            return nxt, out

        # the caller always replaces pool.buffers with the result, so
        # the arena is donated — the scatter updates in place instead
        # of copying slots × capacity × spec bytes every token
        fn = jax.jit(step, donate_argnums=(1,))
        self._exec[key] = fn
        self._note_compile(f"decode[cap={cap}]")
        return fn

    def _get_prefill(self, bucket):
        key = ("prefill", bucket)
        fn = self._exec.get(key)
        if fn is not None:
            return fn
        jax = self._jax
        prefill_fn = self.model.prefill_fn

        def prefill(state, tokens, lengths, temps, top_ks, top_ps,
                    seeds, positions):
            self._trace_count += 1
            kv, last_logits = prefill_fn(state, tokens, lengths)
            filt = sampling_mod.filter_logits(last_logits, temps,
                                              top_ks, top_ps)
            first = sampling_mod.sample_from_filtered(filt, seeds,
                                                      positions)
            # last_logits ride out for the disaggregated prefix cache
            # (a later hit re-samples its own first token from them)
            return kv, first, last_logits

        fn = jax.jit(prefill)
        self._exec[key] = fn
        self._note_compile(f"prefill[L={bucket}]")
        return fn

    def _get_draft_prefill(self, bucket):
        """Draft-arena prompt ingest: the draft's KV only — the first
        token is the target prefill's to sample."""
        key = ("dprefill", bucket)
        fn = self._exec.get(key)
        if fn is not None:
            return fn
        jax = self._jax
        prefill_fn = self.draft_model.prefill_fn

        def prefill(dstate, tokens, lengths):
            self._trace_count += 1
            kv, _last = prefill_fn(dstate, tokens, lengths)
            return kv

        fn = jax.jit(prefill)
        self._exec[key] = fn
        self._note_compile(f"dprefill[L={bucket}]")
        return fn

    def _get_insert(self, bucket, cap, kind="insert"):
        key = (kind, bucket, cap)
        fn = self._exec.get(key)
        if fn is not None:
            return fn
        jax = self._jax

        def insert(buffers, chunk, slot):
            self._trace_count += 1
            out = {}
            for name, buf in buffers.items():
                start = (slot,) + (0,) * (buf.ndim - 1)
                out[name] = jax.lax.dynamic_update_slice(
                    buf, chunk[name], start)
            return out

        fn = jax.jit(insert, donate_argnums=(0,))
        self._exec[key] = fn
        self._note_compile(f"{kind}[L={bucket}, cap={cap}]")
        return fn

    def _get_grow(self, old_cap, new_cap, kind="grow"):
        key = (kind, old_cap, new_cap)
        fn = self._exec.get(key)
        if fn is not None:
            return fn
        jax = self._jax
        jnp = jax.numpy
        extra = new_cap - old_cap

        def grow(buffers):
            self._trace_count += 1
            out = {}
            for name, buf in buffers.items():
                pad = [(0, 0)] * buf.ndim
                pad[1] = (0, extra)
                out[name] = jnp.pad(buf, pad)
            return out

        fn = jax.jit(grow)
        self._exec[key] = fn
        self._note_compile(f"{kind}[{old_cap}->{new_cap}]")
        return fn

    def _get_spec_draft(self, cap):
        """The draft proposal loop: k autoregressive draft steps as one
        executable (``lax.scan``, so k never multiplies dispatches).
        Proposal ``i`` is drawn from the filtered draft distribution
        with the SAME ``(seed, pos0+i, SALT_TOKEN)`` key the
        non-speculative path would use at that generation index — that
        identity is what makes a self-draft reproduce the
        non-speculative stream. Returns ``(proposals[S, k],
        q_probs[S, k, V], updated draft buffers)``."""
        key = ("sdraft", cap)
        fn = self._exec.get(key)
        if fn is not None:
            return fn
        jax = self._jax
        jnp = jax.numpy
        draft_fn = self.draft_model.decode_fn
        n_slots = self.slots
        k = self.spec_k

        def propose(dstate, dbufs, tokens, lengths, active,
                    temps, top_ks, top_ps, seeds, pos0):
            self._trace_count += 1
            rows = jnp.arange(n_slots)

            def body(carry, i):
                bufs, tok, ln = carry
                logits, entry = draft_fn(dstate, tok, bufs, ln)
                filt = sampling_mod.filter_logits(logits, temps,
                                                  top_ks, top_ps)
                d = sampling_mod.sample_from_filtered(filt, seeds,
                                                      pos0 + i)
                q = sampling_mod.probs_from_filtered(filt)
                pos = jnp.minimum(ln, cap - 1)
                bufs = self._masked_write(jnp, bufs, entry, rows, pos,
                                          active, n_slots)
                return (bufs, d, ln + 1), (d, q)

            (bufs, _tok, _ln), (ds, qs) = jax.lax.scan(
                body, (dbufs, tokens, lengths), jnp.arange(k))
            return (jnp.transpose(ds, (1, 0)),
                    jnp.transpose(qs, (1, 0, 2)), bufs)

        fn = jax.jit(propose, donate_argnums=(1,))
        self._exec[key] = fn
        self._note_compile(f"sdraft[cap={cap}, k={k}]")
        return fn

    def _get_verify(self, cap):
        """The target's batched verify: one chunked forward over
        ``[last, d_1 .. d_k]`` evaluates all k+1 positions, writes the
        k+1 cache entries optimistically (the host ledger rolls back to
        the accepted prefix), and runs the accept-prefix rule in-graph.
        Returns ``(n_accepted[S], resampled[S], updated buffers)``."""
        key = ("verify", cap)
        fn = self._exec.get(key)
        if fn is not None:
            return fn
        jax = self._jax
        jnp = jax.numpy
        verify_fn = self.model.verify_fn
        n_slots = self.slots
        k = self.spec_k

        def verify(state, buffers, chunk, lengths, active,
                   temps, top_ks, top_ps, seeds, pos0, proposals,
                   q_probs):
            self._trace_count += 1
            logits, entry = verify_fn(state, chunk, buffers, lengths)
            rows = jnp.arange(n_slots)
            pos = jnp.minimum(
                lengths[:, None] + jnp.arange(k + 1)[None, :], cap - 1)
            out = self._masked_write(jnp, buffers, entry, rows[:, None],
                                     pos, active, n_slots)
            # filter all k+1 target distributions with this slot's knobs
            flat = logits.reshape(n_slots * (k + 1), -1)
            rep = lambda a: jnp.repeat(a, k + 1)    # noqa: E731
            p_flat = sampling_mod.probs_from_filtered(
                sampling_mod.filter_logits(flat, rep(temps),
                                           rep(top_ks), rep(top_ps)))
            p_probs = p_flat.reshape(n_slots, k + 1, -1)
            a, resampled = sampling_mod.accept_prefix(
                p_probs, q_probs, proposals, seeds, pos0)
            return a, resampled, out

        fn = jax.jit(verify, donate_argnums=(1,))
        self._exec[key] = fn
        self._note_compile(f"verify[cap={cap}, k={k}]")
        return fn

    def _note_compile(self, what):
        metrics.record_decode_compile(1, what=what)
        with self._stats_lock:
            self._stats["compiles"] += 1

    def executables(self):
        """(executable count, trace count) — both must stay flat after
        :meth:`warmup` across any amount of join/leave churn."""
        return len(self._exec), self._trace_count

    def _sampling_args(self, n):
        """Zero-valued (= greedy) sampling arrays of batch width ``n``
        for warmup and probe calls — shapes and dtypes must match the
        live tick's exactly or the zero-retrace gate trips."""
        import jax.numpy as jnp
        return (jnp.zeros((n,), jnp.float32),    # temps
                jnp.zeros((n,), jnp.int32),      # top_ks
                jnp.ones((n,), jnp.float32),     # top_ps
                jnp.zeros((n,), jnp.uint32),     # seeds
                jnp.zeros((n,), jnp.int32))      # positions

    def warmup(self, *_signatures):
        """Mint and trace every executable the engine can ever need:
        one decode step per capacity bucket, one grow per consecutive
        bucket pair, one prefill per prompt bucket, and one insert per
        (prompt bucket, capacity) pair that can co-occur — plus, when a
        draft model is mounted, the speculative family (draft prefill /
        insert / grow per the same buckets, and one draft-scan + verify
        pair per capacity). After this, steady-state churn — including
        cache growth and any accept/reject pattern — runs entirely on
        cached executables. Returns the number compiled. (Positional
        signatures from the fleet wrapper are accepted and ignored —
        a decode engine's shapes come from its bucket families.)"""
        import jax.numpy as jnp
        before = len(self._exec)
        family = self.pool.seq_buckets
        spec = self.pool._leaf_list
        state = self.model.state
        tokens_s = jnp.zeros((self.slots,), jnp.int32)
        ones_s = jnp.ones((self.slots,), jnp.int32)
        active = jnp.zeros((self.slots,), bool)
        samp_s = self._sampling_args(self.slots)
        samp_1 = self._sampling_args(1)
        speculative = self.draft_model is not None
        dspec = self.draft_pool._leaf_list if speculative else None

        def zeros_arena(leaf_list, cap):
            # fresh per donating call — the executables consume (donate)
            # their arena argument, so a shared warmup buffer would be
            # a use-after-donate
            return {name: jnp.zeros((self.slots, cap) + tail, dt)
                    for name, tail, dt in leaf_list}

        with _monitor.trace.span("serving.warmup",
                                 buckets=len(family)):
            insert_pads = set(self.prompt_buckets)
            if self.kv_import:
                insert_pads |= set(family)
            for cap in family:
                nxt, out = self._get_decode(cap)(
                    state, zeros_arena(spec, cap), tokens_s, ones_s,
                    active, *samp_s)
                self._jax.block_until_ready(nxt)
                for lb in sorted(insert_pads):
                    if lb > cap:
                        continue
                    chunk = {name: jnp.zeros((1, lb) + tail, dt)
                             for name, tail, dt in spec}
                    self._jax.block_until_ready(self._get_insert(lb, cap)(
                        zeros_arena(spec, cap), chunk, jnp.int32(0)))
                if speculative:
                    ds, qs, _ = self._get_spec_draft(cap)(
                        self._draft_state, zeros_arena(dspec, cap),
                        tokens_s, ones_s, active, *samp_s)
                    self._jax.block_until_ready(ds)
                    k = self.spec_k
                    a, t, _ = self._get_verify(cap)(
                        state, zeros_arena(spec, cap),
                        jnp.zeros((self.slots, k + 1), jnp.int32),
                        ones_s, active, *samp_s,
                        jnp.zeros((self.slots, k), jnp.int32),
                        jnp.zeros((self.slots, k, self.model.vocab),
                                  jnp.float32))
                    self._jax.block_until_ready(a)
                    for lb in self.prompt_buckets:
                        if lb > cap:
                            continue
                        dchunk = {name: jnp.zeros((1, lb) + tail, dt)
                                  for name, tail, dt in dspec}
                        self._jax.block_until_ready(
                            self._get_insert(lb, cap, kind="dinsert")(
                                zeros_arena(dspec, cap), dchunk,
                                jnp.int32(0)))
            for old, new in zip(family, family[1:]):
                bufs = {name: jnp.zeros((self.slots, old) + tail, dt)
                        for name, tail, dt in spec}
                self._jax.block_until_ready(self._get_grow(old, new)(bufs))
                if speculative:
                    dbufs = {name: jnp.zeros((self.slots, old) + tail,
                                             dt)
                             for name, tail, dt in dspec}
                    self._jax.block_until_ready(
                        self._get_grow(old, new, kind="dgrow")(dbufs))
            for lb in self.prompt_buckets:
                kv, first, _logits = self._get_prefill(lb)(
                    state, jnp.zeros((1, lb), jnp.int32),
                    jnp.ones((1,), jnp.int32), *samp_1)
                self._jax.block_until_ready(first)
                if speculative:
                    dkv = self._get_draft_prefill(lb)(
                        self._draft_state, jnp.zeros((1, lb), jnp.int32),
                        jnp.ones((1,), jnp.int32))
                    self._jax.block_until_ready(
                        next(iter(dkv.values())))
        return len(self._exec) - before

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        with self._lock:
            if self._running or self._closed:
                return
            self._running = True
            self._draining = False
            self._thread = threading.Thread(
                target=self._worker, name="paddle_tpu-serving-decode",
                daemon=True)
            self._thread.start()

    def close(self, drain=True, timeout=None):
        """Stop the tick thread. ``drain=True`` keeps ticking until the
        queue and every slot are empty (bounded join); anything left
        after the join fails with RuntimeError — a future is never
        silently lost."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._running = False
            self._draining = bool(drain)
            self._cond.notify_all()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            if timeout is None:
                timeout = 10.0 if drain else 5.0
            t.join(timeout)
        leftovers = []
        with self._cond:
            leftovers.extend(self._queue)
            self._queue.clear()
            for s, slot in enumerate(self._slots):
                if slot.req is not None:
                    leftovers.append(slot.req)
                    slot.req = None
                    self.pool.free(s)
                    if self.draft_pool is not None:
                        self.draft_pool.note_length(s, 0)
        for r in leftovers:
            r.resolve_exception(RuntimeError("decode engine closed"))
        from ..monitor import sampler as _sampler
        _sampler.unregister_provider(self._sampler_key)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()

    # -- supervision surface (the MultiDeviceEngine contract) --------------

    def heartbeat(self, now=None):
        now = time.monotonic() if now is None else now
        with self._lock:
            t0 = self._tick_t0
            depth = len(self._queue)
            seated = sum(1 for s in self._slots if s.req is not None)
        return {
            "queue_depth": depth,
            "inflight_age_s": None if t0 is None else now - t0,
            "inflight_token": t0,
            "last_progress_age_s": now - self._last_progress,
            "last_ok_age_s": now - self._last_ok_t,
            # seated (still-generating) sequences — what a drain waits
            # to hit zero
            "active": seated,
        }

    def probe(self, timeout_s=1.0):
        """Half-open test traffic: run the decode executable (or, on a
        speculative engine, the verify executable) on an all-inactive
        batch on a side thread (the tick thread may be the thing that's
        wedged) and report whether it finished in time."""
        import jax.numpy as jnp
        cap = self.pool.capacity
        kind = ("decode" if ("decode", cap) in self._exec
                else "verify" if ("verify", cap) in self._exec
                else None)
        if kind is None:
            return None          # never warmed / served — nothing to test
        done = threading.Event()
        err = []

        def _go():
            try:
                fn = self._exec[(kind, cap)]
                zeros = jnp.zeros((self.slots,), jnp.int32)
                inactive = jnp.zeros((self.slots,), bool)
                samp = self._sampling_args(self.slots)
                # a throwaway arena, NOT pool.buffers — the executable
                # donates (consumes) its arena argument, and the live
                # pool must survive the probe
                bufs = {name: jnp.zeros(
                            (self.slots, self.pool.capacity) + tail, dt)
                        for name, tail, dt in self.pool._leaf_list}
                if kind == "decode":
                    nxt, _ = fn(self.model.state, bufs,
                                zeros, zeros, inactive, *samp)
                else:
                    k = self.spec_k
                    nxt, _t, _b = fn(
                        self.model.state, bufs,
                        jnp.zeros((self.slots, k + 1), jnp.int32),
                        zeros, inactive, *samp,
                        jnp.zeros((self.slots, k), jnp.int32),
                        jnp.zeros((self.slots, k, self.model.vocab),
                                  jnp.float32))
                self._jax.block_until_ready(nxt)
            except BaseException as e:   # noqa: BLE001 - probe verdict
                err.append(e)
            finally:
                done.set()

        threading.Thread(target=_go, daemon=True,
                         name="paddle_tpu-decode-probe").start()
        ok = done.wait(timeout_s) and not err
        if ok:
            self._last_ok_t = time.monotonic()
        return bool(ok)

    def steal_pending(self):
        """Failover: hand every queued request to the caller."""
        with self._cond:
            taken = list(self._queue)
            self._queue.clear()
        metrics.record_queue_depth(0)
        return taken

    def disown_inflight(self, export_kv=False):
        """Failover: evict every live sequence and hand its request
        over. Partial output is discarded — decode is a pure function
        of the request (greedy argmax, or counter-based sampling keys
        derived from the request's own ``(seed, generation_index)``),
        so the adopting replica's re-prefill regenerates a
        bit-identical stream from the prompt, speculative or not
        (first resolution wins either way).

        ``export_kv=True`` (the disaggregated decode pool's drain path)
        instead carries each sequence's resident KV off the arena via
        :meth:`KVCachePool.export_slot` — padded to its capacity-family
        bucket so the adopter lands it on a warmed insert executable —
        along with the tokens emitted so far, so the adopting replica
        resumes mid-stream (same ledger length, same generation index:
        bit-identical continuation) instead of re-running prefill."""
        taken = []
        evicted = []
        with self._lock:
            for s, slot in enumerate(self._slots):
                if slot.req is not None:
                    if export_kv and slot.length > 0:
                        seg = self.pool.export_slot(
                            s, pad_to=self.pool.capacity_for(
                                slot.length))
                        slot.req.preset = {
                            "segment": seg,
                            "tokens": list(slot.tokens),
                            "last_token": slot.last_token,
                            "prompt_len": int(slot.req.prompt.size),
                        }
                    taken.append(slot.req)
                    evicted.append((s, slot.t_seat))
                    slot.req = None
                    slot.tokens = None
                    self.pool.free(s)
                    if self.draft_pool is not None:
                        self.draft_pool.note_length(s, 0)
        trc = _monitor.trace
        if trc.enabled() and evicted:
            now_pc = time.perf_counter()
            for s, t_seat in evicted:
                trc.lane_complete(f"{self._lane}.slot{s}", "req evicted",
                                  t_seat, now_pc)
        return taken

    def requeue(self, requests):
        """Failover re-dispatch: front-of-queue, no re-admission."""
        if not requests:
            return
        for r in requests:
            tr = getattr(r, "trace", None)
            if tr is not None:
                # the attempt re-enters queue wait on this replica; the
                # failover hop itself is recorded by the fleet owner
                tr.to("queue")
                tr.hop("requeue", replica=self.replica_id)
        with self._cond:
            if self._closed:
                for r in requests:
                    r.resolve_exception(
                        RuntimeError("decode engine closed"))
                return
            for r in reversed(requests):
                self._queue.appendleft(r)
            depth = len(self._queue)
            self._cond.notify()
        metrics.record_queue_depth(depth)

    def _note_outcome(self, ok, exc=None):
        if ok:
            self._last_ok_t = time.monotonic()
        cb = self.on_outcome
        if cb is not None:
            try:
                cb(ok, exc)
            except Exception:   # noqa: BLE001 - observer must not kill
                pass            # the tick thread

    def _admission_event(self, event):
        key = {"rejected": "rejected", "expired": "expired",
               "poisoned": "failed", "shed": "shed"}.get(event)
        if key is not None:
            with self._stats_lock:
                self._stats[key] += 1

    def stats(self):
        with self._stats_lock:
            s = dict(self._stats)
            occ_sum = self._occupancy_sum
        s["queue_depth"] = self.depth()
        s["active_slots"] = self.pool.used_slots()
        s["slots"] = self.slots
        s["avg_occupancy"] = (occ_sum / s["ticks"]) if s["ticks"] else 0.0
        s["executables"] = len(self._exec)
        s["traces"] = self._trace_count
        s.update({f"pool_{k}": v for k, v in self.pool.stats().items()
                  if isinstance(v, (int, float))})
        return s

    # -- the tick loop -----------------------------------------------------

    def _worker(self):
        while True:
            did_work = self.tick()
            if did_work:
                continue
            with self._cond:
                if not self._running:
                    if self._draining and (
                            self._queue or self.pool.used_slots()):
                        continue    # drain: keep ticking until empty
                    return
                if not self._queue and self.pool.used_slots() == 0:
                    self._cond.wait(0.05)

    def tick(self):
        """One engine step: admit into free slots (per the refill
        discipline), then advance every live sequence one token.
        Returns whether any work happened. Tests call this directly
        (``start=False``); the daemon loop drives it otherwise."""
        t0 = time.monotonic()
        with self._lock:
            self._tick_t0 = t0
        try:
            admitted = self._admit()
            stepped = (self._spec_once() if self.draft_model is not None
                       else self._decode_once())
        finally:
            with self._lock:
                self._tick_t0 = None
                self._last_progress = time.monotonic()
        return bool(admitted or stepped)

    # -- admission into slots ----------------------------------------------

    def _pop_next_locked(self, now):
        """Highest-priority (then FIFO) non-expired request, sweeping
        expired ones out as they surface. Caller holds the lock;
        expired requests are returned for resolution outside it."""
        expired = []
        while self._queue:
            best_i, best_p = 0, self._queue[0].priority
            for i, r in enumerate(self._queue):
                if r.priority < best_p:
                    best_i, best_p = i, r.priority
            r = self._queue[best_i]
            del self._queue[best_i]
            if self.admission.is_expired(r, now):
                expired.append(r)
                continue
            return r, expired
        return None, expired

    def _admit(self):
        if self.refill == "drain" and self.pool.used_slots() != 0:
            return 0            # run-to-completion baseline: wait out
        admitted = 0            # the whole wave
        while self.pool.free_slots() > 0:
            now = time.monotonic()
            with self._cond:
                req, expired = self._pop_next_locked(now)
                depth = len(self._queue)
            for r in expired:
                self.admission.expire(r)
            metrics.record_queue_depth(depth)
            if req is None:
                break
            try:
                self._prefill_into_slot(req)
                admitted += 1
            except BaseException as e:   # noqa: BLE001 - to the future
                self._note_outcome(False, e)
                with self._stats_lock:
                    self._stats["failed"] += 1
                req.resolve_exception(e)
        return admitted

    def _ensure_capacity(self, needed_len):
        target = self.pool.capacity_for(needed_len)
        while self.pool.capacity < target:
            old = self.pool.capacity
            new = next_bucket(old + 1, self.pool.seq_buckets)
            fn = self._get_grow(old, new)
            self.pool.grow_to(new, lambda bufs, _o, _n: fn(bufs))
            if self.draft_pool is not None:
                # the two arenas share one page schedule; growing them
                # in lockstep keeps every spec executable single-cap
                dfn = self._get_grow(old, new, kind="dgrow")
                self.draft_pool.grow_to(new,
                                        lambda bufs, _o, _n: dfn(bufs))
            with self._stats_lock:
                self._stats["grows"] += 1
            # growth pad marker on the arena's shared lane — lines up
            # with the per-slot occupancy intervals in the Chrome export
            _monitor.trace.lane_instant(f"{self._lane}.pool",
                                        f"grow {old}->{new}",
                                        old_cap=old, new_cap=new)

    def _prefill_into_slot(self, req):
        """Prompt ingest: run the bucketed prefill executable, write the
        KV pages into a freed slot's arena rows, seat the sequence. The
        first generated token falls out of the prefill itself. A
        request carrying a ``preset`` payload (disaggregated handoff /
        KV-carrying migration) seats by segment import instead — no
        prefill executable runs."""
        import jax.numpy as jnp
        if getattr(req, "preset", None) is not None:
            return self._seat_preset(req)
        p = int(req.prompt.size)
        bucket = next_bucket(p, self.prompt_buckets)
        tr = req.trace
        if tr is not None:
            tr.to("prefill")
        # the arena must hold the prompt pages, the first decode write
        # (position p), and the full insert bucket
        self._ensure_capacity(max(p + 1, bucket))
        s = self.pool.alloc()
        if s is None:
            raise RuntimeError("no free slot after free_slots() > 0")
        pc_seat = time.perf_counter()
        try:
            if _faults.enabled():
                _faults.maybe_serving_fault(self.replica_id)
            t0 = time.monotonic()
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :p] = req.prompt
            sp = req.sampling
            # generation index 0: the prefill's sampled token — the
            # same counter key a failover re-prefill will derive
            kv, first, _logits = self._get_prefill(bucket)(
                self.model.state, jnp.asarray(tokens),
                jnp.asarray([p], jnp.int32),
                jnp.asarray([sp.temperature], jnp.float32),
                jnp.asarray([sp.top_k], jnp.int32),
                jnp.asarray([sp.top_p], jnp.float32),
                jnp.asarray([sp.seed or 0], jnp.uint32),
                jnp.zeros((1,), jnp.int32))
            first = int(first[0])
            self.pool.buffers = self._get_insert(bucket,
                                                 self.pool.capacity)(
                self.pool.buffers, kv, jnp.int32(s))
            self.pool.note_length(s, p)
            if self.draft_pool is not None:
                dkv = self._get_draft_prefill(bucket)(
                    self._draft_state, jnp.asarray(tokens),
                    jnp.asarray([p], jnp.int32))
                self.draft_pool.buffers = self._get_insert(
                    bucket, self.draft_pool.capacity, kind="dinsert")(
                    self.draft_pool.buffers, dkv, jnp.int32(s))
                self.draft_pool.note_length(s, p)
            ms = (time.monotonic() - t0) * 1e3
            metrics.record_prefill(p, ms, bucket)
            with self._stats_lock:
                self._stats["prefills"] += 1
                self._stats["prefill_tokens"] += p
        except BaseException:
            self.pool.free(s)
            raise
        self._note_outcome(True)
        # the TTFT moment: the prefill's last-token logits ARE the first
        # generated token (a failover re-prefill re-stamps it — honest)
        if tr is not None:
            tr.first_token()
        trc = _monitor.trace
        if trc.enabled():
            rid = tr.ctx.rid if tr is not None else None
            trc.lane_complete(f"{self._lane}.slot{s}", "prefill",
                              pc_seat, pc_seat + ms / 1e3,
                              rid=rid, tokens=p, bucket=bucket)
        done = (req.eos_token is not None and first == req.eos_token) \
            or req.max_new_tokens == 1
        if done:
            self.pool.free(s)
            if trc.enabled():
                trc.lane_complete(
                    f"{self._lane}.slot{s}",
                    f"req {rid}" if rid else "req", pc_seat,
                    rid=rid, tokens=1)
            self._complete(req, [first])
            return
        slot = self._slots[s]
        with self._lock:
            slot.req = req
            slot.length = p
            slot.tokens = [first]
            slot.last_token = first
            slot.t_seat = pc_seat

    def _seat_preset(self, req):
        """Seat a sequence whose KV history already exists as a host
        segment (``req.preset``): a disaggregated prefill→decode
        handoff (segment = the prompt's KV, tokens = [first]) or a
        KV-carrying drain migration (segment = prompt + generated KV,
        tokens = everything emitted so far). The segment lands through
        :meth:`KVCachePool.import_slot` on the pre-compiled insert
        executable for its pad bucket — zero fresh compiles — and the
        ``note_length`` ledger restores the generation index, so the
        continued stream is bit-identical to one that never moved."""
        import jax.numpy as jnp
        preset = req.preset
        seg = preset["segment"]
        pad = int(seg["pad"])
        L = int(seg["length"])
        toks = list(preset["tokens"])
        last = int(preset["last_token"])
        tr = req.trace
        self._ensure_capacity(max(L + 1, pad))
        s = self.pool.alloc()
        if s is None:
            raise RuntimeError("no free slot after free_slots() > 0")
        pc_seat = time.perf_counter()
        try:
            if _faults.enabled():
                _faults.maybe_serving_fault(self.replica_id)
            fn = self._get_insert(pad, self.pool.capacity)
            self.pool.import_slot(s, seg, insert_fn=fn)
            with self._stats_lock:
                self._stats["kv_imports"] = \
                    self._stats.get("kv_imports", 0) + 1
        except BaseException:
            self.pool.free(s)
            raise
        self._note_outcome(True)
        # the first token was stamped where it was produced (the
        # prefill pool / the original replica); entering "decode" here
        # closes the handoff (or requeue-wait) stage
        if tr is not None:
            tr.to("decode")
        trc = _monitor.trace
        rid = tr.ctx.rid if tr is not None else None
        if trc.enabled():
            trc.lane_complete(f"{self._lane}.slot{s}", "kv import",
                              pc_seat, time.perf_counter(),
                              rid=rid, tokens=L, pad=pad)
        done = (req.eos_token is not None and last == req.eos_token) \
            or len(toks) >= req.max_new_tokens
        if done:
            self.pool.free(s)
            self._complete(req, toks)
            return
        slot = self._slots[s]
        with self._lock:
            slot.req = req
            slot.length = L
            slot.tokens = toks
            slot.last_token = last
            slot.t_seat = pc_seat

    # -- the fused decode step ---------------------------------------------

    def _gather_batch(self, extra=1):
        """Snapshot the live lanes into the tick's host arrays: tokens /
        lengths / active plus the per-slot sampling knobs (the batch-
        shaped arrays that keep every request config on one executable)
        and each lane's generation index (= the counter the PRNG keys
        derive from). ``extra`` is the per-tick arena headroom (1 for
        plain decode, k+1 for a speculative verify). Caller must NOT
        hold the lock."""
        with self._lock:
            assigned = [(s, slot.req) for s, slot in enumerate(self._slots)
                        if slot.req is not None]
            if not assigned:
                return None
            tokens = np.zeros((self.slots,), np.int32)
            lengths = np.zeros((self.slots,), np.int32)
            active = np.zeros((self.slots,), bool)
            temps = np.zeros((self.slots,), np.float32)
            top_ks = np.zeros((self.slots,), np.int32)
            top_ps = np.ones((self.slots,), np.float32)
            seeds = np.zeros((self.slots,), np.uint32)
            positions = np.zeros((self.slots,), np.int32)
            max_needed = 0
            for s, req in assigned:
                slot = self._slots[s]
                sp = req.sampling
                tokens[s] = slot.last_token
                lengths[s] = slot.length
                active[s] = True
                temps[s] = sp.temperature
                top_ks[s] = sp.top_k
                top_ps[s] = sp.top_p
                seeds[s] = sp.seed or 0
                positions[s] = len(slot.tokens)
                max_needed = max(max_needed, slot.length + extra)
        return (assigned, tokens, lengths, active,
                (temps, top_ks, top_ps, seeds, positions), max_needed)

    def _decode_once(self):
        import jax.numpy as jnp
        batch = self._gather_batch(extra=1)
        if batch is None:
            return False
        assigned, tokens, lengths, active, samp, max_needed = batch
        self._ensure_capacity(max_needed)
        try:
            if _faults.enabled():
                _faults.maybe_serving_fault(self.replica_id)
            t0 = time.monotonic()
            fn = self._get_decode(self.pool.capacity)
            nxt, new_bufs = fn(self.model.state, self.pool.buffers,
                               jnp.asarray(tokens), jnp.asarray(lengths),
                               jnp.asarray(active),
                               *(jnp.asarray(a) for a in samp))
            nxt = np.asarray(nxt)
            step_ms = (time.monotonic() - t0) * 1e3
        except BaseException as e:   # noqa: BLE001 - fail the wave
            self._note_outcome(False, e)
            self._fail_active(assigned, e)
            return True
        self._note_outcome(True)
        self.pool.buffers = new_bufs
        finished = []
        with self._lock:
            n_active = 0
            for s, req in assigned:
                slot = self._slots[s]
                if slot.req is not req:
                    continue        # disowned / failed over mid-step
                n_active += 1
                tok = int(nxt[s])
                slot.length += 1
                slot.tokens.append(tok)
                slot.last_token = tok
                self.pool.note_length(s, slot.length)
                if (req.eos_token is not None and tok == req.eos_token) \
                        or len(slot.tokens) >= req.max_new_tokens:
                    finished.append((s, req, slot.tokens, slot.t_seat))
                    slot.req = None
                    slot.tokens = None
                    self.pool.free(s)
            occupancy = n_active / self.slots
        with self._stats_lock:
            self._stats["ticks"] += 1
            self._stats["tokens"] += n_active
            self._occupancy_sum += occupancy
        metrics.record_decode_tick(n_active, self.slots, n_active, step_ms)
        trc = _monitor.trace
        if trc.enabled() and finished:
            # slot occupancy intervals close when the slot frees — one
            # per finished sequence, on that slot's resource lane
            now_pc = time.perf_counter()
            for s, req, toks, t_seat in finished:
                rid = (req.trace.ctx.rid if req.trace is not None
                       else None)
                trc.lane_complete(f"{self._lane}.slot{s}",
                                  f"req {rid}" if rid else "req",
                                  t_seat, now_pc,
                                  rid=rid, tokens=len(toks))
        for _s, req, toks, _t in finished:
            self._complete(req, toks)
        return True

    def _spec_once(self):
        """One speculative tick: the draft proposes ``k`` tokens per
        live lane (one scan executable), the target verifies all k+1
        positions in one chunked call, and the host ledger settles each
        lane to its accepted prefix:

        * partial accept (``a < k``): emit ``d_1..d_a`` plus the
          residual resample — ``a + 1`` tokens;
        * full accept: emit exactly ``d_1..d_k`` and keep ``d_k`` as
          the next tick's input. **No bonus token** — emitting the
          target's k+1-th sample would leave the draft arena one
          entry behind the target's, and the catch-up write is a
          variable-shape call. Skipping it keeps both arenas in
          per-slot lockstep forever, for one token of upside.

        Both executables write optimistically; ``note_length`` then
        ``rollback`` trims each pool's ledger to the kept prefix
        (pure host bookkeeping — no device copies)."""
        import jax.numpy as jnp
        k = self.spec_k
        batch = self._gather_batch(extra=k + 1)
        if batch is None:
            return False
        assigned, tokens, lengths, active, samp, max_needed = batch
        # a lane within k of its admission-checked budget still verifies
        # a full k+1 chunk — the writes past max_len are dropped by the
        # scatter (OOB update semantics) and the ledger clamps below, so
        # the chunk shape (and the executable) never varies
        self._ensure_capacity(min(max_needed, self.pool.max_len))
        cap = self.pool.capacity
        try:
            if _faults.enabled():
                _faults.maybe_serving_fault(self.replica_id)
            t0 = time.monotonic()
            samp_dev = tuple(jnp.asarray(a) for a in samp)
            tok_dev = jnp.asarray(tokens)
            len_dev = jnp.asarray(lengths)
            act_dev = jnp.asarray(active)
            ds, qs, dbufs = self._get_spec_draft(cap)(
                self._draft_state, self.draft_pool.buffers,
                tok_dev, len_dev, act_dev, *samp_dev)
            # settle the draft arena BEFORE verify can raise: the scan
            # donated (consumed) the old buffers, so the pool must point
            # at the new ones even if this tick's wave fails
            self.draft_pool.buffers = dbufs
            chunk = jnp.concatenate([tok_dev[:, None], ds], axis=1)
            a, resampled, new_bufs = self._get_verify(cap)(
                self.model.state, self.pool.buffers, chunk, len_dev,
                act_dev, *samp_dev, ds, qs)
            a = np.asarray(a)
            resampled = np.asarray(resampled)
            ds_host = np.asarray(ds)
            step_ms = (time.monotonic() - t0) * 1e3
        except BaseException as e:   # noqa: BLE001 - fail the wave
            self._note_outcome(False, e)
            self._fail_active(assigned, e)
            return True
        self._note_outcome(True)
        self.pool.buffers = new_bufs
        finished = []
        emitted_total = 0
        accepted_total = 0
        with self._lock:
            n_active = 0
            for s, req in assigned:
                slot = self._slots[s]
                if slot.req is not req:
                    continue        # disowned / failed over mid-step
                n_active += 1
                L = int(lengths[s])
                ai = int(a[s])
                if ai >= k:
                    new_toks = [int(t) for t in ds_host[s]]
                else:
                    new_toks = [int(t) for t in ds_host[s, :ai]]
                    new_toks.append(int(resampled[s]))
                # EOS / budget truncate: everything past the stop token
                # is unemitted, so the live g-indexing never skews
                emitted = []
                done = False
                for t in new_toks:
                    emitted.append(t)
                    if (req.eos_token is not None
                            and t == req.eos_token) \
                            or len(slot.tokens) + len(emitted) \
                            >= req.max_new_tokens:
                        done = True
                        break
                e = len(emitted)
                # ledger settle: verify wrote target entries for chunk
                # tokens [last, d_1..d_k] at L..L+k; the draft scan
                # wrote [last, d_1..d_k-1] at L..L+k-1. Keep exactly
                # the new last_token's predecessors: L + e entries.
                # Clamp to capacity: a lane within k of max_len still
                # speculates a full chunk, and the tail writes past the
                # arena were dropped on-device (truncated here anyway).
                self.pool.note_length(s, min(L + k + 1, cap))
                self.pool.rollback(s, L + e)
                self.draft_pool.note_length(s, min(L + k, cap))
                if e < k:
                    self.draft_pool.rollback(s, L + e)
                slot.tokens.extend(emitted)
                slot.length = L + e
                slot.last_token = emitted[-1]
                if req.trace is not None:
                    req.trace.note_spec(k, ai)
                emitted_total += e
                accepted_total += ai
                if done:
                    finished.append((s, req, slot.tokens, slot.t_seat))
                    slot.req = None
                    slot.tokens = None
                    self.pool.free(s)
                    self.draft_pool.note_length(s, 0)
            occupancy = n_active / self.slots
        with self._stats_lock:
            self._stats["ticks"] += 1
            self._stats["tokens"] += emitted_total
            self._stats["draft_steps"] += k
            self._stats["verify_steps"] += 1
            self._stats["spec_proposed"] += k * n_active
            self._stats["spec_accepted"] += accepted_total
            self._occupancy_sum += occupancy
        metrics.record_decode_tick(n_active, self.slots, emitted_total,
                                   step_ms)
        metrics.record_spec_tick(k * n_active, accepted_total,
                                 emitted_total, k)
        trc = _monitor.trace
        if trc.enabled() and finished:
            now_pc = time.perf_counter()
            for s, req, toks, t_seat in finished:
                rid = (req.trace.ctx.rid if req.trace is not None
                       else None)
                trc.lane_complete(f"{self._lane}.slot{s}",
                                  f"req {rid}" if rid else "req",
                                  t_seat, now_pc,
                                  rid=rid, tokens=len(toks))
        for _s, req, toks, _t in finished:
            self._complete(req, toks)
        return True

    def _fail_active(self, assigned, exc):
        with self._lock:
            failed = []
            for s, req in assigned:
                slot = self._slots[s]
                if slot.req is not req:
                    continue
                failed.append((s, req, slot.t_seat))
                slot.req = None
                slot.tokens = None
                self.pool.free(s)
                if self.draft_pool is not None:
                    self.draft_pool.note_length(s, 0)
        with self._stats_lock:
            self._stats["failed"] += len(failed)
        trc = _monitor.trace
        if trc.enabled() and failed:
            now_pc = time.perf_counter()
            for s, _r, t_seat in failed:
                trc.lane_complete(f"{self._lane}.slot{s}", "req failed",
                                  t_seat, now_pc)
        for _s, r, _t in failed:
            r.resolve_exception(exc)

    def _complete(self, req, tokens):
        now = time.monotonic()
        latency_ms = req.age(now) * 1e3
        within = req.deadline is None or not req.deadline.expired(now)
        if req.trace is not None:
            # token count must land before resolve_result finalizes the
            # record — tpot derives from it
            req.trace.note_tokens(len(tokens))
        # account BEFORE resolving: the waiter wakes the instant
        # set_result lands, and a stats() read right after result()
        # must already see this completion
        metrics.record_completed(1, [latency_ms], within_sla=[within])
        with self._stats_lock:
            self._stats["completed"] += 1
        req.resolve_result(np.asarray(tokens, np.int32))


# ---------------------------------------------------------------------------
# fleet fan-out


def replicate_decode(model, devices=None):
    """One model view per device: the state pytree is ``device_put``
    onto each device; hyperparameters and the pure prefill/decode
    functions are shared (the decode analogue of ``multi.replicate``)."""
    import copy
    import jax
    devices = list(devices) if devices is not None else jax.local_devices()
    if not devices:
        raise ValueError("replicate_decode: no devices")
    out = []
    for d in devices:
        m = copy.copy(model)
        m.state = jax.device_put(model.state, d)
        m.device = d
        out.append(m)
    return out


class MultiDecodeEngine(MultiDeviceEngine):
    """Breaker-aware decode fan-out: one :class:`GenerateEngine` per
    device replica, behind the same supervision spine as fixed-shape
    serving — per-replica circuit breakers, hang failover (evicted
    sequences regenerate deterministically on the adopting replica),
    half-open probes, restart, and supervisor scaling (goodput floor
    plus the new ``tokens_floor``).

    Hedging defaults OFF for decode (``hedge_ms=0``): a decode request
    occupies a slot for its whole lifetime, so a hedge doubles slot
    pressure for the duration rather than shaving a straggler's tail —
    exactly the wrong trade under load. Pass ``hedge_ms`` explicitly to
    re-enable it for latency-critical, lightly-loaded fleets."""

    def __init__(self, model, devices=None, hedge_ms=0, **kwargs):
        super().__init__(model, devices=devices, hedge_ms=hedge_ms,
                         **kwargs)

    def _replicate(self, model, devices):
        return replicate_decode(model, devices)

    def _new_engine(self, model, index, on_outcome):
        return GenerateEngine(model, replica_id=index,
                              on_outcome=on_outcome,
                              **self._engine_kwargs)

    def submit(self, prompt, max_new_tokens=32, eos_token=None,
               deadline_ms=None, priority=None, trace=None,
               sampling=None, seed=None):
        rep = self._pick_replica()
        req = rep.engine.make_request(prompt,
                                      max_new_tokens=max_new_tokens,
                                      eos_token=eos_token,
                                      deadline_ms=deadline_ms,
                                      priority=priority, trace=trace,
                                      sampling=sampling, seed=seed)
        fut = rep.engine.submit_request(req)
        with self._hedge_lock:
            self._submitted += 1
        delay = self._hedge_delay_s
        if self._hedger is not None and delay and len(self._replicas) > 1:
            self._hedger.schedule(req, rep.index, delay)
        return fut

    def run(self, prompt, max_new_tokens=32, eos_token=None,
            deadline_ms=None, timeout=None, priority=None,
            sampling=None, seed=None):
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           eos_token=eos_token,
                           deadline_ms=deadline_ms,
                           priority=priority, sampling=sampling,
                           seed=seed).result(timeout)

    def _maybe_hedge(self, req, primary_index):
        """Decode hedge: re-prefill the same prompt on a second replica.
        The shadow carries the primary's resolved ``sampling`` (seed
        included), so greedy or sampled, both replicas derive the same
        counter keys and produce the same tokens; first resolution
        wins."""
        if req.future.done():
            return
        with self._hedge_lock:
            if self._hedged >= self.hedge_budget * self._submitted:
                return
            self._hedged += 1
        try:
            rep = self._pick_replica(exclude=(primary_index,))
        except Exception:
            with self._hedge_lock:
                self._hedged -= 1
            return
        ptr = req.trace
        shadow = DecodeRequest(req.prompt, req.max_new_tokens,
                               eos_token=req.eos_token,
                               deadline=req.deadline,
                               priority=req.priority,
                               sampling=req.sampling,
                               # the shadow rides the SAME context as a
                               # hedge attempt: whichever resolution wins
                               # the shared done-latch emits the record
                               trace=(None if ptr is None else
                                      ptr.ctx.attempt("hedge",
                                                      rep.index)))
        if ptr is not None:
            ptr.hop("hedge", replica=rep.index)
        metrics.record_hedge(replica=rep.index)

        def _on_shadow_done(sf, _req=req, _idx=rep.index):
            if sf.cancelled() or sf.exception() is not None:
                return
            try:
                _req.future.set_result(sf.result())
            except concurrent.futures.InvalidStateError:
                return
            with self._hedge_lock:
                self._hedge_wins += 1
            metrics.record_hedge_win(replica=_idx)

        shadow.future.add_done_callback(_on_shadow_done)
        try:
            rep.engine.submit_request(shadow)
        except Exception:
            with self._hedge_lock:
                self._hedged -= 1


# ---------------------------------------------------------------------------
# the reference decode model


class DemoLM:
    """A small causal-LM implementation of the decode-model contract:
    tied-embedding transformer (RMSNorm, per-layer attention + MLP),
    prefill through the flash-attention op (sdpa fallback off-TPU),
    decode as a single-token attention over the KV arena. Fixed random
    weights — it generates structured gibberish deterministically,
    which is exactly what throughput and parity tests need."""

    def __init__(self, vocab=64, dim=32, heads=2, layers=2, max_len=512,
                 seed=0):
        import jax
        import jax.numpy as jnp
        if dim % heads:
            raise ValueError(f"dim {dim} not divisible by heads {heads}")
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.heads = int(heads)
        self.head_dim = self.dim // self.heads
        self.layers = int(layers)
        self.max_len = int(max_len)
        keys = jax.random.split(jax.random.PRNGKey(seed),
                                2 + 6 * self.layers)
        scale = 1.0 / np.sqrt(self.dim)
        state = {"embed": jax.random.normal(
            keys[0], (self.vocab, self.dim), jnp.float32) * scale}
        # sinusoidal positions: deterministic, length-extensible, and
        # identical between prefill and decode by construction
        pos = np.arange(self.max_len)[:, None]
        div = np.exp(np.arange(0, self.dim, 2)
                     * (-np.log(10000.0) / self.dim))
        table = np.zeros((self.max_len, self.dim), np.float32)
        table[:, 0::2] = np.sin(pos * div)
        table[:, 1::2] = np.cos(pos * div)
        state["pos"] = jnp.asarray(table)
        for layer in range(self.layers):
            k = keys[2 + 6 * layer: 8 + 6 * layer]
            state[f"wq{layer}"] = jax.random.normal(
                k[0], (self.dim, self.dim), jnp.float32) * scale
            state[f"wk{layer}"] = jax.random.normal(
                k[1], (self.dim, self.dim), jnp.float32) * scale
            state[f"wv{layer}"] = jax.random.normal(
                k[2], (self.dim, self.dim), jnp.float32) * scale
            state[f"wo{layer}"] = jax.random.normal(
                k[3], (self.dim, self.dim), jnp.float32) * scale
            state[f"w1{layer}"] = jax.random.normal(
                k[4], (self.dim, 2 * self.dim), jnp.float32) * scale
            state[f"w2{layer}"] = jax.random.normal(
                k[5], (2 * self.dim, self.dim), jnp.float32) * scale
        self.state = state
        self.device = None

    def kv_spec(self):
        tail = (self.heads, self.head_dim)
        spec = {}
        for layer in range(self.layers):
            spec[f"k{layer}"] = (tail, "float32")
            spec[f"v{layer}"] = (tail, "float32")
        return spec

    @staticmethod
    def _norm(x):
        import jax.numpy as jnp
        return x * jnp.reciprocal(
            jnp.sqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True)
                     + 1e-6))

    def prefill_fn(self, state, tokens, lengths):
        """Full-prompt forward: (B, L) -> KV chunks + last-token logits.
        Causal attention makes end-padding harmless — every real
        position only sees real positions."""
        import jax.numpy as jnp
        from ..ops.pallas.flash_attention import flash_attention
        b, seq = tokens.shape
        h, hd = self.heads, self.head_dim
        x = state["embed"][tokens] + state["pos"][:seq][None]
        kv = {}
        for layer in range(self.layers):
            hidden = self._norm(x)
            q = (hidden @ state[f"wq{layer}"]).reshape(b, seq, h, hd)
            k = (hidden @ state[f"wk{layer}"]).reshape(b, seq, h, hd)
            v = (hidden @ state[f"wv{layer}"]).reshape(b, seq, h, hd)
            kv[f"k{layer}"] = k
            kv[f"v{layer}"] = v
            out = flash_attention(jnp.transpose(q, (0, 2, 1, 3)),
                                  jnp.transpose(k, (0, 2, 1, 3)),
                                  jnp.transpose(v, (0, 2, 1, 3)),
                                  causal=True)
            out = getattr(out, "data", out)     # dispatch may wrap Tensor
            out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, seq,
                                                           self.dim)
            x = x + out @ state[f"wo{layer}"]
            hidden = self._norm(x)
            x = x + jnp.maximum(
                hidden @ state[f"w1{layer}"], 0.0) @ state[f"w2{layer}"]
        logits = self._norm(x) @ state["embed"].T
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None].astype(jnp.int32),
            axis=1)[:, 0]
        return kv, last

    def decode_fn(self, state, tokens, kv, lengths):
        """One token per slot against the KV arena: attend over the
        resident history (masked by live length) plus the incoming
        token's own K/V — the same math as prefill position
        ``lengths`` — and emit that token's cache entry."""
        import jax.numpy as jnp
        s = tokens.shape[0]
        h, hd = self.heads, self.head_dim
        cap = next(iter(kv.values())).shape[1]
        inv = 1.0 / np.sqrt(hd)
        x = state["embed"][tokens] + state["pos"][lengths]
        entry = {}
        hist_mask = (jnp.arange(cap)[None, None, :]
                     < lengths[:, None, None])
        for layer in range(self.layers):
            hidden = self._norm(x)
            q = (hidden @ state[f"wq{layer}"]).reshape(s, h, hd)
            k_new = (hidden @ state[f"wk{layer}"]).reshape(s, h, hd)
            v_new = (hidden @ state[f"wv{layer}"]).reshape(s, h, hd)
            entry[f"k{layer}"] = k_new
            entry[f"v{layer}"] = v_new
            scores_h = jnp.einsum("shd,schd->shc", q,
                                  kv[f"k{layer}"]) * inv
            scores_h = jnp.where(hist_mask, scores_h, -1e9)
            score_s = jnp.sum(q * k_new, axis=-1,
                              keepdims=True) * inv
            scores = jnp.concatenate([scores_h, score_s], axis=-1)
            probs = jnp.exp(scores - jnp.max(scores, axis=-1,
                                             keepdims=True))
            probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
            out = jnp.einsum("shc,schd->shd", probs[..., :cap],
                             kv[f"v{layer}"]) \
                + probs[..., cap:] * v_new
            x = x + out.reshape(s, self.dim) @ state[f"wo{layer}"]
            hidden = self._norm(x)
            x = x + jnp.maximum(
                hidden @ state[f"w1{layer}"], 0.0) @ state[f"w2{layer}"]
        logits = self._norm(x) @ state["embed"].T
        return logits, entry

    def verify_fn(self, state, tokens, kv, lengths):
        """Chunked decode — ``decode_fn`` generalized to a ``(S, C)``
        chunk for speculative verify. Chunk position ``i`` sits at
        arena position ``lengths + i``: it attends over the resident
        history (masked by live length) plus chunk positions ``<= i``,
        and all C cache entries come back for the engine's optimistic
        write. The C == 1 case computes exactly what ``decode_fn``
        does (masked scores are exact zeros after softmax, so the
        extra padded lanes never perturb the sums) — that identity is
        the greedy-parity gate in scripts/spec_smoke.py."""
        import jax.numpy as jnp
        s, c = tokens.shape
        h, hd = self.heads, self.head_dim
        cap = next(iter(kv.values())).shape[1]
        inv = 1.0 / np.sqrt(hd)
        positions = lengths[:, None] + jnp.arange(c)[None, :]
        x = state["embed"][tokens] + state["pos"][positions]
        entry = {}
        hist_mask = (jnp.arange(cap)[None, None, None, :]
                     < lengths[:, None, None, None])      # [S,1,1,cap]
        self_mask = (jnp.arange(c)[None, :]
                     <= jnp.arange(c)[:, None])[None, :, None, :]
        for layer in range(self.layers):
            hidden = self._norm(x)
            q = (hidden @ state[f"wq{layer}"]).reshape(s, c, h, hd)
            k_new = (hidden @ state[f"wk{layer}"]).reshape(s, c, h, hd)
            v_new = (hidden @ state[f"wv{layer}"]).reshape(s, c, h, hd)
            entry[f"k{layer}"] = k_new
            entry[f"v{layer}"] = v_new
            scores_h = jnp.einsum("schd,sChd->schC", q,
                                  kv[f"k{layer}"]) * inv
            scores_h = jnp.where(hist_mask, scores_h, -1e9)
            scores_c = jnp.einsum("schd,sChd->schC", q, k_new) * inv
            scores_c = jnp.where(self_mask, scores_c, -1e9)
            scores = jnp.concatenate([scores_h, scores_c], axis=-1)
            probs = jnp.exp(scores - jnp.max(scores, axis=-1,
                                             keepdims=True))
            probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
            out = jnp.einsum("schC,sChd->schd", probs[..., :cap],
                             kv[f"v{layer}"]) \
                + jnp.einsum("schC,sChd->schd", probs[..., cap:], v_new)
            x = x + out.reshape(s, c, self.dim) @ state[f"wo{layer}"]
            hidden = self._norm(x)
            x = x + jnp.maximum(
                hidden @ state[f"w1{layer}"], 0.0) @ state[f"w2{layer}"]
        logits = self._norm(x) @ state["embed"].T
        return logits, entry


def demo_model(vocab=64, dim=32, heads=2, layers=2, max_len=512, seed=0):
    """The reference decode model for docs, tests, the loadgen, and the
    smoke/bench stages."""
    return DemoLM(vocab=vocab, dim=dim, heads=heads, layers=layers,
                  max_len=max_len, seed=seed)


def demo_spec_pair(vocab=64, dim=32, heads=2, draft_layers=1,
                   extra_layers=1, max_len=512, seed=0, distill=0.15):
    """A (target, draft) :class:`DemoLM` pair built for a high accept
    rate — the shape a distilled draft gives you in production:

    * the target is a ``draft_layers + extra_layers`` model whose
      *refinement* layers' weights are scaled by ``distill`` — each
      extra layer's residual contribution lands at roughly
      ``distill**2`` (q·k and w1·w2 both carry two damped factors), so
      the target's distribution is a small perturbation of its prefix;
    * the draft IS that prefix: it shares the embedding / position /
      first-``draft_layers`` weight **arrays** with the target (a
      rebuild from the same seed would re-split the PRNG differently),
      so the pair costs one model's memory plus the extra layers.

    Smaller ``distill`` → higher accept rate → more emitted tokens per
    verify step; the loadgen A/B and scripts/spec_smoke.py use this
    pair to demonstrate the speculative speedup honestly (same target
    math on both sides of the A/B)."""
    import copy
    target = DemoLM(vocab=vocab, dim=dim, heads=heads,
                    layers=draft_layers + extra_layers,
                    max_len=max_len, seed=seed)
    eps = float(distill)
    state = dict(target.state)
    for layer in range(draft_layers, target.layers):
        for w in ("wq", "wk", "wv", "wo", "w1", "w2"):
            state[f"{w}{layer}"] = state[f"{w}{layer}"] * eps
    target.state = state
    draft = copy.copy(target)
    draft.layers = int(draft_layers)
    draft.state = {k: v for k, v in state.items()
                   if k in ("embed", "pos")
                   or int(k[2:]) < draft.layers}
    return target, draft
