"""paddle_tpu.serving — the online inference runtime.

The stack below this package ends at a single-request ``Predictor``:
every caller pays its own dispatch, every ragged request shape mints a
fresh XLA executable, and overload turns into unbounded latency. This
subsystem is the serving tier that production TPU inference actually
needs (PAPERS.md: Gemma serving on Cloud TPU; "Operator Fusion in
XLA"): keep a small set of large compiled executables hot and coalesce
traffic into them.

* :mod:`~paddle_tpu.serving.batcher`   — bounded request queue +
  background drain thread; coalesces same-signature requests, flushes
  on ``max_batch`` rows or ``timeout_ms``
* :mod:`~paddle_tpu.serving.engine`    — :class:`ServingEngine`:
  ``submit()`` (future-returning) / ``run()`` (blocking) /
  ``warmup()`` (AOT-compiles every (bucket, signature) pair so steady
  state never compiles); pads to ``io.bucketing`` buckets and slices
  per-request outputs back bit-exactly
* :mod:`~paddle_tpu.serving.admission` — backpressure
  (:class:`QueueFullError` fast-reject), per-request SLA deadlines
  (dropped at dequeue, never occupying a batch slot), and
  ``RetryPolicy``-classified failure triage (one poisoned request
  fails its own future, not the whole batch)
* :mod:`~paddle_tpu.serving.metrics`   — ``serving.*`` counter /
  gauge / histogram series + ``serving.{enqueue,batch_assemble,
  execute,scatter}`` trace spans
* :mod:`~paddle_tpu.serving.multi`     — :class:`MultiDeviceEngine`:
  health-aware fan-out over per-device state replicas, with per-replica
  circuit breakers, hedged stragglers, failover re-dispatch, graceful
  preemption drain (SIGTERM → ``draining`` → zero-loss migration), and
  rolling live weight hot-swap (``swap_weights``)
* :mod:`~paddle_tpu.serving.breaker`   — the three-state
  :class:`CircuitBreaker` (closed → open → half_open) each replica
  carries
* :mod:`~paddle_tpu.serving.supervisor` — :class:`ServingSupervisor`:
  the closed control loop turning heartbeats + the live ``slo.*``
  window into failover / probe / restart / scale decisions
* :mod:`~paddle_tpu.serving.kv_cache`  — :class:`KVCachePool`: the
  paged, bucket-grown, budget-accounted K/V arena behind generative
  decode
* :mod:`~paddle_tpu.serving.generate`  — :class:`GenerateEngine`:
  continuous-batching autoregressive decode (fixed slot batch, one
  fused step per tick, prefill/decode split, zero steady-state
  compiles), with in-step sampling and an optional draft-model
  speculative verify loop, and :class:`MultiDecodeEngine`, its
  breaker-aware fleet fan-out
* :mod:`~paddle_tpu.serving.sampling`  — :class:`SamplingParams`
  (temperature / top-k / top-p / per-request seed), the batch-shaped
  jit-safe filter + Gumbel-max sampler, the counter-based PRNG keys
  that make streams bit-reproducible across replicas, and the
  speculative accept-prefix rule
* :mod:`~paddle_tpu.serving.prefix_cache` — :class:`PrefixCache`:
  ref-counted, byte-budgeted LRU over prefill KV segments keyed on the
  full prompt hash — a hit skips prefill entirely
* :mod:`~paddle_tpu.serving.disagg`    — disaggregated serving:
  :class:`PrefillPool` / :class:`DecodePool` as independently-scaled
  fleets and :class:`DisaggServer`, the priced prefill→decode KV
  handoff between them (bit-identical to the single-engine stream)
* :mod:`~paddle_tpu.serving.reqtrace`  — request-scoped tracing: one
  ``serving.request`` record per logical request with the blame-
  assigned stage waterfall (queue/assemble/execute/prefill/decode/
  hedge/…), ``ttft_ms``/``tpot_ms``, hop lineage across hedges and
  failovers, and the slow-request exemplar rings

See docs/robustness.md ("Self-healing serving") for the failure model.

Quickstart::

    from paddle_tpu import inference, serving

    pred = inference.Predictor(model)
    eng = serving.ServingEngine(pred, buckets=[8, 32], max_batch=32,
                                timeout_ms=5.0, deadline_ms=100.0)
    eng.warmup([((16,), "float32")])       # per-example input spec
    fut = eng.submit(x)                    # x: (n, 16), n <= 32
    y = fut.result()                       # == Predictor(model).run(x)
    eng.close()

See docs/serving.md for architecture and tuning.
"""
from __future__ import annotations

from . import batcher  # noqa: F401
from . import admission  # noqa: F401
from . import metrics  # noqa: F401
from . import breaker  # noqa: F401
from . import engine  # noqa: F401
from . import multi  # noqa: F401
from . import supervisor  # noqa: F401
from . import kv_cache  # noqa: F401
from . import reqtrace  # noqa: F401
from . import sampling  # noqa: F401
from . import generate  # noqa: F401
from . import prefix_cache  # noqa: F401
from . import disagg  # noqa: F401
from .admission import (AdmissionController, QueueFullError,  # noqa: F401
                        DeadlineExpired, ShedError, PRIORITIES)
from .batcher import DynamicBatcher, Request  # noqa: F401
from .breaker import CircuitBreaker  # noqa: F401
from .engine import ServingEngine  # noqa: F401
from .generate import (GenerateEngine, MultiDecodeEngine,  # noqa: F401
                       DecodeRequest, replicate_decode, demo_model,
                       demo_spec_pair)
from .sampling import SamplingParams  # noqa: F401
from .kv_cache import KVCachePool  # noqa: F401
from .multi import (MultiDeviceEngine, NoHealthyReplicaError,  # noqa: F401
                    replicate)
from .reqtrace import RequestTrace  # noqa: F401
from .supervisor import ServingSupervisor  # noqa: F401
from .prefix_cache import PrefixCache  # noqa: F401
from .disagg import (PrefillEngine, PrefillPool, DecodePool,  # noqa: F401
                     DisaggServer)

__all__ = [
    "batcher", "admission", "metrics", "engine", "multi", "breaker",
    "supervisor", "kv_cache", "generate", "reqtrace", "RequestTrace",
    "ServingEngine", "MultiDeviceEngine", "replicate", "DynamicBatcher",
    "Request", "AdmissionController", "QueueFullError", "DeadlineExpired",
    "ShedError", "PRIORITIES", "CircuitBreaker", "NoHealthyReplicaError",
    "ServingSupervisor",
    "GenerateEngine", "MultiDecodeEngine", "DecodeRequest", "KVCachePool",
    "replicate_decode", "demo_model", "demo_spec_pair", "sampling",
    "SamplingParams",
    "prefix_cache", "disagg", "PrefixCache", "PrefillEngine",
    "PrefillPool", "DecodePool", "DisaggServer",
]
