"""paddle_tpu.serving.multi — data-parallel replica fan-out.

A multi-chip inference host serves best as N independent replicas, not
one sharded model: each device holds a full copy of the state
(``jax.device_put`` — the serving analogue of data parallelism), runs
its own dynamic batcher, and a round-robin front door spreads request
streams across them. No collectives on the request path, so per-replica
latency is identical to single-device serving and aggregate QPS scales
with chip count until the host-side queue becomes the bottleneck.

:func:`replicate` is the state mechanic (one Predictor view per device,
sharing the model object, with a per-device executable cache);
:class:`MultiDeviceEngine` is the operational wrapper (one
``ServingEngine`` per replica + the round-robin ``submit``).
"""
from __future__ import annotations

import copy
import threading

from .engine import ServingEngine


def replicate(predictor, devices=None):
    """One ``Predictor`` view per device: the frozen eval-state pytree
    is ``device_put`` onto each device; the model object and config are
    shared (read-only at serving time); each replica gets its own
    executable cache (XLA executables are device-committed). Default
    devices: every local device."""
    import jax
    devices = list(devices) if devices is not None else jax.local_devices()
    if not devices:
        raise ValueError("replicate: no devices")
    replicas = []
    for d in devices:
        p = copy.copy(predictor)
        p.state = jax.device_put(predictor.state, d)
        p._compiled = {}
        p.device = d
        replicas.append(p)
    return replicas


class MultiDeviceEngine:
    """Round-robin fan-out over per-device :class:`ServingEngine`
    replicas. Same client surface (``submit``/``run``/``warmup``/
    ``stats``/context manager); engine kwargs apply per replica, so
    ``queue_depth`` and ``max_batch`` are per-device limits."""

    def __init__(self, predictor, devices=None, **engine_kwargs):
        self.replicas = replicate(predictor, devices)
        self.engines = [ServingEngine(p, **engine_kwargs)
                        for p in self.replicas]
        self._rr_lock = threading.Lock()
        self._rr = 0

    def _next_engine(self):
        with self._rr_lock:
            e = self.engines[self._rr]
            self._rr = (self._rr + 1) % len(self.engines)
        return e

    def submit(self, *inputs, deadline_ms=None):
        return self._next_engine().submit(*inputs, deadline_ms=deadline_ms)

    def run(self, *inputs, deadline_ms=None, timeout=None):
        return self.submit(*inputs, deadline_ms=deadline_ms).result(timeout)

    def warmup(self, *signatures):
        """Warm every replica (each compiles its own device-committed
        executables). Returns total fresh executables."""
        return sum(e.warmup(*signatures) for e in self.engines)

    def start(self):
        for e in self.engines:
            e.start()

    def close(self, drain=True, timeout=None):
        for e in self.engines:
            e.close(drain=drain, timeout=timeout)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()

    def stats(self):
        """Aggregate across replicas, with the per-replica breakdown
        under ``"replicas"``."""
        per = [e.stats() for e in self.engines]
        agg = {k: sum(s[k] for s in per)
               for k in per[0] if isinstance(per[0][k], (int, float))}
        agg["replicas"] = per
        agg["devices"] = [str(getattr(p, "device", "?"))
                          for p in self.replicas]
        return agg
