"""paddle_tpu.serving.multi — self-healing data-parallel replica fan-out.

A multi-chip inference host serves best as N independent replicas, not
one sharded model: each device holds a full copy of the state
(``jax.device_put`` — the serving analogue of data parallelism), runs
its own dynamic batcher, and the front door spreads request streams
across them. No collectives on the request path, so per-replica latency
is identical to single-device serving and aggregate QPS scales with
chip count until the host-side queue becomes the bottleneck.

Blind round-robin dies with its first dead replica (every Nth request
stalls), so routing is **health-aware**:

* each replica carries a :class:`~paddle_tpu.serving.breaker.
  CircuitBreaker` fed by batch outcomes and supervision verdicts;
  requests route only to replicas whose breaker allows them, and a
  fleet with no healthy replica fast-rejects with the retryable
  :class:`NoHealthyReplicaError` rather than queueing onto a corpse;
* a :class:`~paddle_tpu.serving.supervisor.ServingSupervisor` watches
  per-replica heartbeats, trips the breaker on a hung dispatch, moves
  that replica's queued *and* in-flight requests to healthy peers
  (failover — safe because ``Request`` resolution is idempotent:
  whichever dispatch finishes first wins, the loser's resolution is
  swallowed), probes half-open breakers with budgeted test traffic,
  restarts replicas that stay dead, and scales the active set from the
  live ``slo.*`` window;
* stragglers are **hedged**: a request still unresolved after the hedge
  delay (p99-derived by default) is re-dispatched to a second healthy
  replica and the first result wins, with total hedges capped at
  ``hedge_budget`` of traffic so the cure can't out-eat the disease.

:func:`replicate` is the state mechanic (one Predictor view per device,
sharing the model object, with a per-device executable cache);
:class:`MultiDeviceEngine` is the operational wrapper.
"""
from __future__ import annotations

import copy
import heapq
import threading
import time
import weakref

import concurrent.futures

from .admission import ShedError
from .breaker import CircuitBreaker
from .engine import ServingEngine
from . import metrics

#: live MultiDeviceEngines — /healthz walks this (weak: an un-closed
#: engine can still be collected)
_ACTIVE = weakref.WeakSet()

#: floor on the auto hedge delay: below this, hedges fire on normal
#: scheduling jitter and burn the budget on non-stragglers
MIN_HEDGE_S = 0.025


class NoHealthyReplicaError(ShedError):
    """Every replica's breaker is open (or routing-excluded): there is
    no capacity to take this request right now. Transient — the breaker
    cooldown is exactly a retry-after."""


def replicate(predictor, devices=None):
    """One ``Predictor`` view per device: the frozen eval-state pytree
    is ``device_put`` onto each device; the model object and config are
    shared (read-only at serving time); each replica gets its own
    executable cache (XLA executables are device-committed). Default
    devices: every local device."""
    import jax
    devices = list(devices) if devices is not None else jax.local_devices()
    if not devices:
        raise ValueError("replicate: no devices")
    replicas = []
    for d in devices:
        p = copy.copy(predictor)
        p.state = jax.device_put(predictor.state, d)
        p._compiled = {}
        p.device = d
        replicas.append(p)
    return replicas


class _Replica:
    """One slot in the fleet: device + predictor + engine + breaker +
    routing flag, plus the supervision tokens that make hang handling
    exactly-once per dispatch."""

    def __init__(self, index, device, predictor, engine, breaker,
                 active=True):
        self.index = index
        self.device = device
        self.predictor = predictor
        self.engine = engine
        self.breaker = breaker
        self.active = active
        self.handled_token = None    # last in-flight dispatch failed over
        self.restart_token = None    # last in-flight dispatch restarted on
        self.restarts = 0


class _Hedger(threading.Thread):
    """Deadline heap + daemon thread: ``schedule`` arms a hedge timer
    per request; when it fires and the request is still unresolved, the
    owner re-dispatches it to a second replica."""

    def __init__(self, owner):
        super().__init__(name="paddle_tpu-serving-hedger", daemon=True)
        self._owner = weakref.ref(owner)
        self._cond = threading.Condition()
        self._heap = []
        self._seq = 0
        self._stop = False

    def schedule(self, request, primary_index, delay_s):
        with self._cond:
            self._seq += 1
            heapq.heappush(self._heap,
                           (time.monotonic() + delay_s, self._seq,
                            request, primary_index))
            self._cond.notify()

    def stop(self):
        with self._cond:
            self._stop = True
            self._cond.notify()

    def run(self):
        while True:
            with self._cond:
                if self._stop:
                    return
                if not self._heap:
                    self._cond.wait(0.1)
                    continue
                due = self._heap[0][0]
                now = time.monotonic()
                if due > now:
                    self._cond.wait(min(due - now, 0.1))
                    continue
                _, _, req, primary = heapq.heappop(self._heap)
            owner = self._owner()
            if owner is None:
                return
            try:
                owner._maybe_hedge(req, primary)
            except Exception:   # noqa: BLE001 - hedging is best-effort;
                pass            # the primary dispatch still owns the future


class MultiDeviceEngine:
    """Health-aware fan-out over per-device :class:`ServingEngine`
    replicas. Same client surface as v1 (``submit``/``run``/``warmup``/
    ``stats``/context manager); engine kwargs apply per replica, so
    ``queue_depth`` and ``max_batch`` are per-device limits.

    Resilience knobs (see docs/serving.md for the full matrix):

    hedge_ms : straggler hedge delay. ``None`` (default) derives it
        from the live ``slo.p99_ms`` window (floored at 25ms); a number
        fixes it; ``0``/``False`` disables hedging.
    hedge_budget : max fraction of submitted traffic that may be
        hedged (default 0.05).
    breaker_threshold / breaker_cooldown_s / half_open_probes :
        per-replica :class:`CircuitBreaker` tuning.
    inflight_timeout_ms : a dispatch older than this is declared hung —
        breaker trips, batch fails over. ``None`` defaults to 4× the
        engine ``deadline_ms`` when set, else 2000ms.
    supervise : run the :class:`ServingSupervisor` control loop
        (default True; tests drive ticks manually with False).
    min_replicas / initial_active : scaling bounds — the supervisor
        never deactivates below ``min_replicas``; ``initial_active``
        starts the fleet smaller than the device count and lets the
        goodput floor scale it up.
    """

    def __init__(self, predictor, devices=None, hedge_ms=None,
                 hedge_budget=0.05, breaker_threshold=3,
                 breaker_cooldown_s=2.0, half_open_probes=1,
                 inflight_timeout_ms=None, supervise=True,
                 supervisor_interval_s=0.25, min_replicas=1,
                 initial_active=None, restart_after_s=None,
                 tokens_floor=None, **engine_kwargs):
        self.predictor = predictor
        self._engine_kwargs = dict(engine_kwargs)
        self._breaker_kwargs = dict(
            failure_threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s,
            half_open_probes=half_open_probes)
        preds = self._replicate(predictor, devices)
        self._replicas = []
        for i, p in enumerate(preds):
            self._replicas.append(self._make_replica(i, p))
        if initial_active is not None:
            for r in self._replicas[int(initial_active):]:
                r.active = False
        self.min_replicas = max(1, int(min_replicas))
        self._rr_lock = threading.Lock()
        self._rr = 0
        # hedging
        if hedge_ms is None:
            self._hedge_fixed = None
            self._hedge_delay_s = 2 * MIN_HEDGE_S   # until p99 exists
        elif not hedge_ms:                          # 0 / False
            self._hedge_fixed = 0.0
            self._hedge_delay_s = 0.0
        else:
            self._hedge_fixed = float(hedge_ms) / 1e3
            self._hedge_delay_s = self._hedge_fixed
        self.hedge_budget = float(hedge_budget)
        self._hedge_lock = threading.Lock()
        self._submitted = 0
        self._hedged = 0
        self._hedge_wins = 0
        self._failovers = 0
        self._hedger = None
        if self._hedge_delay_s or self._hedge_fixed is None:
            self._hedger = _Hedger(self)
            self._hedger.start()
        # supervision
        if inflight_timeout_ms is None:
            dl = engine_kwargs.get("deadline_ms")
            inflight_timeout_ms = 4 * dl if dl else 2000.0
        self.inflight_timeout_s = float(inflight_timeout_ms) / 1e3
        self._warm_sigs = ()
        self.supervisor = None
        if supervise:
            from .supervisor import ServingSupervisor
            self.supervisor = ServingSupervisor(
                self, interval_s=supervisor_interval_s,
                restart_after_s=restart_after_s,
                tokens_floor=tokens_floor)
        _ACTIVE.add(self)
        metrics.record_active_replicas(
            sum(1 for r in self._replicas if r.active))

    # -- replica construction hooks (overridden by the decode fleet) -------

    def _replicate(self, predictor, devices):
        """State mechanic: one predictor view per device. The decode
        fleet (``generate.MultiDecodeEngine``) overrides this with
        ``replicate_decode`` — same fan-out spine, different payload."""
        return replicate(predictor, devices)

    def _new_engine(self, predictor, index, on_outcome):
        """Per-replica engine factory — the other decode-fleet seam."""
        return ServingEngine(predictor, replica_id=index,
                             on_outcome=on_outcome, **self._engine_kwargs)

    def _make_replica(self, index, predictor):
        breaker = CircuitBreaker(name=str(index), **self._breaker_kwargs)

        def _outcome(ok, exc, _b=breaker):
            if ok:
                _b.record_success()
            else:
                _b.record_failure(repr(exc))

        engine = self._new_engine(predictor, index, _outcome)
        return _Replica(index, getattr(predictor, "device", None),
                        predictor, engine, breaker)

    # -- compat views ------------------------------------------------------

    @property
    def engines(self):
        return [r.engine for r in self._replicas]

    @property
    def replicas(self):
        return [r.predictor for r in self._replicas]

    # -- routing -----------------------------------------------------------

    def _pick_replica(self, exclude=()):
        """Next active replica whose breaker admits traffic, round-robin
        from the cursor. ``allow()`` on a half-open breaker consumes one
        probe slot — it's only called on replicas actually considered.
        Raises :class:`NoHealthyReplicaError` when nobody can take it."""
        with self._rr_lock:
            n = len(self._replicas)
            order = [(self._rr + k) % n for k in range(n)]
            self._rr = (self._rr + 1) % n
        for idx in order:
            r = self._replicas[idx]
            if not r.active or idx in exclude:
                continue
            if r.breaker.allow():
                return r
        states = {r.index: r.breaker.state for r in self._replicas}
        raise NoHealthyReplicaError(
            f"no healthy replica (breakers: {states}); retry after "
            f"{self._breaker_kwargs['cooldown_s'] * 1e3:.0f}ms",
            retry_after_ms=self._breaker_kwargs["cooldown_s"] * 1e3,
            level=3)

    def submit(self, *inputs, deadline_ms=None, priority=None,
               trace=None):
        rep = self._pick_replica()
        req = rep.engine.make_request(inputs, deadline_ms=deadline_ms,
                                      priority=priority, trace=trace)
        fut = rep.engine.submit_request(req)
        with self._hedge_lock:
            self._submitted += 1
        delay = self._hedge_delay_s
        if self._hedger is not None and delay and len(self._replicas) > 1:
            self._hedger.schedule(req, rep.index, delay)
        return fut

    def run(self, *inputs, deadline_ms=None, timeout=None, priority=None):
        return self.submit(*inputs, deadline_ms=deadline_ms,
                           priority=priority).result(timeout)

    # -- hedging -----------------------------------------------------------

    def _maybe_hedge(self, req, primary_index):
        """Hedge timer fired: if the request is still unresolved and the
        budget allows, re-dispatch it to a different healthy replica and
        let the first resolution win."""
        if req.future.done():
            return
        with self._hedge_lock:
            if self._hedged >= self.hedge_budget * self._submitted:
                return
            self._hedged += 1
        try:
            rep = self._pick_replica(exclude=(primary_index,))
        except NoHealthyReplicaError:
            with self._hedge_lock:
                self._hedged -= 1   # unfired: give the budget back
            return
        from .batcher import Request
        ptr = req.trace
        shadow = Request(req.inputs, req.n, req.signature,
                         deadline=req.deadline, priority=req.priority,
                         seq_real=req.seq_real, seq_padded=req.seq_padded,
                         # the shadow rides the SAME trace context as a
                         # hedge attempt: whichever resolution wins the
                         # shared done-latch emits the one record
                         trace=(None if ptr is None else
                                ptr.ctx.attempt("hedge", rep.index)))
        if ptr is not None:
            ptr.hop("hedge", replica=rep.index)
        metrics.record_hedge(replica=rep.index)

        def _on_shadow_done(sf, _req=req, _idx=rep.index):
            if sf.cancelled() or sf.exception() is not None:
                return          # primary still owns the future
            try:
                _req.future.set_result(sf.result())
            except concurrent.futures.InvalidStateError:
                return          # primary won the race
            with self._hedge_lock:
                self._hedge_wins += 1
            metrics.record_hedge_win(replica=_idx)

        shadow.future.add_done_callback(_on_shadow_done)
        try:
            rep.engine.submit_request(shadow)
        except ShedError:
            with self._hedge_lock:
                self._hedged -= 1   # shadow shed at admission: not a hedge
        except RuntimeError:
            pass                    # replica closed under us

    def _refresh_hedge_delay(self, p99_ms):
        """Supervisor tick: re-derive the auto hedge delay from the live
        p99 (a hedge should fire only for genuine stragglers)."""
        if self._hedge_fixed is not None:
            return
        if p99_ms:
            self._hedge_delay_s = max(MIN_HEDGE_S, float(p99_ms) / 1e3)

    # -- failover / restart (supervisor verdicts) --------------------------

    def _failover(self, replica, reason=""):
        """Move a tripped replica's queued and in-flight requests to
        healthy peers. The in-flight group is *disowned* first, so even
        if the hung dispatch eventually completes, whichever resolution
        lands first wins and the other is swallowed — exactly once,
        either way."""
        moved = replica.engine.disown_inflight()
        moved += replica.engine.steal_pending()
        moved = [r for r in moved if not r.future.done()]
        if not moved:
            return 0
        with self._hedge_lock:
            self._failovers += 1
        metrics.record_failover(replica.index, len(moved))
        for r in moved:
            tr = getattr(r, "trace", None)
            if tr is not None:
                tr.hop("failover", replica=replica.index, reason=reason)
        try:
            target = self._pick_replica(exclude=(replica.index,))
        except NoHealthyReplicaError as e:
            for r in moved:
                r.resolve_exception(e)
            return len(moved)
        target.engine.requeue(moved)
        return len(moved)

    def _restart(self, replica):
        """Re-``replicate()`` state onto the replica's device, swap in a
        fresh engine (warmed with the remembered signatures), and close
        the old one in the background with a bounded join — its drain
        thread may be wedged forever."""
        old_engine = replica.engine
        fresh_pred = self._replicate(self.predictor, [replica.device])[0]
        fresh = self._make_replica(replica.index, fresh_pred)
        # keep the ORIGINAL breaker (state + flap history): the restarted
        # engine stays open until a probe or budgeted request closes it
        def _outcome(ok, exc, _b=replica.breaker):
            if ok:
                _b.record_success()
            else:
                _b.record_failure(repr(exc))
        fresh.engine.on_outcome = _outcome
        if self._warm_sigs:
            try:
                fresh.engine.warmup(*self._warm_sigs)
            except Exception:   # noqa: BLE001 - warm lazily instead
                pass
        fresh.engine.start()
        replica.predictor = fresh.predictor
        replica.engine = fresh.engine
        replica.restarts += 1
        replica.restart_token = None
        metrics.record_replica_restart(replica.index)
        threading.Thread(
            target=lambda: old_engine.close(drain=False, timeout=1.0),
            name="paddle_tpu-serving-reap", daemon=True).start()

    # -- scaling (supervisor verdicts) -------------------------------------

    def _active_count(self):
        return sum(1 for r in self._replicas if r.active)

    def _activate_one(self):
        for r in self._replicas:
            if not r.active:
                r.active = True
                metrics.record_active_replicas(self._active_count())
                return r
        return None

    def _deactivate_one(self):
        if self._active_count() <= self.min_replicas:
            return None
        for r in reversed(self._replicas):
            if r.active:
                r.active = False
                # drain its queue onto the survivors
                moved = [q for q in r.engine.steal_pending()
                         if not q.future.done()]
                if moved:
                    try:
                        self._pick_replica(
                            exclude=(r.index,)).engine.requeue(moved)
                    except NoHealthyReplicaError:
                        r.engine.requeue(moved)   # undo: keep serving
                        r.active = True
                        return None
                metrics.record_active_replicas(self._active_count())
                return r
        return None

    # -- fleet lifecycle ---------------------------------------------------

    def warmup(self, *signatures):
        """Warm every replica (each compiles its own device-committed
        executables); the signatures are remembered so a restarted
        replica re-warms before taking traffic. Returns total fresh
        executables."""
        self._warm_sigs = signatures
        return sum(r.engine.warmup(*signatures) for r in self._replicas)

    def start(self):
        for r in self._replicas:
            r.engine.start()

    def close(self, drain=True, timeout=None):
        if self.supervisor is not None:
            self.supervisor.stop()
        if self._hedger is not None:
            self._hedger.stop()
        _ACTIVE.discard(self)
        for r in self._replicas:
            # a hung replica must not hold close() hostage: bound the
            # join (its stranded futures fail rather than strand)
            t = timeout
            if t is None and drain:
                t = 10.0
            r.engine.close(drain=drain, timeout=t)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()

    # -- observability -----------------------------------------------------

    def stats(self):
        """Aggregate across replicas, with the per-replica breakdown
        under ``"replicas"`` and the resilience tallies alongside."""
        per = [r.engine.stats() for r in self._replicas]
        agg = {k: sum(s[k] for s in per)
               for k in per[0] if isinstance(per[0][k], (int, float))}
        agg["replicas"] = per
        agg["devices"] = [str(r.device) for r in self._replicas]
        with self._hedge_lock:
            agg["hedged"] = self._hedged
            agg["hedge_wins"] = self._hedge_wins
            agg["failovers"] = self._failovers
        agg["restarts"] = sum(r.restarts for r in self._replicas)
        agg["active_replicas"] = self._active_count()
        agg["breakers"] = {r.index: r.breaker.state
                           for r in self._replicas}
        return agg

    def health(self, now=None):
        """The /healthz ``serving`` block: per-replica breaker state and
        heartbeat ages, plus ``all_open`` (no replica can take traffic
        → the endpoint answers 503)."""
        now = time.monotonic() if now is None else now
        reps = []
        any_admitting = False
        for r in self._replicas:
            h = r.engine.heartbeat(now)
            state = r.breaker.state
            if r.active and state != "open":
                any_admitting = True
            reps.append({
                "replica": r.index,
                "device": str(r.device),
                "breaker": state,
                "active": bool(r.active),
                "queue_depth": h["queue_depth"],
                "inflight_age_s": None if h["inflight_age_s"] is None
                else round(h["inflight_age_s"], 3),
                "heartbeat_age_s": round(h["last_ok_age_s"], 3),
                "restarts": r.restarts,
            })
        out = {"replicas": reps, "all_open": not any_admitting,
               "active_replicas": self._active_count()}
        if self.supervisor is not None:
            out["supervisor"] = self.supervisor.last_decision()
        return out


def health():
    """Health blocks for every live MultiDeviceEngine (what
    ``monitor.export.health_payload`` embeds under ``serving``)."""
    return [eng.health() for eng in list(_ACTIVE)]


def publish_gauges():
    """Sampler tick: republish per-replica breaker state and the active
    count (transitions set the gauges too, but a tick keeps the
    open→half_open cooldown promotion visible without traffic)."""
    from .. import monitor as _monitor
    if not _monitor.enabled():
        return
    for eng in list(_ACTIVE):
        metrics.record_active_replicas(eng._active_count())
        for r in eng._replicas:
            _monitor.gauge(f"serving.breaker_state.{r.index}").set(
                metrics._BREAKER_STATE_NUM.get(r.breaker.state, -1))
