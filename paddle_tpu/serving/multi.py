"""paddle_tpu.serving.multi — self-healing data-parallel replica fan-out.

A multi-chip inference host serves best as N independent replicas, not
one sharded model: each device holds a full copy of the state
(``jax.device_put`` — the serving analogue of data parallelism), runs
its own dynamic batcher, and the front door spreads request streams
across them. No collectives on the request path, so per-replica latency
is identical to single-device serving and aggregate QPS scales with
chip count until the host-side queue becomes the bottleneck.

Blind round-robin dies with its first dead replica (every Nth request
stalls), so routing is **health-aware**:

* each replica carries a :class:`~paddle_tpu.serving.breaker.
  CircuitBreaker` fed by batch outcomes and supervision verdicts;
  requests route only to replicas whose breaker allows them, and a
  fleet with no healthy replica fast-rejects with the retryable
  :class:`NoHealthyReplicaError` rather than queueing onto a corpse;
* a :class:`~paddle_tpu.serving.supervisor.ServingSupervisor` watches
  per-replica heartbeats, trips the breaker on a hung dispatch, moves
  that replica's queued *and* in-flight requests to healthy peers
  (failover — safe because ``Request`` resolution is idempotent:
  whichever dispatch finishes first wins, the loser's resolution is
  swallowed), probes half-open breakers with budgeted test traffic,
  restarts replicas that stay dead, and scales the active set from the
  live ``slo.*`` window;
* stragglers are **hedged**: a request still unresolved after the hedge
  delay (p99-derived by default) is re-dispatched to a second healthy
  replica and the first result wins, with total hedges capped at
  ``hedge_budget`` of traffic so the cure can't out-eat the disease.

Beyond failure handling, the fleet has a *lifecycle*: scheduler
preemption (SIGTERM, or the injected ``preempt_replica`` fault) flips a
replica to **draining** — healthy but refusing new work — and migrates
its queued and in-flight requests to peers over the same
``disown_inflight``/``requeue`` deterministic-replay path failover
uses, so a preemption loses zero requests and sampled streams complete
bit-identical to the fault-free run. :meth:`MultiDeviceEngine.
swap_weights` rolls new weights through the fleet one replica at a
time (drain-lite → place state → probe → readmit) without dropping a
request or minting an executable; a quorum-failing checkpoint publish
never swaps in. Every fleet subscribes itself to
``resilience.preempt`` at construction — a process-level SIGTERM
drains every live fleet.

:func:`replicate` is the state mechanic (one Predictor view per device,
sharing the model object, with a per-device executable cache);
:class:`MultiDeviceEngine` is the operational wrapper.
"""
from __future__ import annotations

import copy
import heapq
import threading
import time
import weakref

import concurrent.futures

from .admission import ShedError
from .breaker import CircuitBreaker
from .engine import ServingEngine
from . import metrics
from ..resilience import faults as _faults
from ..resilience import preempt as _preempt

#: live MultiDeviceEngines — /healthz walks this (weak: an un-closed
#: engine can still be collected)
_ACTIVE = weakref.WeakSet()

#: most recent lifecycle event across all fleets (the /snapshot block)
_LAST_LIFECYCLE = None


def last_lifecycle():
    return _LAST_LIFECYCLE

#: floor on the auto hedge delay: below this, hedges fire on normal
#: scheduling jitter and burn the budget on non-stragglers
MIN_HEDGE_S = 0.025


class NoHealthyReplicaError(ShedError):
    """Every replica's breaker is open (or routing-excluded): there is
    no capacity to take this request right now. Transient — the breaker
    cooldown is exactly a retry-after."""


def replicate(predictor, devices=None):
    """One ``Predictor`` view per device: the frozen eval-state pytree
    is ``device_put`` onto each device; the model object and config are
    shared (read-only at serving time); each replica gets its own
    executable cache (XLA executables are device-committed). Default
    devices: every local device."""
    import jax
    devices = list(devices) if devices is not None else jax.local_devices()
    if not devices:
        raise ValueError("replicate: no devices")
    replicas = []
    for d in devices:
        p = copy.copy(predictor)
        p.state = jax.device_put(predictor.state, d)
        p._compiled = {}
        p.device = d
        replicas.append(p)
    return replicas


class _Replica:
    """One slot in the fleet: device + predictor + engine + breaker +
    routing flag, plus the supervision tokens that make hang handling
    exactly-once per dispatch."""

    def __init__(self, index, device, predictor, engine, breaker,
                 active=True):
        self.index = index
        self.device = device
        self.predictor = predictor
        self.engine = engine
        self.breaker = breaker
        self.active = active
        # draining: healthy but refusing NEW work (preemption notice or
        # a rolling weight swap); distinct from an open breaker
        self.draining = False
        self.handled_token = None    # last in-flight dispatch failed over
        self.restart_token = None    # last in-flight dispatch restarted on
        self.restarts = 0

    @property
    def state(self):
        """Routing state for /healthz and the gauges: ``draining``
        masks the (healthy) breaker state while the replica refuses
        admission."""
        return "draining" if self.draining else self.breaker.state


class _Hedger(threading.Thread):
    """Deadline heap + daemon thread: ``schedule`` arms a hedge timer
    per request; when it fires and the request is still unresolved, the
    owner re-dispatches it to a second replica."""

    def __init__(self, owner):
        super().__init__(name="paddle_tpu-serving-hedger", daemon=True)
        self._owner = weakref.ref(owner)
        self._cond = threading.Condition()
        self._heap = []
        self._seq = 0
        self._stop = False

    def schedule(self, request, primary_index, delay_s):
        with self._cond:
            self._seq += 1
            heapq.heappush(self._heap,
                           (time.monotonic() + delay_s, self._seq,
                            request, primary_index))
            self._cond.notify()

    def stop(self):
        with self._cond:
            self._stop = True
            self._cond.notify()

    def run(self):
        while True:
            with self._cond:
                if self._stop:
                    return
                if not self._heap:
                    self._cond.wait(0.1)
                    continue
                due = self._heap[0][0]
                now = time.monotonic()
                if due > now:
                    self._cond.wait(min(due - now, 0.1))
                    continue
                _, _, req, primary = heapq.heappop(self._heap)
            owner = self._owner()
            if owner is None:
                return
            try:
                owner._maybe_hedge(req, primary)
            except Exception:   # noqa: BLE001 - hedging is best-effort;
                pass            # the primary dispatch still owns the future


class MultiDeviceEngine:
    """Health-aware fan-out over per-device :class:`ServingEngine`
    replicas. Same client surface as v1 (``submit``/``run``/``warmup``/
    ``stats``/context manager); engine kwargs apply per replica, so
    ``queue_depth`` and ``max_batch`` are per-device limits.

    Resilience knobs (see docs/serving.md for the full matrix):

    hedge_ms : straggler hedge delay. ``None`` (default) derives it
        from the live ``slo.p99_ms`` window (floored at 25ms); a number
        fixes it; ``0``/``False`` disables hedging.
    hedge_budget : max fraction of submitted traffic that may be
        hedged (default 0.05).
    breaker_threshold / breaker_cooldown_s / half_open_probes :
        per-replica :class:`CircuitBreaker` tuning.
    inflight_timeout_ms : a dispatch older than this is declared hung —
        breaker trips, batch fails over. ``None`` defaults to 4× the
        engine ``deadline_ms`` when set, else 2000ms.
    supervise : run the :class:`ServingSupervisor` control loop
        (default True; tests drive ticks manually with False).
    min_replicas / initial_active : scaling bounds — the supervisor
        never deactivates below ``min_replicas``; ``initial_active``
        starts the fleet smaller than the device count and lets the
        goodput floor scale it up.
    """

    def __init__(self, predictor, devices=None, hedge_ms=None,
                 hedge_budget=0.05, breaker_threshold=3,
                 breaker_cooldown_s=2.0, half_open_probes=1,
                 inflight_timeout_ms=None, supervise=True,
                 supervisor_interval_s=0.25, min_replicas=1,
                 initial_active=None, restart_after_s=None,
                 tokens_floor=None, **engine_kwargs):
        self.predictor = predictor
        self._engine_kwargs = dict(engine_kwargs)
        self._breaker_kwargs = dict(
            failure_threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s,
            half_open_probes=half_open_probes)
        preds = self._replicate(predictor, devices)
        self._replicas = []
        for i, p in enumerate(preds):
            self._replicas.append(self._make_replica(i, p))
        if initial_active is not None:
            for r in self._replicas[int(initial_active):]:
                r.active = False
        self.min_replicas = max(1, int(min_replicas))
        self._rr_lock = threading.Lock()
        self._rr = 0
        # hedging
        if hedge_ms is None:
            self._hedge_fixed = None
            self._hedge_delay_s = 2 * MIN_HEDGE_S   # until p99 exists
        elif not hedge_ms:                          # 0 / False
            self._hedge_fixed = 0.0
            self._hedge_delay_s = 0.0
        else:
            self._hedge_fixed = float(hedge_ms) / 1e3
            self._hedge_delay_s = self._hedge_fixed
        self.hedge_budget = float(hedge_budget)
        self._hedge_lock = threading.Lock()
        self._submitted = 0
        self._hedged = 0
        self._hedge_wins = 0
        self._failovers = 0
        self._hedger = None
        if self._hedge_delay_s or self._hedge_fixed is None:
            self._hedger = _Hedger(self)
            self._hedger.start()
        # supervision
        if inflight_timeout_ms is None:
            dl = engine_kwargs.get("deadline_ms")
            inflight_timeout_ms = 4 * dl if dl else 2000.0
        self.inflight_timeout_s = float(inflight_timeout_ms) / 1e3
        self._warm_sigs = ()
        self.supervisor = None
        if supervise:
            from .supervisor import ServingSupervisor
            self.supervisor = ServingSupervisor(
                self, interval_s=supervisor_interval_s,
                restart_after_s=restart_after_s,
                tokens_floor=tokens_floor)
        # lifecycle: served weights version (stamped into reqtrace
        # records), the fleet's last lifecycle event, and the process
        # preemption subscription — SIGTERM drains this fleet; the
        # subscription holds the fleet weakly so an un-closed engine
        # can still be collected
        self.weights_version = 0
        for r in self._replicas:
            r.engine.weights_version = 0
        self._lifecycle = None
        self._swap_lock = threading.Lock()
        _self_ref = weakref.ref(self)

        def _on_preempt(signum, _ref=_self_ref):
            owner = _ref()
            if owner is not None:
                owner.drain_fleet(reason=f"preempt:{signum}")

        self._preempt_cb = _preempt.subscribe(_on_preempt)
        _ACTIVE.add(self)
        metrics.record_active_replicas(
            sum(1 for r in self._replicas if r.active))

    # -- replica construction hooks (overridden by the decode fleet) -------

    def _replicate(self, predictor, devices):
        """State mechanic: one predictor view per device. The decode
        fleet (``generate.MultiDecodeEngine``) overrides this with
        ``replicate_decode`` — same fan-out spine, different payload."""
        return replicate(predictor, devices)

    def _new_engine(self, predictor, index, on_outcome):
        """Per-replica engine factory — the other decode-fleet seam."""
        return ServingEngine(predictor, replica_id=index,
                             on_outcome=on_outcome, **self._engine_kwargs)

    def _make_replica(self, index, predictor):
        breaker = CircuitBreaker(name=str(index), **self._breaker_kwargs)

        def _outcome(ok, exc, _b=breaker):
            if ok:
                _b.record_success()
            else:
                _b.record_failure(repr(exc))

        engine = self._new_engine(predictor, index, _outcome)
        return _Replica(index, getattr(predictor, "device", None),
                        predictor, engine, breaker)

    # -- compat views ------------------------------------------------------

    @property
    def engines(self):
        return [r.engine for r in self._replicas]

    @property
    def replicas(self):
        return [r.predictor for r in self._replicas]

    # -- routing -----------------------------------------------------------

    def _pick_replica(self, exclude=()):
        """Next active replica whose breaker admits traffic, round-robin
        from the cursor. ``allow()`` on a half-open breaker consumes one
        probe slot — it's only called on replicas actually considered.
        Raises :class:`NoHealthyReplicaError` when nobody can take it."""
        with self._rr_lock:
            n = len(self._replicas)
            order = [(self._rr + k) % n for k in range(n)]
            self._rr = (self._rr + 1) % n
        for idx in order:
            r = self._replicas[idx]
            if not r.active or r.draining or idx in exclude:
                continue
            if r.breaker.allow():
                return r
        states = {r.index: r.state for r in self._replicas}
        raise NoHealthyReplicaError(
            f"no healthy replica (breakers: {states}); retry after "
            f"{self._breaker_kwargs['cooldown_s'] * 1e3:.0f}ms",
            retry_after_ms=self._breaker_kwargs["cooldown_s"] * 1e3,
            level=3)

    def submit(self, *inputs, deadline_ms=None, priority=None,
               trace=None):
        rep = self._pick_replica()
        req = rep.engine.make_request(inputs, deadline_ms=deadline_ms,
                                      priority=priority, trace=trace)
        fut = rep.engine.submit_request(req)
        with self._hedge_lock:
            self._submitted += 1
        delay = self._hedge_delay_s
        if self._hedger is not None and delay and len(self._replicas) > 1:
            self._hedger.schedule(req, rep.index, delay)
        return fut

    def run(self, *inputs, deadline_ms=None, timeout=None, priority=None):
        return self.submit(*inputs, deadline_ms=deadline_ms,
                           priority=priority).result(timeout)

    # -- hedging -----------------------------------------------------------

    def _maybe_hedge(self, req, primary_index):
        """Hedge timer fired: if the request is still unresolved and the
        budget allows, re-dispatch it to a different healthy replica and
        let the first resolution win."""
        if req.future.done():
            return
        with self._hedge_lock:
            if self._hedged >= self.hedge_budget * self._submitted:
                return
            self._hedged += 1
        try:
            rep = self._pick_replica(exclude=(primary_index,))
        except NoHealthyReplicaError:
            with self._hedge_lock:
                self._hedged -= 1   # unfired: give the budget back
            return
        from .batcher import Request
        ptr = req.trace
        shadow = Request(req.inputs, req.n, req.signature,
                         deadline=req.deadline, priority=req.priority,
                         seq_real=req.seq_real, seq_padded=req.seq_padded,
                         # the shadow rides the SAME trace context as a
                         # hedge attempt: whichever resolution wins the
                         # shared done-latch emits the one record
                         trace=(None if ptr is None else
                                ptr.ctx.attempt("hedge", rep.index)))
        if ptr is not None:
            ptr.hop("hedge", replica=rep.index)
        metrics.record_hedge(replica=rep.index)

        def _on_shadow_done(sf, _req=req, _idx=rep.index):
            if sf.cancelled() or sf.exception() is not None:
                return          # primary still owns the future
            try:
                _req.future.set_result(sf.result())
            except concurrent.futures.InvalidStateError:
                return          # primary won the race
            with self._hedge_lock:
                self._hedge_wins += 1
            metrics.record_hedge_win(replica=_idx)

        shadow.future.add_done_callback(_on_shadow_done)
        try:
            rep.engine.submit_request(shadow)
        except ShedError:
            with self._hedge_lock:
                self._hedged -= 1   # shadow shed at admission: not a hedge
        except RuntimeError:
            pass                    # replica closed under us

    def _refresh_hedge_delay(self, p99_ms):
        """Supervisor tick: re-derive the auto hedge delay from the live
        p99 (a hedge should fire only for genuine stragglers)."""
        if self._hedge_fixed is not None:
            return
        if p99_ms:
            self._hedge_delay_s = max(MIN_HEDGE_S, float(p99_ms) / 1e3)

    # -- failover / drain / restart (supervisor verdicts) ------------------

    def _migrate(self, replica, hop, reason=""):
        """Move a replica's queued and in-flight requests to healthy
        peers (the shared spine under failover AND graceful drain). The
        in-flight group is *disowned* first, so even if the source
        dispatch eventually completes, whichever resolution lands first
        wins and the other is swallowed — exactly once, either way.
        Decode requests regenerate bit-identically on the adopting
        replica (counter-based sampling — see ``disown_inflight``)."""
        moved = self._disown(replica)
        moved += replica.engine.steal_pending()
        moved = [r for r in moved if not r.future.done()]
        if not moved:
            return 0
        for r in moved:
            tr = getattr(r, "trace", None)
            if tr is not None:
                tr.hop(hop, replica=replica.index, reason=reason)
        try:
            target = self._pick_replica(exclude=(replica.index,))
        except NoHealthyReplicaError as e:
            for r in moved:
                r.resolve_exception(e)
            return len(moved)
        target.engine.requeue(moved)
        return len(moved)

    def _disown(self, replica):
        """Seam: how in-flight work leaves a replica during migration.
        The disaggregated decode pool overrides this to carry each
        sequence's KV segment along (``disown_inflight(export_kv=True)``)
        so a drained sequence resumes mid-stream instead of
        re-prefilling."""
        return replica.engine.disown_inflight()

    def _failover(self, replica, reason=""):
        """Move a tripped replica's work to healthy peers and count it."""
        moved = self._migrate(replica, "failover", reason)
        if moved:
            with self._hedge_lock:
                self._failovers += 1
            metrics.record_failover(replica.index, moved)
        return moved

    # -- graceful drain (preemption / rolling swap) ------------------------

    def _record_lifecycle(self, event, **fields):
        global _LAST_LIFECYCLE
        entry = {"event": event, "t": time.time(), **fields}
        self._lifecycle = entry
        _LAST_LIFECYCLE = entry
        metrics.record_lifecycle(event, **fields)

    def _resolve_replica(self, replica):
        if isinstance(replica, _Replica):
            return replica
        return self._replicas[int(replica)]

    def _has_peer(self, exclude_index):
        """Is there anywhere for migrated work to land?"""
        return any(r.active and not r.draining
                   and r.breaker.state != "open"
                   and r.index != exclude_index for r in self._replicas)

    def drain_replica(self, replica, reason="preempt"):
        """Preemption notice for ONE replica: stop admitting, migrate
        its queued and in-flight work to healthy peers (zero lost
        requests — streams regenerate bit-identically). With no healthy
        peer the replica keeps its work and finishes it while refusing
        new admissions. Returns the number of requests migrated."""
        r = self._resolve_replica(replica)
        if r.draining:
            return 0
        r.draining = True
        moved = self._migrate(r, "drain", reason) \
            if self._has_peer(r.index) else 0
        self._record_lifecycle("drain", replica=r.index, reason=reason,
                               moved=moved)
        return moved

    def undrain_replica(self, replica, reason=""):
        """Readmit a drained replica into the rotation."""
        r = self._resolve_replica(replica)
        if not r.draining:
            return
        r.draining = False
        self._record_lifecycle("undrain", replica=r.index, reason=reason)

    def drain_fleet(self, reason="preempt"):
        """Process-level preemption notice (SIGTERM): EVERY replica
        stops admitting new work; queued and in-flight requests run to
        completion in place (there is no healthy peer to migrate to —
        the whole process is going away). Subsequent submits shed with
        :class:`NoHealthyReplicaError`. Poll :meth:`drained` / block on
        :meth:`drain_wait` before exiting."""
        flipped = [r.index for r in self._replicas if not r.draining]
        for r in self._replicas:
            r.draining = True
        self._record_lifecycle("drain_fleet", reason=reason,
                               replicas=len(flipped))
        return len(flipped)

    def drained(self, now=None):
        """True when no replica holds queued or in-flight work."""
        for r in self._replicas:
            h = r.engine.heartbeat(now)
            if h["queue_depth"] or h.get("active"):
                return False
        return True

    def drain_wait(self, timeout_s=10.0, poll_s=0.01):
        """Block until :meth:`drained` (or timeout); returns the final
        drained verdict."""
        deadline = time.monotonic() + float(timeout_s)
        while not self.drained():
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)
        return True

    # -- live weight hot-swap ----------------------------------------------

    def _replica_empty(self, r, timeout_s, poll_s=0.005):
        """Wait until one replica holds no queued or in-flight work."""
        deadline = time.monotonic() + float(timeout_s)
        while True:
            h = r.engine.heartbeat()
            if not h["queue_depth"] and not h.get("active") \
                    and h["inflight_age_s"] is None:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)

    def _resolve_swap_source(self, source, step):
        """Turn a swap source into a host state tree.

        ``source`` is a live pytree (served as-is), a sharded checkpoint
        directory path, or a ``CheckpointManager`` (+ ``step``) whose
        published step directory is resolved. Directory sources must
        pass the full quorum :func:`io.sharded.validate` — a corrupt
        publish is quarantined (``<dir>.corrupt``), counted
        (``serving.lifecycle.swap_refused``) and never swaps in."""
        import os
        from ..io import sharded as _sharded
        dirname = None
        if hasattr(source, "_sharded_path"):
            if step is None:
                raise ValueError(
                    "swap_weights(CheckpointManager) needs step=")
            dirname = source._sharded_path(step)
        elif isinstance(source, (str, os.PathLike)):
            dirname = os.fspath(source)
        if dirname is None:
            return source     # a live tree
        # the publish-corruption fault garbles one committed shard just
        # before the swap reads it — quorum validation must catch it
        spec = _faults.fire("publish_corrupt", None) \
            if _faults.enabled() else None
        if spec is not None:
            shards = sorted(f for f in os.listdir(dirname)
                            if f.endswith(".npy"))
            if shards:
                _faults.garble_file(os.path.join(dirname, shards[0]))
        ok, why = _sharded.validate(dirname)
        if not ok:
            quarantine = dirname + ".corrupt"
            try:
                os.replace(dirname, quarantine)
            except OSError:
                quarantine = None
            self._record_lifecycle("swap_refused", source=dirname,
                                   why=why, quarantined=quarantine)
            raise ValueError(
                f"swap_weights: publish {dirname} failed quorum "
                f"validation ({why}); quarantined, serving version "
                f"{self.weights_version} unchanged")
        state, _manifest = _sharded.load_state(dirname, verify=False)
        # a CheckpointManager publish wraps the tree ({"step":…,
        # "model": …}); unwrap to the served payload
        if isinstance(state, dict) and "model" in state:
            state = state["model"]
        return state

    def _check_swap_shapes(self, new_tree):
        """Same-shape contract: the swap must not mint executables, so
        treedef and every leaf's (shape, dtype) must match the serving
        template."""
        import jax
        import numpy as np
        old_leaves, old_def = jax.tree_util.tree_flatten(
            self.predictor.state)
        new_leaves, new_def = jax.tree_util.tree_flatten(new_tree)
        if old_def != new_def:
            return f"tree structure mismatch: {new_def} != {old_def}"
        for i, (a, b) in enumerate(zip(old_leaves, new_leaves)):
            sa, sb = np.shape(a), np.shape(b)
            if sa != sb:
                return f"leaf {i} shape mismatch: {sb} != {sa}"
        return None

    def swap_weights(self, source, step=None, version=None, probe=True,
                     drain_timeout_s=10.0, probe_timeout_s=2.0):
        """Roll new weights through the live fleet, one replica at a
        time, without dropping a request or minting an executable.

        Per replica: drain-lite (stop admitting; migrate its queued +
        in-flight work to peers when any exist, else let it finish in
        place), ``device_put`` the new state onto its device, half-open
        style :meth:`~ServingEngine.probe` with the fresh weights, then
        readmit. State rides the executables as an *argument* (the
        state-as-argument jit contract), so a same-shape swap reuses
        every compiled executable — ``executables()`` before and after
        must agree.

        ``source``: a live state pytree, a sharded checkpoint directory,
        or a ``CheckpointManager`` with ``step=`` — directory sources
        must pass quorum validation (see :meth:`_resolve_swap_source`).
        ``version`` defaults to ``weights_version + 1``. On a probe
        failure the whole roll is unwound — the failing replica AND
        every already-swapped replica get their old state back — so the
        fleet is never left serving mixed weights. Returns the new
        version."""
        import jax
        with self._swap_lock:
            state = self._resolve_swap_source(source, step)
            why = self._check_swap_shapes(state)
            if why is not None:
                self._record_lifecycle("swap_refused", why=why)
                raise ValueError(f"swap_weights: {why}")
            new_version = (int(version) if version is not None
                           else self.weights_version + 1)
            swapped = []   # (replica, old_state) — rollback ledger
            for r in self._replicas:
                was_draining = r.draining
                r.draining = True
                try:
                    if self._has_peer(r.index):
                        self._migrate(r, "swap", reason="hot_swap")
                    self._replica_empty(r, drain_timeout_s)
                    old_state = r.predictor.state
                    r.predictor.state = jax.device_put(state, r.device)
                    if probe:
                        ok = r.engine.probe(timeout_s=probe_timeout_s)
                        # None = never served, nothing to replay: pass
                        if ok is False:
                            # unwind the WHOLE roll: a half-swapped
                            # fleet serving mixed weights breaks the
                            # bit-reproducibility contract
                            r.predictor.state = old_state
                            for rb, rb_old in swapped:
                                rb.predictor.state = rb_old
                                rb.engine.weights_version = \
                                    self.weights_version
                            self._record_lifecycle(
                                "swap_failed", replica=r.index,
                                version=new_version,
                                rolled_back=[x.index for x, _ in swapped])
                            raise RuntimeError(
                                f"swap_weights: probe failed on replica "
                                f"{r.index} with version {new_version}; "
                                f"the roll was unwound and the fleet "
                                f"keeps serving version "
                                f"{self.weights_version}")
                    r.engine.weights_version = new_version
                    swapped.append((r, old_state))
                finally:
                    r.draining = was_draining
            # the template feeds _restart/_replicate: future rebuilds
            # must come up on the new version
            self.predictor.state = state
            self.weights_version = new_version
            metrics.record_weights_version(new_version)
            self._record_lifecycle(
                "swap", version=new_version,
                source=("tree" if not isinstance(source, (str,))
                        and not hasattr(source, "_sharded_path")
                        else "checkpoint"),
                replicas=len(swapped))
            return new_version

    def _restart(self, replica):
        """Re-``replicate()`` state onto the replica's device, swap in a
        fresh engine (warmed with the remembered signatures), and close
        the old one in the background with a bounded join — its drain
        thread may be wedged forever."""
        old_engine = replica.engine
        fresh_pred = self._replicate(self.predictor, [replica.device])[0]
        fresh = self._make_replica(replica.index, fresh_pred)
        # keep the ORIGINAL breaker (state + flap history): the restarted
        # engine stays open until a probe or budgeted request closes it
        def _outcome(ok, exc, _b=replica.breaker):
            if ok:
                _b.record_success()
            else:
                _b.record_failure(repr(exc))
        fresh.engine.on_outcome = _outcome
        if self._warm_sigs:
            try:
                fresh.engine.warmup(*self._warm_sigs)
            except Exception:   # noqa: BLE001 - warm lazily instead
                pass
        fresh.engine.start()
        replica.predictor = fresh.predictor
        replica.engine = fresh.engine
        replica.restarts += 1
        replica.restart_token = None
        # drop the dead engine's per-replica gauges: the next sampler
        # tick re-mints them from the live breaker, so a stale "open"
        # from before the restart can't linger in rollups
        metrics.clear_replica_series(replica.index)
        metrics.record_replica_restart(replica.index)
        threading.Thread(
            target=lambda: old_engine.close(drain=False, timeout=1.0),
            name="paddle_tpu-serving-reap", daemon=True).start()

    # -- scaling (supervisor verdicts) -------------------------------------

    def _active_count(self):
        return sum(1 for r in self._replicas if r.active)

    def _activate_one(self):
        for r in self._replicas:
            if not r.active and not r.draining:
                r.active = True
                metrics.record_active_replicas(self._active_count())
                return r
        return None

    def _deactivate_one(self):
        if self._active_count() <= self.min_replicas:
            return None
        for r in reversed(self._replicas):
            if r.active and not r.draining:
                r.active = False
                # drain its queue onto the survivors
                moved = [q for q in r.engine.steal_pending()
                         if not q.future.done()]
                if moved:
                    try:
                        self._pick_replica(
                            exclude=(r.index,)).engine.requeue(moved)
                    except NoHealthyReplicaError:
                        r.engine.requeue(moved)   # undo: keep serving
                        r.active = True
                        return None
                metrics.record_active_replicas(self._active_count())
                return r
        return None

    # -- fleet lifecycle ---------------------------------------------------

    def warmup(self, *signatures):
        """Warm every replica (each compiles its own device-committed
        executables); the signatures are remembered so a restarted
        replica re-warms before taking traffic. Returns total fresh
        executables."""
        self._warm_sigs = signatures
        return sum(r.engine.warmup(*signatures) for r in self._replicas)

    def start(self):
        for r in self._replicas:
            r.engine.start()

    def close(self, drain=True, timeout=None):
        if self.supervisor is not None:
            self.supervisor.stop()
        if self._hedger is not None:
            self._hedger.stop()
        _preempt.unsubscribe(self._preempt_cb)
        _ACTIVE.discard(self)
        for r in self._replicas:
            # a hung replica must not hold close() hostage: bound the
            # join (its stranded futures fail rather than strand)
            t = timeout
            if t is None and drain:
                t = 10.0
            r.engine.close(drain=drain, timeout=t)
            # closed replicas leave no stale per-replica gauges behind
            metrics.clear_replica_series(r.index)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()

    # -- observability -----------------------------------------------------

    def stats(self):
        """Aggregate across replicas, with the per-replica breakdown
        under ``"replicas"`` and the resilience tallies alongside."""
        per = [r.engine.stats() for r in self._replicas]
        agg = {k: sum(s[k] for s in per)
               for k in per[0] if isinstance(per[0][k], (int, float))}
        agg["replicas"] = per
        agg["devices"] = [str(r.device) for r in self._replicas]
        with self._hedge_lock:
            agg["hedged"] = self._hedged
            agg["hedge_wins"] = self._hedge_wins
            agg["failovers"] = self._failovers
        agg["restarts"] = sum(r.restarts for r in self._replicas)
        agg["active_replicas"] = self._active_count()
        agg["draining_replicas"] = sum(
            1 for r in self._replicas if r.draining)
        agg["weights_version"] = self.weights_version
        agg["breakers"] = {r.index: r.state for r in self._replicas}
        return agg

    def health(self, now=None):
        """The /healthz ``serving`` block: per-replica routing state
        (``state`` is the breaker state, or ``draining`` — a healthy
        replica refusing admission is NOT unhealthy) and heartbeat
        ages, plus ``all_open`` (no replica can take traffic → the
        endpoint answers 503; a fully draining fleet reads all_open
        because it really is refusing traffic)."""
        now = time.monotonic() if now is None else now
        reps = []
        any_admitting = False
        for r in self._replicas:
            h = r.engine.heartbeat(now)
            if r.active and not r.draining and r.breaker.state != "open":
                any_admitting = True
            reps.append({
                "replica": r.index,
                "device": str(r.device),
                "state": r.state,
                "breaker": r.breaker.state,
                "draining": bool(r.draining),
                "active": bool(r.active),
                "queue_depth": h["queue_depth"],
                "inflight": h.get("active", 0),
                "inflight_age_s": None if h["inflight_age_s"] is None
                else round(h["inflight_age_s"], 3),
                "heartbeat_age_s": round(h["last_ok_age_s"], 3),
                "restarts": r.restarts,
            })
        out = {"replicas": reps, "all_open": not any_admitting,
               "active_replicas": self._active_count(),
               "weights_version": self.weights_version}
        if self._lifecycle is not None:
            out["last_lifecycle"] = self._lifecycle
        if self.supervisor is not None:
            out["supervisor"] = self.supervisor.last_decision()
        return out


def health():
    """Health blocks for every live MultiDeviceEngine (what
    ``monitor.export.health_payload`` embeds under ``serving``)."""
    return [eng.health() for eng in list(_ACTIVE)]


def publish_gauges():
    """Sampler tick: republish per-replica breaker state and the active
    count (transitions set the gauges too, but a tick keeps the
    open→half_open cooldown promotion visible without traffic)."""
    from .. import monitor as _monitor
    if not _monitor.enabled():
        return
    for eng in list(_ACTIVE):
        metrics.record_active_replicas(eng._active_count())
        metrics.record_weights_version(eng.weights_version)
        for r in eng._replicas:
            _monitor.gauge(f"serving.breaker_state.{r.index}").set(
                metrics._BREAKER_STATE_NUM.get(r.state, -1))
