"""paddle_tpu.serving.breaker — per-replica circuit breaking.

A replica that keeps failing (device error, poisoned state, hung
runtime) must stop receiving traffic *before* callers notice: every
request routed at a dead replica is a blown SLA the healthy replicas
could have served. The breaker is the standard three-state machine,
kept deliberately boring:

* **closed** — healthy; every request allowed. ``failure_threshold``
  *consecutive* failures (errors or supervision timeouts) trip it open.
* **open** — no traffic at all for ``cooldown_s``; the replica gets
  time to recover (a transient hang clears, the supervisor restarts
  it) without burning live requests as probes.
* **half_open** — after the cooldown, up to ``half_open_probes``
  requests are allowed through as budgeted test traffic (the
  supervisor's active probe uses the same budget). One success closes
  the breaker; one failure re-opens it and restarts the cooldown.

State is exported as ``serving.breaker_state.<name>`` (0 = closed,
1 = half_open, 2 = open) plus a ``serving.breaker_open`` /
``serving.breaker_closed`` transition counter pair, so a dashboard
shows both where the fleet is *now* and how often it flaps.

The clock is injectable (the :class:`~paddle_tpu.resilience.deadline.
Deadline` convention) so tests replay exact open→half-open schedules
without sleeping.
"""
from __future__ import annotations

import threading
import time

from . import metrics

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """See module docstring. Thread-safe; every transition is recorded
    through :func:`serving.metrics.record_breaker_transition`."""

    def __init__(self, name="", failure_threshold=3, cooldown_s=5.0,
                 half_open_probes=1, clock=time.monotonic):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        self.name = str(name)
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.half_open_probes = max(1, int(half_open_probes))
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = None
        self._probes_inflight = 0
        self.open_count = 0       # lifetime open transitions (flap gauge)

    # -- state ------------------------------------------------------------

    def _promote_locked(self):
        """open → half_open once the cooldown has elapsed."""
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.cooldown_s:
            self._transition_locked(HALF_OPEN, "cooldown")

    def _transition_locked(self, new, reason):
        old, self._state = self._state, new
        if new == OPEN:
            self._opened_at = self._clock()
            self.open_count += 1
        if new in (OPEN, CLOSED):
            self._probes_inflight = 0
        if new == CLOSED:
            self._consecutive = 0
        if old != new:
            metrics.record_breaker_transition(self.name, old, new, reason)

    @property
    def state(self):
        """Live state (reading it applies the open→half_open cooldown
        promotion, so pollers see ``half_open`` the moment it's due)."""
        with self._lock:
            self._promote_locked()
            return self._state

    # -- routing ----------------------------------------------------------

    def allow(self):
        """May one request be routed to this replica right now? In
        half_open this *consumes* one probe slot from the budget."""
        with self._lock:
            self._promote_locked()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and \
                    self._probes_inflight < self.half_open_probes:
                self._probes_inflight += 1
                return True
            return False

    # -- outcomes ---------------------------------------------------------

    def record_success(self):
        with self._lock:
            self._promote_locked()
            if self._state == HALF_OPEN:
                self._transition_locked(CLOSED, "probe_ok")
            self._consecutive = 0

    def record_failure(self, reason=""):
        with self._lock:
            self._promote_locked()
            self._consecutive += 1
            if self._state == HALF_OPEN:
                self._transition_locked(OPEN, reason or "probe_failed")
            elif self._state == CLOSED and \
                    self._consecutive >= self.failure_threshold:
                self._transition_locked(OPEN, reason or "threshold")

    def trip(self, reason=""):
        """Force open immediately (the supervisor's verdict on a hung
        replica — a timeout is not a vote, it's a diagnosis)."""
        with self._lock:
            if self._state != OPEN:
                self._transition_locked(OPEN, reason or "tripped")

    def __repr__(self):
        return (f"CircuitBreaker({self.name!r}, state={self.state}, "
                f"consecutive={self._consecutive})")
