"""paddle_tpu.serving.engine — a Predictor as an online endpoint.

``ServingEngine`` composes the pieces: the batcher decides *when* a
coalesced group flushes (``max_batch`` rows or ``timeout_ms``,
whichever first); the engine decides *how* — concatenate the group's
inputs along the batch axis, pad to the next ``io.bucketing`` bucket
(repeat-mode, so pad rows stay in-distribution), run the wrapped
``Predictor`` on a pre-compiled bucket shape, slice every request's
rows back out, and resolve its future with host numpy outputs
(bit-identical to what ``Predictor.run`` on the lone request returns).

:meth:`warmup` AOT-compiles every (bucket, signature) pair up front via
``Predictor.warmup`` — ``lower().compile()`` over ShapeDtypeStructs,
the ``Executor.warmup`` discipline — so steady-state traffic performs
**zero** compiles (asserted by ``scripts/serving_smoke.py`` via the
``serving.compiles`` counter).

Failure semantics ride ``admission.py``: transient batch failures are
retried under the ``RetryPolicy``; terminal ones re-run the group
request-by-request so a poisoned request fails only its own future.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from .. import monitor as _monitor
from ..io.bucketing import next_bucket, pad_to_bucket, split_rows, unpad
from ..resilience import faults as _faults
from ..tensor import Tensor
from .admission import AdmissionController, resolve_priority
from .batcher import DynamicBatcher, Request
from . import metrics
from . import reqtrace

# host-side feed canonicalization, matching Executor's (and jax's
# x64-disabled) convention so a float64 submit and the float32 warmup
# signature share one executable
_CANON = {np.dtype("float64"): np.dtype("float32"),
          np.dtype("int64"): np.dtype("int32"),
          np.dtype("uint64"): np.dtype("uint32"),
          np.dtype("complex128"): np.dtype("complex64")}


def _as_host_array(x):
    if isinstance(x, Tensor):
        x = x.data
    a = np.asarray(x)
    tgt = _CANON.get(a.dtype)
    return a.astype(tgt) if tgt is not None else a


class ServingEngine:
    """Dynamic-batching online inference over one ``Predictor``.

    Parameters
    ----------
    predictor : inference.Predictor (already precision-converted)
    buckets : batch-size bucket set; default powers of two up to
        ``max_batch``. Always normalized to include ``max_batch`` and
        exclude anything above it, so every flush lands on a warmable
        shape.
    max_batch : row cap per coalesced batch (also the largest single
        request accepted).
    timeout_ms : max time the oldest queued request waits before a
        partial batch flushes.
    queue_depth : admission bound — submits past it fast-reject with
        ``QueueFullError``.
    deadline_ms : default per-request SLA (None = no deadline unless
        the submit carries one).
    retry_policy : ``resilience.retry.RetryPolicy`` classifying batch
        failures.
    start : launch the drain thread now (False = tests drive it
        manually via ``.start()``).
    metrics_port : also start ``monitor.serve(port=metrics_port)`` —
        the live /metrics + /healthz + /snapshot endpoint (0 picks an
        ephemeral port; ``monitor.export.port()`` tells you which).
        The server is process-global and outlives this engine;
        ``monitor.disable()`` tears it down.
    """

    def __init__(self, predictor, buckets=None, max_batch=32,
                 timeout_ms=5.0, queue_depth=256, deadline_ms=None,
                 retry_policy=None, start=True, metrics_port=None,
                 replica_id=None, on_outcome=None, shed=True,
                 slo_goodput_floor=0.90, seq_buckets=None):
        self.predictor = predictor
        # sequence-length buckets for ragged prompts: inputs with a
        # second (sequence) axis are padded up to the next bucket
        # BEFORE the coalescing signature is computed, so prompts of
        # length 7/12/15 all group as one bucket-16 signature instead
        # of fragmenting into per-length single-request batches. The
        # model must treat pad positions as inert (causal attention or
        # an explicit length mask — see docs/serving.md); per-request
        # outputs are sliced back to the real length at scatter.
        self.seq_buckets = (tuple(sorted({int(b) for b in seq_buckets}))
                            if seq_buckets else None)
        # identity inside a MultiDeviceEngine fleet (fault targeting,
        # breaker gauges); None for a standalone engine
        self.replica_id = replica_id
        # served weights version: bumped by the fleet's rolling
        # hot-swap and stamped into every request's reqtrace record
        self.weights_version = 0
        # breaker feedback: called with (ok: bool, exc|None) after each
        # batch execution attempt settles
        self.on_outcome = on_outcome
        self.max_batch = int(max_batch)
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if buckets:
            bs = {int(b) for b in buckets if int(b) <= self.max_batch}
        else:
            bs, b = set(), 1
            while b < self.max_batch:
                bs.add(b)
                b <<= 1
        bs.add(self.max_batch)
        self.buckets = sorted(bs)
        self.admission = AdmissionController(
            max_queue_depth=queue_depth,
            default_deadline_ms=deadline_ms,
            retry_policy=retry_policy, shed=shed,
            slo_goodput_floor=slo_goodput_floor)
        self.admission.on_event = self._admission_event
        self._batcher = DynamicBatcher(
            self._process, self.admission,
            max_batch=self.max_batch, timeout_ms=timeout_ms)
        self._stats_lock = threading.Lock()
        self._stats = {"submitted": 0, "completed": 0, "failed": 0,
                       "rejected": 0, "expired": 0, "shed": 0,
                       "batches": 0, "coalesced_rows": 0,
                       "padded_rows": 0, "compiles": 0, "retries": 0,
                       "isolated": 0}
        # a 1-row copy of the first submit's inputs: the supervisor's
        # half-open probe replays it as budgeted test traffic
        self._probe_template = None
        self._last_ok_t = time.monotonic()
        # live-telemetry wiring: the sampler republishes this engine's
        # queue depth each tick (a gauge set only at enqueue/dequeue
        # edges goes stale the moment traffic stops), weakly so an
        # un-closed engine can still be collected
        import weakref
        from ..monitor import sampler as _sampler
        ref = weakref.ref(self)

        def _depth_series():
            eng = ref()
            if eng is None:
                return None  # provider dies with the engine
            return {"serving.queue_depth": eng._batcher.depth()}

        self._sampler_key = _sampler.register_provider(
            f"serving-engine-{id(self)}", _depth_series)
        if metrics_port is not None:
            # serve-while-serving: expose /metrics + /healthz for the
            # lifetime of the process (monitor.disable() tears it down)
            _monitor.serve(port=metrics_port)
        if start:
            self.start()

    # -- client surface ---------------------------------------------------

    def make_request(self, inputs, deadline_ms=None, priority=None,
                     trace=None):
        """Validate + canonicalize one submit's inputs into a
        ``Request`` (not yet enqueued — ``MultiDeviceEngine`` builds
        the request once, then picks which replica's
        :meth:`submit_request` gets it). Raises ``ValueError`` on
        malformed inputs. ``trace=`` carries an existing
        ``reqtrace.RequestTrace`` across a shed-then-retry resubmit so
        the retry stays the SAME logical request (one terminal record,
        backoff blamed as ``shed_retry_ms``)."""
        if not inputs:
            raise ValueError("submit() needs at least one input array")
        arrays = tuple(_as_host_array(x) for x in inputs)
        if any(a.ndim < 1 for a in arrays):
            raise ValueError(
                "serving inputs need a leading batch dimension")
        n = arrays[0].shape[0]
        if any(a.shape[0] != n for a in arrays):
            raise ValueError(
                f"inconsistent leading dims: "
                f"{[a.shape[0] for a in arrays]}")
        if n < 1:
            raise ValueError("empty request (0 rows)")
        if n > self.max_batch:
            raise ValueError(
                f"request of {n} rows exceeds max_batch={self.max_batch}"
                f" — split it client-side")
        from ..resilience.deadline import Deadline
        deadline = (Deadline.after_ms(deadline_ms)
                    if deadline_ms is not None else None)
        seq_real = seq_padded = None
        if self.seq_buckets:
            # pad the sequence axis to its bucket BEFORE the signature:
            # this is what lets ragged prompts coalesce into one
            # executable signature (repeat-mode pad — rows stay
            # in-distribution, causal/masked models ignore them)
            padded, pads = [], set()
            for a in arrays:
                if a.ndim >= 2 and a.shape[1] > 0:
                    seq_n = a.shape[1]
                    target = next_bucket(seq_n, self.seq_buckets)
                    if target != seq_n:
                        a = pad_to_bucket(a, target, axis=1)
                    pads.add((seq_n, target))
                padded.append(a)
            arrays = tuple(padded)
            if len(pads) == 1:
                (seq_real, seq_padded), = pads
        sig = tuple((a.shape[1:], str(a.dtype)) for a in arrays)
        prio = resolve_priority(priority)
        return Request(arrays, n, sig, deadline=deadline,
                       priority=prio,
                       seq_real=seq_real, seq_padded=seq_padded,
                       trace=reqtrace.attach(trace, kind="serve",
                                             priority=prio,
                                             replica=self.replica_id,
                                             version=self.weights_version))

    def submit_request(self, req):
        """Enqueue an already-built ``Request``; returns its future.
        Raises ``ShedError`` / ``QueueFullError`` from admission."""
        if self._probe_template is None:
            self._probe_template = tuple(a[:1].copy() for a in req.inputs)
        with _monitor.trace.span("serving.enqueue", rows=req.n):
            fut = self._batcher.submit(req)
            if req.trace is not None:
                req.trace.hop("enqueue", replica=self.replica_id)
                reqtrace.flow_mark(req.trace)
        with self._stats_lock:
            self._stats["submitted"] += 1
        return fut

    def submit(self, *inputs, deadline_ms=None, priority=None,
               trace=None):
        """Enqueue one request (each input shaped ``(n, ...)``, all with
        the same leading ``n <= max_batch``); returns a
        ``concurrent.futures.Future`` resolving to what
        ``Predictor.run`` on the same inputs returns. ``priority`` is
        'high'/'normal'/'low' (default 'normal') — under overload the
        admission ladder sheds low classes first. Raises ``ShedError``
        / ``QueueFullError`` under overload, ``ValueError`` on
        malformed inputs. A caller retrying after a shed passes the
        shed request's ``trace`` back so the retry is attributed to the
        same logical request."""
        return self.submit_request(self.make_request(
            inputs, deadline_ms=deadline_ms, priority=priority,
            trace=trace))

    def run(self, *inputs, deadline_ms=None, timeout=None, priority=None):
        """Blocking submit: enqueue, wait, return the outputs (or raise
        what the request's future raised)."""
        return self.submit(*inputs, deadline_ms=deadline_ms,
                           priority=priority).result(timeout)

    def warmup(self, *signatures):
        """AOT-compile every (bucket, signature) pair. Each signature is
        a list of per-input ``(example_shape, dtype)`` pairs — the shape
        WITHOUT the batch dim, e.g. ``[((16,), "float32")]`` for a
        single ``(n, 16)`` float input. Returns the number of
        executables compiled."""
        before = len(self.predictor._compiled)
        with _monitor.trace.span("serving.warmup",
                                 buckets=len(self.buckets)):
            for sig in signatures:
                norm = []
                for item in sig:
                    if hasattr(item, "shape") and hasattr(item, "dtype"):
                        norm.append((tuple(item.shape), item.dtype))
                    else:
                        shape, dtype = item
                        norm.append((tuple(shape), dtype))
                for b in self.buckets:
                    self.predictor.warmup(
                        [((b,) + shape, dtype) for shape, dtype in norm])
                if self._probe_template is None and norm:
                    # a freshly (re)started replica has served nothing:
                    # synthesize probe input from the warmup signature so
                    # the supervisor can still test it back to health
                    self._probe_template = tuple(
                        np.zeros((1,) + shape, dtype=dtype)
                        for shape, dtype in norm)
        fresh = len(self.predictor._compiled) - before
        if fresh:
            metrics.record_compiles(fresh)
            with self._stats_lock:
                self._stats["compiles"] += fresh
        return fresh

    def start(self):
        self._batcher.start()

    def close(self, drain=True, timeout=None):
        self._batcher.close(drain=drain, timeout=timeout)
        from ..monitor import sampler as _sampler
        _sampler.unregister_provider(self._sampler_key)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()

    # -- supervision surface ----------------------------------------------

    def heartbeat(self, now=None):
        """Liveness signals for the ``ServingSupervisor``: queue depth,
        whether a batch is currently dispatched and for how long, time
        since the drain thread last made progress, and time since the
        last successful batch."""
        now = time.monotonic() if now is None else now
        age = self._batcher.inflight_age(now)
        return {
            "queue_depth": self._batcher.depth(),
            "inflight_age_s": age,
            "inflight_token": self._batcher.inflight_token(),
            "last_progress_age_s": self._batcher.last_progress_age(now),
            "last_ok_age_s": now - self._last_ok_t,
            # in-flight request count — what a drain waits to hit zero
            "active": 0 if age is None else 1,
        }

    def probe(self, timeout_s=1.0):
        """Half-open test traffic: replay a 1-row copy of real input
        through the full assemble→execute path on a side thread (the
        drain thread may be wedged — that's exactly what we're probing)
        and report whether it finished in time. No future, no queue:
        the probe must not compete with, or be blocked by, real work."""
        template = self._probe_template
        if template is None:
            return None     # nothing served yet — nothing to replay
        done = threading.Event()
        err = []

        def _go():
            try:
                sig = tuple((a.shape[1:], str(a.dtype)) for a in template)
                req = Request(tuple(a.copy() for a in template), 1, sig)
                arrays, _real, _bucket = self._assemble([req])
                self._run_batch(arrays)
            except BaseException as e:  # noqa: BLE001 - probe verdict
                err.append(e)
            finally:
                done.set()

        threading.Thread(target=_go, daemon=True,
                         name="paddle_tpu-serving-probe").start()
        ok = done.wait(timeout_s) and not err
        if ok:
            self._last_ok_t = time.monotonic()
        return bool(ok)

    def steal_pending(self):
        """Failover: hand every queued request to the caller."""
        return self._batcher.steal_pending()

    def disown_inflight(self):
        """Failover: hand over the currently dispatched group."""
        return self._batcher.disown_inflight()

    def requeue(self, requests):
        """Failover: accept already-admitted requests at queue front."""
        for r in requests:
            tr = getattr(r, "trace", None)
            if tr is not None:
                # back to queue wait on the adopting replica; the
                # failover hop itself is recorded by the fleet owner
                tr.to("queue")
                tr.hop("requeue", replica=self.replica_id)
        self._batcher.requeue(requests)

    def _note_outcome(self, ok, exc=None):
        if ok:
            self._last_ok_t = time.monotonic()
        cb = self.on_outcome
        if cb is not None:
            try:
                cb(ok, exc)
            except Exception:   # noqa: BLE001 - observer must not kill
                pass            # the drain thread

    def _admission_event(self, event):
        key = {"rejected": "rejected", "expired": "expired",
               "poisoned": "failed", "shed": "shed"}.get(event)
        if key is not None:
            with self._stats_lock:
                self._stats[key] += 1

    def stats(self):
        """Engine-local accounting (independent of the monitor): every
        submitted request is completed, failed, expired or still
        queued — the smoke gate's zero-lost-futures check."""
        with self._stats_lock:
            s = dict(self._stats)
        s["queue_depth"] = self._batcher.depth()
        s["buckets"] = list(self.buckets)
        return s

    # -- batch execution (drain thread) -----------------------------------

    def _process(self, requests):
        """One coalesced same-signature group: assemble → execute (with
        retry/isolation) → scatter."""
        with self._stats_lock:
            self._stats["batches"] += 1
        with _monitor.trace.span("serving.batch_assemble",
                                 requests=len(requests)):
            # queue time ends here: the drain thread owns the group now
            reqtrace.transition(requests, "assemble", flow=True)
            arrays, real_n, bucket = self._assemble(requests)
        metrics.record_batch(real_n, bucket, len(requests))
        with self._stats_lock:
            self._stats["coalesced_rows"] += real_n
            self._stats["padded_rows"] += bucket - real_n
        outs = self._execute_with_recovery(requests, arrays)
        if outs is None:
            return      # isolation path resolved every future already
        with _monitor.trace.span("serving.scatter",
                                 requests=len(requests)):
            self._scatter(requests, outs)

    def _assemble(self, requests):
        """Concatenate the group's inputs along the batch axis and pad
        to the next bucket (repeat-mode: pad rows stay in-distribution;
        their outputs are dropped at scatter — the ``batch_mask``
        contract from io.bucketing)."""
        real_n = sum(r.n for r in requests)
        bucket = next_bucket(real_n, self.buckets)
        arrays = []
        for i in range(len(requests[0].inputs)):
            parts = [r.inputs[i] for r in requests]
            a = parts[0] if len(parts) == 1 else np.concatenate(parts,
                                                                axis=0)
            arrays.append(pad_to_bucket(a, bucket))
        return arrays, real_n, bucket

    def _run_batch(self, arrays):
        """Execute one bucket-shaped batch; returns a tuple of device
        outputs plus whether the model is multi-output. Counts fresh
        executables into ``serving.compiles`` (zero in steady state)."""
        before = len(self.predictor._compiled)
        if _faults.enabled():
            # the chaos gate's injection site: replica_error raises,
            # replica_hang/replica_slow stall right where a wedged
            # device runtime would
            _faults.maybe_serving_fault(self.replica_id)
        with _monitor.trace.span("serving.execute",
                                 rows=int(arrays[0].shape[0])):
            out = self.predictor.run_device(*arrays)
        fresh = len(self.predictor._compiled) - before
        if fresh:
            metrics.record_compiles(fresh)
            with self._stats_lock:
                self._stats["compiles"] += fresh
        multi = isinstance(out, (tuple, list))
        return (tuple(out) if multi else (out,)), multi

    def _execute_with_recovery(self, requests, arrays):
        """Transient failures retry the whole batch under the admission
        policy; terminal (or exhausted) ones fall to per-request
        isolation — one poisoned request fails its own future only."""
        policy = self.admission.retry_policy
        attempt = 0
        while True:
            try:
                reqtrace.transition(requests, "execute")
                out = self._run_batch(arrays)
                self._note_outcome(True)
                return out
            except BaseException as e:  # noqa: BLE001 - triaged below
                self._note_outcome(False, e)
                if policy.is_transient(e) \
                        and attempt + 1 < policy.max_attempts:
                    metrics.record_retry(where="serving.execute")
                    with self._stats_lock:
                        self._stats["retries"] += 1
                    with _monitor.trace.span("serving.retry_backoff",
                                             attempt=attempt + 1):
                        reqtrace.transition(requests, "retry_backoff")
                        time.sleep(policy.delay(attempt))
                    attempt += 1
                    continue
                with self._stats_lock:
                    self._stats["isolated"] += len(requests)
                self.admission.isolate(requests, self._run_one, e)
                return None

    def _run_one(self, request):
        """Isolation path: execute ONE request alone (still bucket-
        padded, so no fresh shapes are minted) and resolve its future.
        Raises to the caller (admission.isolate) if this request is the
        poison."""
        reqtrace.transition([request], "execute")
        arrays, _real, _bucket = self._assemble([request])
        outs, multi = self._run_batch(arrays)
        self._scatter([request], (outs, multi))

    def _scatter(self, requests, outs_multi):
        """Slice each request's rows back out, device→host once for the
        whole batch, resolve futures, record latency."""
        outs, multi = outs_multi
        reqtrace.transition(requests, "scatter", flow=True)
        import jax
        host = [np.asarray(jax.device_get(o)) for o in outs]
        bucket = None
        for a in host:
            if getattr(a, "ndim", 0) >= 1:
                bucket = a.shape[0]
                break
        sizes = [r.n for r in requests]
        per_out_chunks = []
        for a in host:
            if getattr(a, "ndim", 0) >= 1 and a.shape[0] == bucket:
                per_out_chunks.append(split_rows(a, sizes))
            else:
                # no batch dim (a scalar reduction): every request gets
                # the whole thing — documented in docs/serving.md
                per_out_chunks.append([a] * len(requests))
        now = time.monotonic()
        latencies, within = [], []
        for j, r in enumerate(requests):
            vals = [chunks[j] for chunks in per_out_chunks]
            if r.seq_padded is not None and r.seq_real != r.seq_padded:
                # bucket-padded sequence axis: slice outputs that kept
                # the padded length back to the request's real length
                vals = [unpad(v, r.seq_real, axis=1)
                        if getattr(v, "ndim", 0) >= 2
                        and v.shape[1] == r.seq_padded else v
                        for v in vals]
            r.resolve_result(list(vals) if multi else vals[0])
            latencies.append(r.age(now) * 1e3)
            # the slo.* goodput numerator: resolved before its SLA ran
            # out (no deadline = always within)
            within.append(r.deadline is None
                          or not r.deadline.expired(now))
        metrics.record_completed(len(requests), latencies,
                                 within_sla=within)
        with self._stats_lock:
            self._stats["completed"] += len(requests)
