"""paddle_tpu.serving.supervisor — the closed-loop self-healing brain.

`ElasticSupervisor` (resilience/elastic.py) proved the shape for
training: a loop that watches for a failure signal, shrinks the world,
and resumes. Serving needs the same loop with different verbs, running
*continuously* rather than per-crash:

* **hang detection** — a dispatch stuck inside a replica longer than
  ``inflight_timeout_s`` is declared hung: the replica's breaker trips
  (no more traffic), its queued *and* in-flight requests fail over to
  healthy peers. The verdict is keyed on the dispatch identity, so one
  hang produces exactly one failover, however many ticks observe it.
* **recovery probing** — a breaker in half_open gets one budgeted probe
  per tick (a 1-row replay of real input on a side thread, see
  ``ServingEngine.probe``); success closes the breaker and the replica
  rejoins the rotation.
* **restart** — a replica still wedged ``restart_after_s`` after its
  hang verdict gets rebuilt: state re-``replicate()``d onto the device,
  a fresh engine warmed and swapped in, the wedged one reaped in the
  background.
* **scaling** — when the live ``slo.goodput`` window sags below the
  floor — or, for decode fleets, when the rolling ``slo.tokens_per_s``
  window drops under ``tokens_floor`` — and inactive replicas exist,
  one is activated per tick; a fleet idle for ``idle_ticks_down``
  consecutive ticks gives one back (never below ``min_replicas``).

Every verdict is recorded planner-style — a ``serving.supervisor``
ledger event plus :func:`last_decision` — so ``/snapshot`` can answer
"why did the fleet change shape?" the way it answers "why did the
planner pick that mesh?".
"""
from __future__ import annotations

import threading
import time
import weakref

from . import metrics
from ..resilience import faults as _faults

#: most recent decision across all supervisors (the /snapshot block)
_LAST_DECISION = None


def last_decision():
    return _LAST_DECISION


class ServingSupervisor:
    """Control loop over one :class:`~paddle_tpu.serving.multi.
    MultiDeviceEngine`. Holds its owner weakly — a dropped engine kills
    the loop instead of the loop immortalizing the engine."""

    def __init__(self, owner, interval_s=0.25, probe_timeout_s=1.0,
                 goodput_floor=0.90, restart_after_s=None,
                 idle_ticks_down=120, scale=True, start=True,
                 tokens_floor=None, ttft_ceiling_ms=None,
                 queue_depth_ceiling=None):
        self._owner = weakref.ref(owner)
        self.interval_s = float(interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.goodput_floor = float(goodput_floor)
        # decode SLO floor: scale up while the rolling slo.tokens_per_s
        # window sits below this (None = goodput-only scaling)
        self.tokens_floor = (float(tokens_floor)
                             if tokens_floor is not None else None)
        # prefill SLO ceilings (disaggregated pools): scale up while the
        # rolling slo.ttft_p99_ms window sits ABOVE ttft_ceiling_ms, or
        # the pool's aggregate queue depth above queue_depth_ceiling —
        # TTFT is prefill's SLO the way tokens/s is decode's
        self.ttft_ceiling_ms = (float(ttft_ceiling_ms)
                                if ttft_ceiling_ms is not None else None)
        self.queue_depth_ceiling = (int(queue_depth_ceiling)
                                    if queue_depth_ceiling is not None
                                    else None)
        # default: a hung replica gets 3 supervision timeouts of grace
        # after failover before the heavyweight rebuild
        self.restart_after_s = (float(restart_after_s)
                                if restart_after_s is not None
                                else 3.0 * owner.inflight_timeout_s)
        self.idle_ticks_down = int(idle_ticks_down)
        self.scale = bool(scale)
        self._idle_ticks = 0
        self._stop = threading.Event()
        self._thread = None
        self.decisions = []     # bounded local history (snapshot block)
        self._seen_anomalies = set()  # finding names already noted
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="paddle_tpu-serving-supervisor",
            daemon=True)
        self._thread.start()

    def stop(self, timeout=2.0):
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            owner = self._owner()
            if owner is None:
                return
            try:
                self.tick(owner)
            except Exception:   # noqa: BLE001 - the loop must survive
                pass            # any single bad tick

    # -- decisions ---------------------------------------------------------

    def _decide(self, decision, **fields):
        global _LAST_DECISION
        entry = {"decision": decision, "t": time.time(), **fields}
        # cite the anomaly board: a drain/scale verdict issued while the
        # detector has findings in force carries WHICH anomaly was live
        # (the "why" an operator reads off the decision ledger)
        anomalies = self._active_anomalies()
        if anomalies and "anomalies" not in entry:
            entry["anomalies"] = anomalies
            fields = dict(fields, anomalies=anomalies)
        _LAST_DECISION = entry
        self.decisions.append(entry)
        del self.decisions[:-50]
        metrics.record_supervisor(decision, **fields)

    @staticmethod
    def _active_anomalies():
        """Names of the findings currently on the anomaly board
        (monitor/alerts.py), lazily — supervision must not drag the
        alerting plane in when nobody armed it."""
        import sys
        _alerts = sys.modules.get("paddle_tpu.monitor.alerts")
        if _alerts is None:
            return []
        try:
            return [f["name"] for f in _alerts.active_findings()]
        except Exception:
            return []

    def last_decision(self):
        return self.decisions[-1] if self.decisions else None

    # -- one control-loop step --------------------------------------------

    def tick(self, owner=None, now=None):
        """One supervision pass; callable directly by tests (pass the
        owner) or driven by the daemon loop."""
        owner = owner or self._owner()
        if owner is None:
            return
        now = time.monotonic() if now is None else now
        rollup = metrics.slo_rollup(now)
        decode = metrics.decode_rollup(now)
        owner._refresh_hedge_delay(rollup.get("p99_ms"))
        self._note_anomalies()
        busy = False
        for replica in list(owner._replicas):
            busy |= self._supervise_replica(owner, replica, now)
        if self.scale:
            self._autoscale(owner, rollup, busy, decode)

    def _note_anomalies(self):
        """A finding newly on the anomaly board becomes a first-class
        ``anomaly`` decision — the detector's verdict enters the same
        ledger as drains and scale moves, once per finding edge."""
        current = set(self._active_anomalies())
        for name in sorted(current - self._seen_anomalies):
            self._decide("anomaly", anomaly=name)
        self._seen_anomalies = current

    def _supervise_replica(self, owner, replica, now):
        hb = replica.engine.heartbeat(now)
        age = hb["inflight_age_s"]
        token = hb["inflight_token"]
        busy = bool(hb["queue_depth"]) or age is not None \
            or bool(hb.get("active"))

        # preemption notice (injected): graceful drain, not a hang —
        # the replica is healthy, the scheduler just wants it back
        if _faults.enabled() and _faults.fire(
                "preempt_replica", None, replica=replica.index) is not None:
            moved = owner.drain_replica(replica, reason="preempt_replica")
            self._decide("drain", replica=replica.index, moved=moved)
            return busy

        # a draining replica is finishing (or has migrated) its work —
        # no hang verdicts, no probes; readmission is the drain owner's
        # call (undrain / swap completion), not the supervisor's
        if replica.draining:
            return busy

        # hang: one verdict per dispatch (the token is the dispatch's
        # start time — a NEW dispatch hanging gets its own verdict)
        if age is not None and age > owner.inflight_timeout_s \
                and token != replica.handled_token:
            replica.handled_token = token
            metrics.record_replica_hung(replica.index, age)
            replica.breaker.trip("hung")
            moved = owner._failover(replica, reason="hung")
            self._decide("failover", replica=replica.index,
                         inflight_age_s=round(age, 3), moved=moved)

        # restart: the same dispatch still wedged well past the verdict
        if age is not None and age > self.restart_after_s \
                and token != replica.restart_token:
            replica.restart_token = token
            owner._restart(replica)
            self._decide("restart", replica=replica.index,
                         inflight_age_s=round(age, 3),
                         restarts=replica.restarts)
            return busy

        # recovery: one budgeted probe per tick per half-open breaker
        if replica.active and replica.breaker.state == "half_open":
            ok = replica.engine.probe(timeout_s=self.probe_timeout_s)
            if ok:
                replica.breaker.record_success()
                self._decide("reclose", replica=replica.index)
            elif ok is not None:
                replica.breaker.record_failure("probe")
        return busy

    def _autoscale(self, owner, rollup, busy, decode=None):
        goodput = rollup.get("goodput")
        submitted = rollup.get("submitted") or 0
        # request-SLO context rides on every scale verdict: "goodput
        # 0.84 at ttft_p99 310ms" is actionable where the bare ratio
        # is not (reqtrace feeds these windows)
        slo_ctx = {k: round(rollup[k], 3)
                   for k in ("ttft_p99_ms", "tpot_p99_ms")
                   if rollup.get(k) is not None}
        # speculative context: a tokens/s sag with a healthy accept
        # rate is slot starvation (scale up helps); a sag WITH a
        # collapsed accept rate is a draft/target mismatch (scale up
        # won't) — the verdict carries both so /snapshot can tell them
        # apart
        if decode:
            for k in ("accept_rate", "spec_tokens_per_step"):
                if decode.get(k) is not None:
                    slo_ctx[k] = round(decode[k], 3)
        if goodput is not None and submitted >= 20 \
                and goodput < self.goodput_floor:
            self._idle_ticks = 0
            rep = owner._activate_one()
            if rep is not None:
                self._decide("scale_up", replica=rep.index,
                             goodput=round(goodput, 4),
                             active=owner._active_count(), **slo_ctx)
            return
        # prefill SLO (disaggregated pools): TTFT p99 over the ceiling
        # or a backed-up prefill queue means prompt ingest is the
        # bottleneck — add a prefill replica. An idle window reads as
        # None, never as a breach.
        if self.ttft_ceiling_ms is not None \
                or self.queue_depth_ceiling is not None:
            ttft = rollup.get("ttft_p99_ms")
            depth = sum(r.engine.depth() for r in owner._replicas
                        if r.active and hasattr(r.engine, "depth"))
            breach_ttft = (self.ttft_ceiling_ms is not None
                           and ttft is not None
                           and ttft > self.ttft_ceiling_ms)
            breach_depth = (self.queue_depth_ceiling is not None
                            and depth > self.queue_depth_ceiling)
            if breach_ttft or breach_depth:
                self._idle_ticks = 0
                rep = owner._activate_one()
                if rep is not None:
                    self._decide(
                        "scale_up", replica=rep.index,
                        queue_depth=depth,
                        ttft_ceiling_ms=self.ttft_ceiling_ms,
                        queue_depth_ceiling=self.queue_depth_ceiling,
                        active=owner._active_count(), **slo_ctx)
                return
        # decode SLO: rolling token throughput below the floor means the
        # fleet is slot-starved — add a replica. An idle engine reads as
        # None (no decode traffic in the window), never as a breach.
        tps = decode.get("tokens_per_s") if decode else None
        if self.tokens_floor is not None and tps is not None \
                and tps < self.tokens_floor:
            self._idle_ticks = 0
            rep = owner._activate_one()
            if rep is not None:
                self._decide("scale_up", replica=rep.index,
                             tokens_per_s=round(tps, 3),
                             tokens_floor=self.tokens_floor,
                             active=owner._active_count(), **slo_ctx)
            return
        if busy or submitted:
            self._idle_ticks = 0
            return
        self._idle_ticks += 1
        if self._idle_ticks >= self.idle_ticks_down:
            self._idle_ticks = 0
            rep = owner._deactivate_one()
            if rep is not None:
                self._decide("scale_down", replica=rep.index,
                             active=owner._active_count())
