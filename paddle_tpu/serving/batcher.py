"""paddle_tpu.serving.batcher — dynamic request coalescing.

The throughput argument (PAPERS.md: Gemma-on-TPU serving; "Operator
Fusion in XLA"): a TPU earns its keep on a few large, hot, pre-compiled
executables — not thousands of single-row dispatches. The batcher is
the mechanism: callers submit ragged requests (1, 3, 7, 13 rows …) into
a bounded queue; a background thread drains it, coalesces
same-signature requests along the batch axis, and flushes when either
``max_batch`` rows accumulate or the oldest request has waited
``timeout_ms`` — whichever comes first. The engine pads the coalesced
rows up to the next ``io.bucketing`` bucket so every flush hits a
pre-compiled shape, and slices per-request outputs back out.

Queueing discipline:

* FIFO by arrival. A flush takes the oldest request's signature and
  collects its same-signature successors in order (no reordering
  within a signature; a different signature never blocks behind a
  full flush of another).
* Admission runs at enqueue (fast-reject on a full queue) and expiry
  at dequeue (an expired request is resolved with ``DeadlineExpired``
  and never counted toward a flush) — see ``admission.py``.
* Futures are resolved OUTSIDE the queue lock: a done-callback that
  immediately re-submits must not deadlock the drain thread.
"""
from __future__ import annotations

import collections
import concurrent.futures
import threading
import time

from .. import monitor as _monitor
from . import metrics


class Request:
    """One in-flight unit of work: ``n`` example rows across one or
    more input arrays, a future the caller holds, and an optional
    deadline. Created by ``ServingEngine.submit``."""

    __slots__ = ("inputs", "n", "signature", "future", "deadline",
                 "t_enqueue", "priority", "seq_real", "seq_padded",
                 "trace")

    def __init__(self, inputs, n, signature, deadline=None, priority=1,
                 seq_real=None, seq_padded=None, trace=None):
        self.inputs = inputs              # tuple of host arrays
        self.n = int(n)                   # rows along the batch axis
        self.signature = signature        # per-example (shape, dtype) tuple
        self.future = concurrent.futures.Future()
        self.deadline = deadline
        self.priority = int(priority)     # admission.PRIORITIES rank
        self.t_enqueue = time.monotonic()
        # sequence-axis bucketing (engine seq_buckets=): the real vs
        # padded length along axis 1, recorded BEFORE the signature is
        # computed so ragged prompts coalesce into one executable
        # signature; scatter slices axis 1 back to seq_real
        self.seq_real = seq_real
        self.seq_padded = seq_padded
        # reqtrace.Attempt riding the request through thread handoffs
        # (None = monitor disabled; every site checks exactly this)
        self.trace = trace

    def age(self, now=None):
        return (now if now is not None else time.monotonic()) \
            - self.t_enqueue

    # concurrent.futures raises InvalidStateError on a cancelled future;
    # a caller cancelling mid-flight must not crash the drain thread.
    # The winner of the set_* race — and ONLY the winner — finalizes the
    # request trace: a hedge shadow, a failed-over duplicate, and the
    # primary share one context, so exactly one terminal
    # ``serving.request`` record exists per logical request.
    def resolve_result(self, value):
        try:
            self.future.set_result(value)
        except concurrent.futures.InvalidStateError:
            return
        if self.trace is not None:
            self.trace.finalize("ok")

    def resolve_exception(self, exc):
        try:
            self.future.set_exception(exc)
        except concurrent.futures.InvalidStateError:
            return
        if self.trace is not None:
            from .admission import DeadlineExpired, ShedError
            outcome = ("expired" if isinstance(exc, DeadlineExpired)
                       else "shed" if isinstance(exc, ShedError)
                       else "error")
            self.trace.finalize(outcome, error=repr(exc))


class DynamicBatcher:
    """Bounded queue + drain thread. ``process(requests)`` — supplied by
    the engine — executes one coalesced, same-signature group; the
    batcher owns *when* and *what* to flush, the engine owns *how*."""

    def __init__(self, process, admission, max_batch=32, timeout_ms=5.0,
                 name="paddle_tpu-serving"):
        self._process = process
        self._admission = admission
        self.max_batch = int(max_batch)
        self.timeout_s = float(timeout_ms) / 1e3
        self._name = name
        self._queue = collections.deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._running = False     # drain thread active
        self._closed = False      # no further submits
        self._draining = False
        self._thread = None
        # the group currently inside _process (supervision + the
        # close(drain=False) no-stranded-future guarantee)
        self._inflight = []
        self._inflight_t0 = None
        self._last_progress = time.monotonic()

    # -- producer side ----------------------------------------------------

    def submit(self, request):
        """Admit + enqueue; returns the request's future. Raises
        ``QueueFullError`` synchronously when the queue is at depth.
        Valid before :meth:`start` — requests queue up for the first
        flush."""
        with self._cond:
            if self._closed:
                raise RuntimeError("serving engine is closed")
            self._admission.admit(request, len(self._queue))
            self._queue.append(request)
            depth = len(self._queue)
            self._cond.notify()
        metrics.record_submit(request.n)
        metrics.record_queue_depth(depth)
        return request.future

    def depth(self):
        with self._lock:
            return len(self._queue)

    # -- supervision hooks ------------------------------------------------

    def inflight_age(self, now=None):
        """Seconds the current in-flight group has been inside
        ``process`` (None when idle) — the supervisor's hang signal."""
        with self._lock:
            t0 = self._inflight_t0
        if t0 is None:
            return None
        return (now if now is not None else time.monotonic()) - t0

    def inflight_token(self):
        """Opaque identity of the current in-flight dispatch (None when
        idle). The supervisor keys its one-failover-per-dispatch rule on
        this so a still-hung batch isn't failed over twice."""
        with self._lock:
            return self._inflight_t0

    def last_progress_age(self, now=None):
        with self._lock:
            t = self._last_progress
        return (now if now is not None else time.monotonic()) - t

    def steal_pending(self):
        """Take every queued (not yet dispatched) request — failover
        moves them to a healthy replica without re-admission."""
        with self._lock:
            taken = list(self._queue)
            self._queue.clear()
            metrics.record_queue_depth(0)
        return taken

    def disown_inflight(self):
        """Take ownership of the currently dispatched group (failover:
        the requests will be re-run elsewhere; first resolution wins
        because Request resolution is idempotent). After this, neither
        the worker's failure path nor close() touches their futures."""
        with self._lock:
            taken = list(self._inflight)
            self._inflight = []
        return taken

    def requeue(self, requests):
        """Front-of-queue insert of already-admitted requests (failover
        re-dispatch). Bypasses admission — these requests already paid
        it on their original replica; shedding them now would turn a
        replica fault into caller-visible errors."""
        if not requests:
            return
        with self._cond:
            if self._closed:
                for r in requests:
                    r.resolve_exception(
                        RuntimeError("serving engine closed"))
                return
            for r in reversed(requests):
                self._queue.appendleft(r)
            depth = len(self._queue)
            self._cond.notify()
        metrics.record_queue_depth(depth)

    # -- lifecycle --------------------------------------------------------

    def start(self):
        with self._lock:
            if self._running or self._closed:
                return
            self._running = True
            self._draining = False
            self._thread = threading.Thread(
                target=self._worker, name=self._name, daemon=True)
            self._thread.start()

    def close(self, drain=True, timeout=None):
        """Stop accepting work and stop the drain thread. With
        ``drain=True`` (default) queued requests are flushed first;
        anything still queued afterwards (``drain=False``, or no thread
        ever started) fails with RuntimeError. If the drain thread is
        wedged inside ``process`` (a hung replica) the join times out
        and the *dispatched* group's unresolved futures fail too — a
        future is never silently lost, even when its executor never
        comes back. Disowned in-flight requests (failover took them)
        are someone else's to resolve and are left alone."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._running = False
            self._draining = bool(drain)
            self._cond.notify_all()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            # a hung process() would otherwise hold close() forever;
            # drain=False is the "replica is dead, get out" path, so it
            # always gets a bounded join
            if timeout is None and not drain:
                timeout = 5.0
            t.join(timeout)
        with self._lock:
            leftovers = list(self._queue)
            self._queue.clear()
            stranded = [r for r in self._inflight if not r.future.done()]
        for r in leftovers:
            r.resolve_exception(RuntimeError("serving engine closed"))
        for r in stranded:
            r.resolve_exception(RuntimeError(
                "serving engine closed with the request still dispatched "
                "(replica hung or died mid-batch)"))

    # -- drain thread -----------------------------------------------------

    def _worker(self):
        while True:
            expired, group, wait_s = self._pick_locked()
            for r in expired:
                self._admission.expire(r)
            if group:
                with self._lock:
                    self._inflight = group
                    self._inflight_t0 = time.monotonic()
                try:
                    with _monitor.trace.span("serving.batch",
                                             requests=len(group)):
                        self._process(group)
                except BaseException as e:  # noqa: BLE001 - to futures
                    # process() resolves its own failures; this is the
                    # belt-and-braces path for an unexpected escape, so
                    # the group can never strand. Disowned requests
                    # (failover took them mid-dispatch) are excluded —
                    # they'll resolve on their new replica.
                    with self._lock:
                        owned = list(self._inflight)
                    for r in owned:
                        r.resolve_exception(e)
                finally:
                    with self._lock:
                        self._inflight = []
                        self._inflight_t0 = None
                        self._last_progress = time.monotonic()
                continue
            with self._cond:
                if not self._running:
                    if self._queue and self._draining:
                        continue        # re-pick: drain flushes the rest
                    return
                # re-checks hold the lock, so a submit that landed after
                # _pick_locked released it is visible here — only the
                # flush-threshold race can delay, bounded by timeout_s
                if not self._queue:
                    self._cond.wait(0.1)
                elif wait_s > 0:
                    self._cond.wait(wait_s)

    def _pick_locked(self):
        """Under the lock: sweep expired requests out of the whole
        queue, then decide whether the head signature's group should
        flush now. Returns (expired, group, seconds_to_wait)."""
        with self._lock:
            now = time.monotonic()
            expired, kept = [], collections.deque()
            while self._queue:
                r = self._queue.popleft()
                if self._admission.is_expired(r, now):
                    expired.append(r)
                else:
                    kept.append(r)
            self._queue = kept
            if not self._queue:
                metrics.record_queue_depth(0)
                return expired, [], 0.0

            head = self._queue[0]
            sig = head.signature
            # overload shrinks the largest batch the picker may build
            # (admission ladder rung 2+) so service latency stays
            # bounded while the queue is deep
            cap = self._admission.effective_max_batch(
                self.max_batch, len(self._queue)) \
                if hasattr(self._admission, "effective_max_batch") \
                else self.max_batch
            cand, rows, overflow = [], 0, False
            for r in self._queue:
                if r.signature != sig:
                    continue
                # the head is always taken even if it alone exceeds a
                # shrunken cap — progress must not depend on the cap
                if cand and rows + r.n > cap:
                    # keep FIFO within a signature: stop rather than
                    # skip-fill with later, smaller requests
                    overflow = True
                    break
                cand.append(r)
                rows += r.n

            flush_now = (overflow or rows >= cap
                         or head.age(now) >= self.timeout_s
                         or self._draining or not self._running)
            if not flush_now:
                return expired, [], max(self.timeout_s - head.age(now),
                                        1e-4)
            taken = set(map(id, cand))
            self._queue = collections.deque(
                r for r in self._queue if id(r) not in taken)
            metrics.record_queue_depth(len(self._queue))
            return expired, cand, 0.0
