"""paddle_tpu.serving.prefix_cache — KV reuse for shared prompt heads.

Production traffic is head-heavy: most requests open with one of a
handful of system prompts, and prefill recomputes the same KV for that
head on every arrival. This module is the memo table in front of the
prefill pool: a bucketed hash of the *full* prompt token sequence maps
to the KV segment (and final-position logits) prefill produced the
first time, so a repeat prompt skips prefill entirely and hands cached
KV straight to a decode slot.

Design constraints, in order:

* **No new executables on a hit.** Cached KV is stored padded to the
  same ``io.bucketing`` prompt bucket prefill ran at, so the segment
  lands on the decode pool's already-warmed insert executable for that
  ``(pad, capacity)`` pair. A hit never changes the set of shapes in
  flight.
* **Bit-identical streams.** The cache stores prefill's *inputs to
  sampling* (the last-position logits), not its sampled token — the
  hitting request samples its own first token from those logits with
  its own counter-PRNG key at generation index 0, exactly as fused
  prefill would have. Greedy and sampled streams are therefore
  byte-for-byte the streams the single-engine oracle emits.
* **Pinned entries never evict.** ``lookup`` takes a reference;
  eviction (LRU order under a byte budget) only considers entries with
  zero outstanding references, so a segment mid-handoff cannot vanish
  underneath the transfer. Callers must ``release`` when the segment
  has landed (or the request died).

The cache is host-side numpy — it prices and stores segments in the
same transport format ``KVCachePool.export_slot`` produces
(``{"length", "pad", "bytes", "leaves"}``), so handoff, drain
migration, and prefix hits all ride one copy primitive.
"""
from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict

import numpy as np

from . import metrics
from .kv_cache import bytes_per_token


def prompt_key(tokens):
    """Stable key for a full prompt: blake2b over the little-endian
    int32 token bytes, salted with the length (so a prefix of another
    prompt can never collide with it)."""
    arr = np.ascontiguousarray(np.asarray(tokens, dtype=np.int32))
    h = hashlib.blake2b(digest_size=16)
    h.update(arr.size.to_bytes(8, "little"))
    h.update(arr.tobytes())
    return h.hexdigest()


class _Entry:
    __slots__ = ("segment", "logits", "prompt_len", "nbytes", "refs",
                 "hits", "t_insert")

    def __init__(self, segment, logits, prompt_len, nbytes):
        self.segment = segment          # export_slot transport dict
        self.logits = logits            # [V] float32, last prompt position
        self.prompt_len = int(prompt_len)
        self.nbytes = int(nbytes)
        self.refs = 0
        self.hits = 0
        self.t_insert = time.monotonic()


class PrefixCache:
    """Ref-counted LRU over prefill KV segments, bounded by bytes.

    Parameters
    ----------
    spec : the per-token KV spec (``model.kv_spec()``) — used to verify
        inserted segments price out to exactly ``bytes_per_token(spec)
        * pad`` (the same assertion ``export_slot`` makes), so cache
        accounting can never drift from arena accounting.
    budget_bytes : byte ceiling for resident segments (logits ride
        free — they are ~vocab floats against megabytes of KV). When
        the ceiling would be crossed, unpinned entries evict in LRU
        order; if everything is pinned the insert is refused rather
        than the budget broken.
    """

    def __init__(self, spec, budget_bytes=64 * 1024 * 1024):
        self.spec = dict(spec)
        self.budget_bytes = int(budget_bytes)
        self._per_token = bytes_per_token(self.spec)
        self._lock = threading.Lock()
        self._entries = OrderedDict()   # key -> _Entry, LRU order
        self._bytes = 0
        self._hits = 0
        self._misses = 0
        self._inserts = 0
        self._evictions = 0
        self._refused = 0

    # -- lookup / release --------------------------------------------------

    def lookup(self, tokens):
        """Hit: returns ``(key, entry)`` with a reference taken (entry
        is pinned until :meth:`release`). Miss: ``(key, None)`` — the
        caller runs prefill and may :meth:`insert` under the same key."""
        key = prompt_key(tokens)
        t0 = time.perf_counter()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                entry.refs += 1
                entry.hits += 1
                self._hits += 1
            else:
                self._misses += 1
        metrics.record_prefix_lookup(entry is not None,
                                     (time.perf_counter() - t0) * 1e3)
        return key, entry

    def release(self, key):
        """Drop one reference taken by a hit (or a just-inserted
        segment). Unpinned entries become evictable again."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry.refs > 0:
                entry.refs -= 1

    # -- insert / evict ----------------------------------------------------

    def insert(self, key, segment, logits, pin=False):
        """Adopt a prefill-produced segment under ``key``. The segment
        must be in export_slot transport format; its byte count is
        re-derived from the spec and asserted, never trusted. Returns
        True when resident (False when the budget is all pinned or the
        single segment exceeds it)."""
        pad = int(segment["pad"])
        nbytes = sum(int(np.asarray(a).nbytes)
                     for a in segment["leaves"].values())
        expected = self._per_token * pad
        if nbytes != expected:
            raise AssertionError(
                f"prefix segment bytes {nbytes} != spec-priced {expected} "
                f"({self._per_token} B/token x pad {pad})")
        if int(segment["bytes"]) != nbytes:
            raise AssertionError(
                f"segment self-reported {segment['bytes']} B, "
                f"leaves hold {nbytes} B")
        logits = np.asarray(logits)
        with self._lock:
            if key in self._entries:        # racer already inserted
                entry = self._entries[key]
                self._entries.move_to_end(key)
                if pin:
                    entry.refs += 1
                return True
            if nbytes > self.budget_bytes:
                self._refused += 1
                return False
            if not self._make_room(nbytes):
                self._refused += 1
                return False
            entry = _Entry(segment, logits, segment["length"], nbytes)
            if pin:
                entry.refs = 1
            self._entries[key] = entry
            self._bytes += nbytes
            self._inserts += 1
            cache_bytes, n = self._bytes, len(self._entries)
        metrics.record_prefix_cache(cache_bytes, n, self.budget_bytes)
        return True

    def _make_room(self, nbytes):
        """Evict unpinned entries (LRU first) until ``nbytes`` fits
        under the budget. Lock held by caller. False when pinned
        entries alone exceed the remaining headroom."""
        freed = 0
        evicted = 0
        while self._bytes + nbytes > self.budget_bytes:
            victim = next((k for k, e in self._entries.items()
                           if e.refs == 0), None)
            if victim is None:
                return False
            entry = self._entries.pop(victim)
            self._bytes -= entry.nbytes
            freed += entry.nbytes
            evicted += 1
        if evicted:
            self._evictions += evicted
            metrics.record_prefix_evict(evicted, freed)
        return True

    # -- introspection -----------------------------------------------------

    def bytes(self):
        with self._lock:
            return self._bytes

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def hit_rate(self):
        with self._lock:
            total = self._hits + self._misses
            return (self._hits / total) if total else None

    def stats(self):
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
                "hits": self._hits,
                "misses": self._misses,
                "inserts": self._inserts,
                "evictions": self._evictions,
                "refused": self._refused,
                "pinned": sum(1 for e in self._entries.values()
                              if e.refs > 0),
            }
