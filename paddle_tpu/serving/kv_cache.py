"""paddle_tpu.serving.kv_cache — the paged KV-cache pool behind
continuous-batching decode.

Autoregressive serving lives or dies on its KV-cache discipline
(PAPERS.md: Gemma-on-TPU serving): every active sequence needs its
attention history resident on device, histories grow one token per
step, and sequences of wildly different lengths share the same decode
executable. Three constraints shape the pool:

* **Fixed slot count.** The decode batch is ``slots`` wide, always.
  A sequence occupies one slot from prefill handoff to EOS; freeing a
  slot is a host-side bookkeeping write, so a finished sequence's slot
  is refillable at the very next tick — no drain-the-batch barrier.
* **Bucketed capacity, never ragged.** Per-slot K/V storage is one
  arena per spec leaf, shaped ``[slots, capacity, *tail]``.
  ``capacity`` only ever moves along a closed
  :func:`~paddle_tpu.io.bucketing.grow_buckets` family (the *page
  schedule*): when any sequence outgrows the current capacity the whole
  arena steps to the next bucket via a pre-compiled copy. Every shape
  the pool can ever take is declared up front, so :meth:`warmup` can
  AOT-compile all of them and steady-state growth performs **zero**
  fresh compiles.
* **Budgeted, not discovered.** ``bytes()`` is exact arithmetic over
  the spec (``slots × capacity × Σ leaf bytes/token``), published as
  ``serving.decode.cache_bytes`` with headroom against the PR 12
  memory model's device budget (``monitor.memory.device_hbm_limit``) —
  the pool tells you its peak *before* you hit it, the same pre-flight
  discipline as ``memory_plan``.

The pool owns buffers and slot bookkeeping; the decode engine
(``serving/generate.py``) owns the jitted prefill/decode/insert
executables that read and write them.
"""
from __future__ import annotations

import math
import threading

import numpy as np

from ..io.bucketing import grow_buckets, next_bucket
from . import metrics


def _leaves(spec):
    """Normalize a kv spec — a dict of leaf name -> (tail_shape, dtype)
    — into a sorted list of (name, tail_shape, np.dtype)."""
    out = []
    for name in sorted(spec):
        tail, dtype = spec[name]
        out.append((name, tuple(int(d) for d in tail), np.dtype(dtype)))
    return out


def bytes_per_token(spec):
    """Exact per-token KV footprint of one sequence: the sum over spec
    leaves of ``prod(tail) * dtype.itemsize``. Accepts a single kv
    spec or a list of specs (a speculative deployment prices the
    target arena *and* the draft arena as one number — both pools
    share the slot count and page schedule, so their footprints add)."""
    if isinstance(spec, (list, tuple)):
        return sum(bytes_per_token(s) for s in spec)
    return sum(int(np.prod(tail, dtype=np.int64)) * dt.itemsize
               for _, tail, dt in _leaves(spec))


class KVCachePool:
    """Fixed-slot paged K/V arena with geometric capacity growth.

    Parameters
    ----------
    spec : dict of leaf name -> (tail_shape, dtype) — the per-token KV
        layout (e.g. ``{"k0": ((H, D), "float32"), "v0": ...}`` per
        layer). The decode model declares it (``model.kv_spec()``).
    slots : decode batch width — concurrent sequences served.
    page : smallest capacity bucket (tokens). Capacity starts here.
    factor / max_len : the geometric page schedule —
        ``grow_buckets(page, factor, max_len)``. ``max_len`` is the
        hard ceiling on prompt + generated tokens per sequence.
    """

    def __init__(self, spec, slots, page=128, factor=2.0, max_len=1024,
                 label=None):
        import jax.numpy as jnp
        self.spec = dict(spec)
        self.slots = int(slots)
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.label = label          # metrics namespace ("draft" for the
        #                             speculative draft arena)
        self.seq_buckets = grow_buckets(page, factor, max_len)
        self.max_len = int(self.seq_buckets[-1])
        self.capacity = int(self.seq_buckets[0])
        self._leaf_list = _leaves(self.spec)
        self.buffers = {
            name: jnp.zeros((self.slots, self.capacity) + tail, dtype=dt)
            for name, tail, dt in self._leaf_list}
        self._lock = threading.Lock()
        self._free = list(range(self.slots))[::-1]   # pop() -> slot 0 first
        # per-slot live length: how many leading arena positions hold
        # *accepted* history. Readers mask by it; rollback() shrinks it.
        self._lengths = [0] * self.slots
        self._grows = 0
        self._rollbacks = 0
        self._rollback_tokens = 0
        self._publish()

    # -- slot bookkeeping --------------------------------------------------

    def alloc(self):
        """Claim a free slot index, or None when the batch is full."""
        with self._lock:
            if not self._free:
                return None
            s = self._free.pop()
            self._lengths[s] = 0
            return s

    def free(self, slot):
        """Return a slot to the pool. The stale K/V rows are left in
        place — every reader masks by live length, so a freed slot's
        garbage is never attended to, and the next prefill overwrites
        it."""
        with self._lock:
            if slot in self._free:
                raise ValueError(f"slot {slot} double-freed")
            self._free.append(int(slot))
            self._lengths[int(slot)] = 0

    def length(self, slot):
        """Live (accepted) length of one slot's history."""
        with self._lock:
            return self._lengths[int(slot)]

    def note_length(self, slot, new_len):
        """Record that arena positions ``[0, new_len)`` of ``slot`` now
        hold written history (prefill insert, decode write, or a
        speculative verify that wrote k+1 positions ahead of
        acceptance)."""
        new_len = int(new_len)
        if new_len < 0 or new_len > self.capacity:
            raise ValueError(
                f"length {new_len} outside [0, capacity={self.capacity}]")
        with self._lock:
            self._lengths[int(slot)] = new_len

    def rollback(self, slot, new_len):
        """Truncate one slot's live length to ``new_len`` WITHOUT
        freeing pages — the speculative verify-reject path: the target
        wrote k+1 positions optimistically, acceptance kept a prefix,
        and the positions past it become dead. No device data moves
        (every reader masks by length, and the next write overwrites
        in place); this is pure ledger truncation, the primitive
        prefix-cache reuse (ROADMAP item 3) will also need. Growing a
        length is note_length's job — rollback refuses it."""
        new_len = int(new_len)
        with self._lock:
            cur = self._lengths[int(slot)]
            if new_len > cur:
                raise ValueError(
                    f"rollback to {new_len} would GROW slot {slot} "
                    f"(live length {cur}) — use note_length for writes")
            if new_len < 0:
                raise ValueError(f"rollback length {new_len} < 0")
            dropped = cur - new_len
            self._lengths[int(slot)] = new_len
            self._rollbacks += 1
            self._rollback_tokens += dropped
        if dropped:
            metrics.record_rollback(dropped, label=self.label)
        return dropped

    def free_slots(self):
        with self._lock:
            return len(self._free)

    def used_slots(self):
        with self._lock:
            return self.slots - len(self._free)

    # -- slot transport (handoff / drain migration) ------------------------

    def export_slot(self, slot, pad_to=None):
        """Copy one slot's resident K/V history off the arena as a
        host-side *segment* — the one transport format shared by the
        disaggregated prefill→decode handoff and the drain-migration
        path (one tested copy primitive instead of ad-hoc tree maps).

        The segment is padded to ``pad_to`` arena positions (default:
        the slot's live length; pass a bucket so the receiving side can
        land it on a pre-compiled insert executable). Byte accounting
        is exact and asserted: the segment's payload must equal
        ``bytes_per_token(spec) × pad`` to the byte.

        Returns ``{"length", "pad", "bytes", "leaves"}`` where
        ``leaves[name]`` is a ``[pad, *tail]`` numpy array."""
        slot = int(slot)
        with self._lock:
            length = self._lengths[slot]
        pad = int(pad_to) if pad_to is not None else length
        if pad < length:
            raise ValueError(
                f"export pad {pad} < live length {length} of slot {slot}")
        if pad > self.capacity:
            raise ValueError(
                f"export pad {pad} exceeds arena capacity "
                f"{self.capacity}")
        leaves = {name: np.asarray(self.buffers[name][slot, :pad])
                  for name, _tail, _dt in self._leaf_list}
        seg_bytes = sum(int(a.nbytes) for a in leaves.values())
        expected = bytes_per_token(self.spec) * pad
        if seg_bytes != expected:
            raise AssertionError(
                f"export_slot byte accounting drifted: segment holds "
                f"{seg_bytes} bytes, spec arithmetic says {expected} "
                f"({pad} positions × {bytes_per_token(self.spec)} B/tok)")
        return {"length": length, "pad": pad, "bytes": seg_bytes,
                "leaves": leaves}

    def import_slot(self, slot, segment, insert_fn=None):
        """Land an exported segment into ``slot``: write the leaves at
        arena positions ``[0, pad)`` and record the live length through
        the :meth:`note_length` ledger (so a migrated stream's counter-
        PRNG indexing continues bit-identically).

        ``insert_fn(buffers, chunk, slot) -> buffers`` is the engine's
        pre-compiled insert executable for ``(pad, capacity)`` — the
        zero-compile path every serving import must use. Without it the
        write falls back to per-leaf ``dynamic_update_slice`` (tests,
        offline tools). Asserts the byte arithmetic on entry and that
        ``allocated_bytes()`` is unchanged by the import (a slot write
        must never resize the arena). Returns the segment bytes."""
        import jax
        import jax.numpy as jnp
        slot = int(slot)
        pad = int(segment["pad"])
        length = int(segment["length"])
        if pad > self.capacity:
            raise ValueError(
                f"segment pad {pad} exceeds arena capacity "
                f"{self.capacity} — grow first")
        leaves = segment["leaves"]
        names = {name for name, _t, _d in self._leaf_list}
        if set(leaves) != names:
            raise ValueError(
                f"segment leaves {sorted(leaves)} != spec leaves "
                f"{sorted(names)}")
        seg_bytes = sum(int(np.asarray(a).nbytes)
                        for a in leaves.values())
        expected = bytes_per_token(self.spec) * pad
        if seg_bytes != expected:
            raise AssertionError(
                f"import_slot byte accounting drifted: segment holds "
                f"{seg_bytes} bytes, spec arithmetic says {expected}")
        before = self.allocated_bytes()
        if insert_fn is not None:
            chunk = {name: jnp.asarray(np.asarray(leaves[name])[None])
                     for name, _t, _d in self._leaf_list}
            self.buffers = insert_fn(self.buffers, chunk,
                                     jnp.int32(slot))
        else:
            for name, tail, _dt in self._leaf_list:
                start = (slot, 0) + (0,) * len(tail)
                self.buffers[name] = jax.lax.dynamic_update_slice(
                    self.buffers[name], jnp.asarray(leaves[name])[None],
                    start)
        after = self.allocated_bytes()
        if after != before:
            raise AssertionError(
                f"import_slot changed the arena footprint: "
                f"{before} -> {after} bytes")
        self.note_length(slot, length)
        return seg_bytes

    # -- capacity schedule -------------------------------------------------

    def capacity_for(self, needed_len):
        """The family bucket a sequence of ``needed_len`` tokens needs
        (raises when it exceeds ``max_len`` — admission should have
        rejected it)."""
        needed = int(needed_len)
        if needed > self.max_len:
            raise ValueError(
                f"sequence of {needed} tokens exceeds the pool's "
                f"max_len={self.max_len} (family {self.seq_buckets})")
        return next_bucket(needed, self.seq_buckets)

    def needs_growth(self, needed_len):
        return self.capacity_for(needed_len) > self.capacity

    def grow_to(self, new_capacity, grow_fn):
        """Step the arena to ``new_capacity`` (a family member) using
        ``grow_fn(buffers, old_cap, new_cap) -> buffers`` — supplied by
        the engine so the copy rides a pre-compiled executable. Pages
        are only ever added; the pool never shrinks mid-flight (slots
        churn constantly; a shrink would need a stop-the-world over
        every live sequence)."""
        new_capacity = int(new_capacity)
        if new_capacity not in self.seq_buckets:
            raise ValueError(
                f"capacity {new_capacity} is not in the bucket family "
                f"{self.seq_buckets}")
        if new_capacity <= self.capacity:
            return
        self.buffers = grow_fn(self.buffers, self.capacity, new_capacity)
        self.capacity = new_capacity
        self._grows += 1
        metrics.record_cache_grow(new_capacity)
        self._publish()

    # -- budget ------------------------------------------------------------

    def bytes(self, capacity=None):
        """Exact arena footprint at ``capacity`` (default: current):
        ``slots × capacity × bytes_per_token(spec)``."""
        cap = self.capacity if capacity is None else int(capacity)
        return self.slots * cap * bytes_per_token(self.spec)

    def max_bytes(self):
        """The worst-case footprint — every slot at ``max_len``. This is
        the number to check against the HBM budget pre-flight."""
        return self.bytes(self.max_len)

    def allocated_bytes(self):
        """What the live buffers actually occupy (must equal
        :meth:`bytes` — the smoke gate's budget-honesty check)."""
        return sum(int(b.nbytes) for b in self.buffers.values())

    def headroom(self, limit_bytes=None):
        """``(limit - max_bytes, limit)`` against the device budget from
        the PR 12 memory model (``monitor.memory.device_hbm_limit``;
        override with ``limit_bytes``). ``(None, None)`` when no budget
        is known (CPU) — the pool never invents a verdict."""
        if limit_bytes is None:
            try:
                from ..monitor.memory import device_hbm_limit
                limit_bytes = device_hbm_limit()
            except Exception:
                limit_bytes = None
        if limit_bytes is None:
            return None, None
        return int(limit_bytes) - self.max_bytes(), int(limit_bytes)

    def _publish(self):
        headroom, limit = self.headroom()
        metrics.record_cache(self.bytes(), self.capacity,
                             headroom_bytes=headroom, limit_bytes=limit,
                             label=self.label)

    def stats(self):
        return {
            "slots": self.slots,
            "used_slots": self.used_slots(),
            "capacity": self.capacity,
            "max_len": self.max_len,
            "seq_buckets": list(self.seq_buckets),
            "cache_bytes": self.bytes(),
            "cache_max_bytes": self.max_bytes(),
            "grows": self._grows,
            "rollbacks": self._rollbacks,
            "rollback_tokens": self._rollback_tokens,
        }


def fits_budget(spec, slots, max_len, limit_bytes=None,
                reserve_frac=0.0):
    """Pre-flight: would a pool of ``slots × max_len`` fit under the
    device budget with ``reserve_frac`` held back for weights and
    activations? Returns (fits: bool | None, needed_bytes, limit).
    None means no budget is known — same contract as the planner's
    feasibility column. Pass ``spec`` as a list of kv specs to price a
    speculative deployment (target + draft arenas) as one pre-flight."""
    needed = int(slots) * int(max_len) * bytes_per_token(spec)
    if limit_bytes is None:
        try:
            from ..monitor.memory import device_hbm_limit
            limit_bytes = device_hbm_limit()
        except Exception:
            limit_bytes = None
    if limit_bytes is None:
        return None, needed, None
    usable = int(limit_bytes) * (1.0 - float(reserve_frac))
    return needed <= usable, needed, int(limit_bytes)


def plan_slots(spec, max_len, limit_bytes=None, reserve_frac=0.5,
               max_slots=256):
    """Inverse budget: the largest slot count whose worst-case pool
    fits in ``(1 - reserve_frac)`` of the budget. None when no budget
    is known. A list ``spec`` prices target + draft arenas together,
    so the planned slot count already pays for speculation."""
    if limit_bytes is None:
        try:
            from ..monitor.memory import device_hbm_limit
            limit_bytes = device_hbm_limit()
        except Exception:
            limit_bytes = None
    if limit_bytes is None:
        return None
    per_slot = int(max_len) * bytes_per_token(spec)
    usable = int(limit_bytes) * (1.0 - float(reserve_frac))
    return max(0, min(int(max_slots), int(math.floor(usable / per_slot))))
