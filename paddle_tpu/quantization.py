"""paddle_tpu.quantization — QAT fake-quant + post-training int8.

TPU-native rebuild of the reference's slim quantization
(reference: python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py:147 QuantizationTransformPass — inserts
fake_quantize/dequantize ops on conv/mul inputs; post_training_quantization.py
— calibrates activation scales from sample data then freezes int8 weights).

The reference rewrites the static Program graph; here quantization is a
Layer transform (the dygraph-natural form):

* :func:`quant_aware` wraps every Linear / Conv2D in a fake-quant layer:
  weights quantize per-channel abs-max each step, activations through a
  moving-average abs-max observer (a persistable buffer, like the
  reference's MovingAverageAbsMaxScale op). The quant-dequant uses a
  straight-through estimator (custom rounding VJP), so training under
  jit/GSPMD just works.
* :func:`convert` freezes a calibrated/trained model for inference:
  weights stored int8 + per-channel scales (the int8 tensors are what a
  serving stack ships; compute dequantizes into bf16 for the MXU).
* :func:`quant_post_static` = run calibration batches through the
  observers, then convert (PTQ).
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from .tensor import Tensor, Parameter, as_tensor
from .dispatch import apply
from . import nn
from .nn.layer import Layer

__all__ = ["fake_quant", "QuantConfig", "quant_aware", "convert",
           "quant_post_static", "QuantedLinear", "QuantedConv2D",
           "QuantizedLinear", "QuantizedConv2D"]


@jax.custom_vjp
def _ste_round(x):
    return jnp.round(x)


def _ste_fwd(x):
    return jnp.round(x), None


def _ste_bwd(_, g):
    return (g,)  # straight-through: d round(x)/dx := 1


_ste_round.defvjp(_ste_fwd, _ste_bwd)


def _qdq(x, scale, bits):
    """Quantize-dequantize with STE. scale broadcasts against x."""
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(_ste_round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def fake_quant(x, scale, bits=8, name=None):
    """Framework op: fake quantization (reference: fake_quantize_op.cc
    FakeQuantizeDequantizeAbsMax)."""
    return apply(lambda x, s: _qdq(x, s, bits), (x, as_tensor(scale)),
                 name="fake_quant")


class QuantConfig:
    def __init__(self, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max"):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate
        self.weight_quantize_type = weight_quantize_type
        self.activation_quantize_type = activation_quantize_type


class _QuantedBase(Layer):
    """Shared QAT machinery: activation observer + weight fake-quant."""

    def __init__(self, inner, config, ch_axis):
        super().__init__()
        self.inner = inner
        self._cfg = config
        self._ch_axis = ch_axis  # weight output-channel axis
        self.register_buffer("act_scale",
                             Tensor(jnp.zeros((), jnp.float32)),
                             persistable=True)
        self._calibrating = False

    def _observe(self, x):
        """Moving-average abs-max observer → fake-quant activations. The
        whole update is functional (like BatchNorm's running stats), so
        the observer advances both eagerly AND under jit tracing — the
        new scale is written back through the buffer holder, which
        to_static threads as mutable state."""
        cur = apply(lambda x: jnp.max(jnp.abs(x)).astype(jnp.float32),
                    (x,), nondiff=True, name="abs_max")
        r = self._cfg.moving_rate
        if self.training or self._calibrating:
            new_scale = apply(
                lambda old, cur: jnp.where(old > 0.0,
                                           r * old + (1 - r) * cur, cur),
                (self.act_scale, cur), nondiff=True, name="ma_scale")
            self.act_scale.data = new_scale.data
            scale = new_scale
        else:
            # eval before any calibration: fall back to the batch abs-max
            scale = apply(lambda s, cur: jnp.where(s > 0.0, s, cur),
                          (self.act_scale, cur), nondiff=True,
                          name="scale_or_cur")
        return fake_quant(x, scale, self._cfg.activation_bits)

    def _wq(self, w):
        if self._cfg.weight_quantize_type == "channel_wise_abs_max":
            axes = tuple(i for i in range(w.data.ndim)
                         if i != self._ch_axis)
            scale = apply(
                lambda w: jnp.max(jnp.abs(w), axis=axes, keepdims=True),
                (w,), nondiff=True, name="w_abs_max")
        else:
            scale = apply(lambda w: jnp.max(jnp.abs(w)), (w,),
                          nondiff=True, name="w_abs_max")
        return fake_quant(w, scale, self._cfg.weight_bits)


class QuantedLinear(_QuantedBase):
    """reference: QuantizationTransformPass on mul/matmul ops."""

    def __init__(self, inner, config):
        super().__init__(inner, config, ch_axis=1)  # (in, out)

    def forward(self, x):
        from .ops import nn_ops as F
        x = self._observe(x)
        w = self._wq(self.inner.weight)
        out = x @ w
        if self.inner.bias is not None:
            out = out + self.inner.bias
        return out


class QuantedConv2D(_QuantedBase):
    """reference: QuantizationTransformPass on conv2d ops."""

    def __init__(self, inner, config):
        super().__init__(inner, config, ch_axis=0)  # (out, in, kh, kw)

    def forward(self, x):
        from .ops import nn_ops as F
        x = self._observe(x)
        w = self._wq(self.inner.weight)
        return F.conv2d(x, w, self.inner.bias, **self.inner._attrs)


def _wrap(layer, config):
    for name, child in list(layer._sub_layers.items()):
        if isinstance(child, nn.Linear):
            layer.add_sublayer(name, QuantedLinear(child, config))
        elif isinstance(child, nn.Conv2D):
            layer.add_sublayer(name, QuantedConv2D(child, config))
        else:
            _wrap(child, config)
    return layer


def quant_aware(model, config=None):
    """Insert fake-quant wrappers on every Linear/Conv2D (reference:
    QuantizationTransformPass.apply). Train as usual afterwards."""
    return _wrap(model, config or QuantConfig())


# ---------------------------------------------------------------------------
# frozen int8 inference layers

def _freeze_weight(w, ch_axis, bits):
    qmax = 2 ** (bits - 1) - 1
    arr = w.data if isinstance(w, Tensor) else jnp.asarray(w)
    axes = tuple(i for i in range(arr.ndim) if i != ch_axis)
    scale = jnp.maximum(jnp.max(jnp.abs(arr), axis=axes, keepdims=True),
                        1e-8)
    q = jnp.clip(jnp.round(arr / scale * qmax), -qmax, qmax).astype(
        jnp.int8)
    return q, (scale / qmax).astype(jnp.float32)


class QuantizedLinear(Layer):
    """Frozen int8 linear (reference: QuantizationFreezePass output —
    int8 weight + per-channel scale). Weight ships int8.

    With a CALIBRATED activation scale (QAT/PTQ observer) the matmul runs
    int8 x int8 -> int32 on the MXU (`lax.dot_general` with
    preferred_element_type=int32) and only the edges are float: quantize
    the input once, rescale the int32 accumulator by
    act_step * per-channel weight_step. Uncalibrated models keep the
    dequantize-to-activation-dtype path (memory win only)."""

    def __init__(self, inner, bits=8, act_scale=None, act_bits=8,
                 int8_compute=True):
        super().__init__()
        q, scale = _freeze_weight(inner.weight, 1, bits)
        self.register_buffer("qweight", Tensor(q), persistable=True)
        self.register_buffer("wscale", Tensor(scale), persistable=True)
        a = 0.0 if act_scale is None else float(np.asarray(
            jax.device_get(act_scale.data if isinstance(
                act_scale, Tensor) else act_scale)))
        self.register_buffer("act_scale",
                             Tensor(jnp.asarray(a, jnp.float32)),
                             persistable=True)
        self._act_bits = act_bits
        # int8 MXU math needs a host-known calibrated scale (the dtype of
        # the dot is a trace-time property, not a jnp.where branch)
        self._int8_opt_in = bool(int8_compute)
        self._int8_compute = self._int8_opt_in and a > 0.0
        self.bias = inner.bias

    def _refresh_int8_gate(self):
        """Re-decide the int8-vs-dequant path whenever the act_scale
        buffer is host-readable: a calibrated state_dict loaded into a
        convert()-built layer (or a scale zeroed after the fact) must
        flip the path, not silently keep the construction-time choice."""
        a = self.act_scale.data
        if not isinstance(a, jax.core.Tracer):
            self._int8_compute = self._int8_opt_in and \
                float(np.asarray(jax.device_get(a))) > 0.0

    def forward(self, x):
        self._refresh_int8_gate()
        a_bits = self._act_bits
        a_qmax = float(2 ** (a_bits - 1) - 1)

        def impl_int8(x, q, s, ascale, *b):
            step = jnp.maximum(ascale, 1e-8) / a_qmax
            xq = jnp.clip(jnp.round(x.astype(jnp.float32) / step),
                          -a_qmax, a_qmax).astype(jnp.int8)
            acc = jax.lax.dot_general(
                xq, q, (((xq.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * step * s  # s: (1, out) steps
            if b:
                out = out + b[0]
            return out.astype(x.dtype)

        def impl(x, q, s, ascale, *b):
            x = jnp.where(ascale > 0.0, _qdq(x, ascale, a_bits), x)
            w = q.astype(x.dtype) * s.astype(x.dtype)
            out = x @ w
            if b:
                out = out + b[0]
            return out

        args = (x, self.qweight, self.wscale, self.act_scale)
        if self.bias is not None:
            args = args + (self.bias,)
        return apply(impl_int8 if self._int8_compute else impl, args,
                     name="quantized_linear")


class QuantizedConv2D(Layer):
    """Frozen int8 conv — same int8 x int8 -> int32 design as
    QuantizedLinear (lax.conv_general_dilated accumulates int32 on the
    MXU when calibrated; dequant-to-float fallback otherwise)."""

    def __init__(self, inner, bits=8, act_scale=None, act_bits=8,
                 int8_compute=True):
        super().__init__()
        q, scale = _freeze_weight(inner.weight, 0, bits)
        a = 0.0 if act_scale is None else float(np.asarray(
            jax.device_get(act_scale.data if isinstance(
                act_scale, Tensor) else act_scale)))
        self.register_buffer("act_scale",
                             Tensor(jnp.asarray(a, jnp.float32)),
                             persistable=True)
        self._act_bits = act_bits
        self._int8_opt_in = bool(int8_compute)
        self._int8_compute = self._int8_opt_in and a > 0.0
        self.register_buffer("qweight", Tensor(q), persistable=True)
        self.register_buffer("wscale", Tensor(scale), persistable=True)
        self.bias = inner.bias
        self._conv_attrs = dict(inner._attrs)

    _refresh_int8_gate = QuantizedLinear._refresh_int8_gate

    def forward(self, x):
        from .ops import nn_ops as F
        self._refresh_int8_gate()
        a_bits = self._act_bits
        if not self._int8_compute:
            x = apply(lambda x, a: jnp.where(a > 0.0, _qdq(x, a, a_bits),
                                             x),
                      (x, self.act_scale), name="act_quant")
            w = apply(lambda q, s: q.astype(jnp.float32) * s,
                      (self.qweight, self.wscale), nondiff=True,
                      name="dequant_w")
            return F.conv2d(x, w, self.bias, **self._conv_attrs)

        from .ops.nn_ops import (_conv_dimension_numbers, _norm_padding,
                                 _pair)
        a_qmax = float(2 ** (a_bits - 1) - 1)
        at = self._conv_attrs
        data_format = at.get("data_format", "NCHW")
        dn = _conv_dimension_numbers(4, data_format)
        stride = _pair(at.get("stride", 1), 2)
        padding = _norm_padding(at.get("padding", 0), 2)
        dilation = _pair(at.get("dilation", 1), 2)
        groups = at.get("groups", 1)

        def impl(x, q, s, ascale, *b):
            step = jnp.maximum(ascale, 1e-8) / a_qmax
            xq = jnp.clip(jnp.round(x.astype(jnp.float32) / step),
                          -a_qmax, a_qmax).astype(jnp.int8)
            acc = jax.lax.conv_general_dilated(
                xq, q, window_strides=stride, padding=padding,
                rhs_dilation=dilation, feature_group_count=groups,
                dimension_numbers=dn,
                preferred_element_type=jnp.int32)
            ch = (1, -1, 1, 1) if dn[2] == "NCHW" else (1, 1, 1, -1)
            out = acc.astype(jnp.float32) * step * s.reshape(ch)
            if b:
                out = out + b[0].reshape(ch)
            return out.astype(x.dtype)

        args = (x, self.qweight, self.wscale, self.act_scale)
        if self.bias is not None:
            args = args + (self.bias,)
        return apply(impl, args, name="quantized_conv2d")


def convert(model, bits=8):
    """Freeze a quant_aware (or plain) model for int8 inference
    (reference: QuantizationFreezePass + convert). Calibrated observer
    scales from QAT/PTQ carry into the frozen layers' act_scale."""
    def _conv(layer):
        for name, child in list(layer._sub_layers.items()):
            if isinstance(child, QuantedLinear):
                layer.add_sublayer(name, QuantizedLinear(
                    child.inner, bits, act_scale=child.act_scale,
                    act_bits=child._cfg.activation_bits))
            elif isinstance(child, QuantedConv2D):
                layer.add_sublayer(name, QuantizedConv2D(
                    child.inner, bits, act_scale=child.act_scale,
                    act_bits=child._cfg.activation_bits))
            elif isinstance(child, nn.Linear):
                layer.add_sublayer(name, QuantizedLinear(child, bits))
            elif isinstance(child, nn.Conv2D):
                layer.add_sublayer(name, QuantizedConv2D(child, bits))
            else:
                _conv(child)
        return layer

    model = _conv(model)
    model.eval()
    return model


def quant_post_static(model, sample_batches, config=None, bits=8):
    """Post-training quantization (reference:
    post_training_quantization.py): run calibration batches through
    observers, then freeze."""
    config = config or QuantConfig()
    model = quant_aware(model, config)
    for m in model.sublayers(include_self=True):
        if isinstance(m, _QuantedBase):
            m._calibrating = True
    model.eval()
    from . import autograd
    with autograd.no_grad():
        for batch in sample_batches:
            if isinstance(batch, (tuple, list)):
                model(*batch)
            else:
                model(batch)
    for m in model.sublayers(include_self=True):
        if isinstance(m, _QuantedBase):
            m._calibrating = False
    return convert(model, bits)
