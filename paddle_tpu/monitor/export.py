"""paddle_tpu.monitor.export — the live telemetry HTTP plane.

Everything the monitor produced before this module is *post-hoc*: JSONL
files and Perfetto dumps you read after the run dies. Production TPU
serving (PAPERS.md: Gemma on Cloud TPU) and any SLO accounting need the
*pull* model instead — Prometheus scrapes a ``/metrics`` endpoint, a
load balancer probes ``/healthz``, an operator curls ``/snapshot`` —
all while the run is alive. This is that surface, stdlib-only
(``http.server``), off by default, and torn down by
``monitor.disable()``:

* ``GET /metrics``  — the whole Registry rendered live as
  OpenMetrics/Prometheus text: counters → ``<name>_total``, gauges →
  ``<name>``, histograms → cumulative ``_bucket{le=...}`` rows +
  ``_sum``/``_count``. Dotted series names sanitize to underscores
  (``executor.run`` → ``executor_run``).
* ``GET /healthz``  — liveness + the resilience plane's verdicts:
  watchdog stall state (HTTP 503 while a step is past its deadline),
  NaN-guard trip counts, preemption flag. JSON body either way.
* ``GET /snapshot`` — ``monitor.snapshot()`` as JSON plus the newest
  flight-recorder directory, the JSONL sink path, and uptime.

Arming it::

    from paddle_tpu import monitor
    monitor.enable()
    srv = monitor.serve(port=9464)      # or port=0 for an ephemeral one
    print(srv.url)                      # http://127.0.0.1:9464
    ...
    monitor.disable()                   # joins the server + sampler

or zero-code via ``PADDLE_TPU_METRICS_PORT=9464`` (checked by
``monitor.enable()``, so ``PADDLE_TPU_MONITOR=1`` + the port variable
arm the whole plane from the environment).

Cost discipline: until ``serve()`` is called there is no thread, no
socket, and no hot-path check at all — the exporter reads the same
Registry the instrumentation already writes; scrapes cost the writers
nothing beyond normal lock acquisition.
"""
from __future__ import annotations

import http.server
import json
import os
import re
import threading
import time

__all__ = [
    "serve", "stop", "active", "port", "render_openmetrics",
    "health_payload", "snapshot_payload", "MetricsServer",
    "OPENMETRICS_CONTENT_TYPE",
]

OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"

_lock = threading.Lock()
_server = None
_t_started = None

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name):
    """Dotted registry name -> a legal Prometheus metric name."""
    n = _NAME_RE.sub("_", str(name))
    if n and n[0].isdigit():
        n = "_" + n
    return n


def _fmt(v):
    """Prometheus float formatting: integers render bare (1, not 1.0)."""
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_openmetrics(registry=None):
    """The whole registry as OpenMetrics text (ends with ``# EOF``).
    Histograms render the full cumulative bucket ladder with a final
    ``+Inf`` equal to ``_count``; sanitized-name collisions keep the
    first metric and drop later ones (a scrape must stay parseable)."""
    from .. import monitor as _mon
    reg = registry if registry is not None else _mon.registry()
    lines, seen = [], set()
    for name, kind, payload in reg.collect():
        n = _sanitize(name)
        if n in seen:
            continue
        seen.add(n)
        if kind == "counter":
            lines.append(f"# TYPE {n} counter")
            lines.append(f"{n}_total {_fmt(payload)}")
        elif kind == "gauge":
            if payload is None:
                continue
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {_fmt(payload)}")
        elif kind == "histogram":
            lines.append(f"# TYPE {n} histogram")
            for bound, cum in payload["buckets"]:
                lines.append(f'{n}_bucket{{le="{_fmt(bound)}"}} {cum}')
            lines.append(f'{n}_bucket{{le="+Inf"}} {payload["inf"]}')
            lines.append(f"{n}_sum {_fmt(payload['sum'])}")
            lines.append(f"{n}_count {payload['count']}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def _serving_health():
    """Per-fleet serving health blocks (lazy: only consulted when the
    serving tier was actually imported — the telemetry plane must not
    drag it in). Returns (blocks|None, any_fleet_all_open)."""
    import sys
    smulti = sys.modules.get("paddle_tpu.serving.multi")
    if smulti is None:
        return None, False
    try:
        blocks = smulti.health()
    except Exception:   # noqa: BLE001 - health must not 500 on a race
        return None, False
    if not blocks:
        return None, False
    return blocks, any(b.get("all_open") for b in blocks)


def health_payload():
    """(http_status, dict) for /healthz: 200 while healthy, 503 while
    any running watchdog's in-flight step is past its deadline OR every
    replica of a serving fleet's breakers are open (no capacity)."""
    from .. import monitor as _mon
    from ..resilience import guard as _guard
    from ..resilience import watchdog as _watchdog
    from . import trace as _trace

    wds = _watchdog.health()
    stalled = any(h.get("stalled") for h in wds)
    serving, all_open = _serving_health()
    reg = _mon.registry()
    payload = {
        "status": ("stalled" if stalled
                   else "degraded" if all_open else "ok"),
        "pid": os.getpid(),
        "uptime_s": (round(time.monotonic() - _t_started, 3)
                     if _t_started is not None else None),
        "monitor_enabled": _mon.enabled(),
        "watchdogs": wds,
        "watchdog_stalls": int(reg.value("resilience.watchdog_stall", 0)),
        "nan_guard": {
            "trips": _guard.total_trips(),
            "nan_skip": int(reg.value("resilience.nan_skip", 0)),
            "rollback": int(reg.value("resilience.rollback", 0)),
            "nan_raise": int(reg.value("resilience.nan_raise", 0)),
        },
        "flight_dir": _trace.last_flight(),
    }
    if serving is not None:
        payload["serving"] = serving
    return (503 if (stalled or all_open) else 200), payload


def snapshot_payload():
    """The /snapshot body: full registry snapshot + evidence pointers —
    including the newest xla_cost capture and the last profiled hotspot
    summary, so one scrape is enough to triage a slow step."""
    from .. import monitor as _mon
    from . import memory as _memory
    from . import profile as _profile
    from . import trace as _trace
    from . import xla as _xla
    newest = _xla.last()
    xla_cost = None
    if newest is not None:
        label, info = newest
        xla_cost = {"labels": _xla.labels(), "last_label": label,
                    "last": dict(info or {})}
    planner_block = None
    try:
        # lazy: the planner lives in parallel/ and importing it here
        # eagerly would couple the telemetry plane to jax.sharding
        from ..parallel import planner as _planner
        planner_block = _planner.last_decision()
    except Exception:
        planner_block = None
    # the memory block: predicted vs measured peak, top contributors,
    # and the last OOM flight pointer — the pre-flight budget + the
    # postmortem, one scrape apart
    memory_block = None
    try:
        summary = _memory.last_summary(top_k=3)
        oom = _memory.last_oom()
        if summary is not None or oom is not None:
            memory_block = {"report": summary, "last_oom": oom}
    except Exception:
        memory_block = None
    # serving block: fleet health + the supervisor's latest verdict —
    # "why did the fleet change shape?" answered the planner way
    serving_block = None
    try:
        import sys
        blocks, _ = _serving_health()
        ssup = sys.modules.get("paddle_tpu.serving.supervisor")
        smulti = sys.modules.get("paddle_tpu.serving.multi")
        decision = ssup.last_decision() if ssup is not None else None
        lifecycle = smulti.last_lifecycle() if smulti is not None else None
        if blocks is not None or decision is not None:
            serving_block = {"fleets": blocks, "last_decision": decision,
                             "last_lifecycle": lifecycle}
    except Exception:
        serving_block = None
    # slow-request exemplars: the N worst completed waterfalls by ttft
    # and tpot, so "which request blew the SLO and where did its time
    # go?" is answerable from one scrape (lazy: only if the serving
    # spine ever ran)
    slow_requests = None
    try:
        import sys
        _rq = sys.modules.get("paddle_tpu.serving.reqtrace")
        if _rq is not None:
            ex = _rq.exemplars()
            if ex["worst_ttft"] or ex["worst_tpot"]:
                slow_requests = ex
    except Exception:
        slow_requests = None
    return {
        "ts": time.time(),
        "pid": os.getpid(),
        "monitor_enabled": _mon.enabled(),
        "jsonl": _mon.jsonl_path(),
        "flight_dir": _trace.last_flight(),
        "xla_cost": xla_cost,
        "hotspots": _profile.last_summary(),
        "memory": memory_block,
        "planner": planner_block,
        "serving": serving_block,
        "slow_requests": slow_requests,
        "counters": _mon.snapshot(),
    }


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = "paddle-tpu-telemetry/1.0"
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # a scrape per second is not a log line
        pass

    def _send(self, code, body, content_type):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                self._send(200, render_openmetrics(),
                           OPENMETRICS_CONTENT_TYPE)
            elif path == "/healthz":
                code, payload = health_payload()
                self._send(code, json.dumps(payload, default=str),
                           "application/json")
            elif path == "/snapshot":
                self._send(200, json.dumps(snapshot_payload(),
                                           default=str),
                           "application/json")
            elif path == "/fleet":
                # merged cross-process rollup — only when this process
                # hosts a FleetAggregator (monitor/fleet.py)
                from . import fleet as _fleet
                agg = _fleet.active_aggregator()
                if agg is None:
                    self._send(404, "no fleet aggregator in this "
                                    "process\n",
                               "text/plain; charset=utf-8")
                else:
                    self._send(200, json.dumps(agg.payload(),
                                               default=str),
                               "application/json")
            elif path == "/":
                self._send(200, "paddle_tpu telemetry: "
                                "/metrics /healthz /snapshot /fleet\n",
                           "text/plain; charset=utf-8")
            else:
                self._send(404, "not found\n",
                           "text/plain; charset=utf-8")
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-write
        except Exception as e:  # noqa: BLE001 - a scrape must not crash
            try:
                self._send(500, f"telemetry error: {e!r}\n",
                           "text/plain; charset=utf-8")
            except Exception:
                pass


class MetricsServer:
    """A ThreadingHTTPServer on a daemon thread. ``port=0`` binds an
    ephemeral port (read it back from ``.port`` — the test-friendly
    path). ``stop()`` shuts down, closes the socket, and joins."""

    def __init__(self, port=0, host="127.0.0.1"):
        self._httpd = http.server.ThreadingHTTPServer(
            (host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host = self._httpd.server_address[0]
        self.port = int(self._httpd.server_address[1])
        self._thread = None

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.1},
                name="paddle_tpu-metrics", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout=5.0):
        try:
            self._httpd.shutdown()
        finally:
            self._httpd.server_close()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            self._thread = None


def serve(port=None, host="127.0.0.1", sampler=True,
          sample_interval_s=None):
    """Start (or return) the process's telemetry server. ``port=None``
    reads $PADDLE_TPU_METRICS_PORT, else 0 (ephemeral). By default
    also arms the periodic sampler so ``mem.*``/``slo.*`` gauges are
    live. Returns the :class:`MetricsServer` (``.port``/``.url``).
    Idempotent: a second call returns the running server unchanged."""
    global _server, _t_started
    with _lock:
        if _server is not None:
            return _server
        if port is None:
            env = os.environ.get("PADDLE_TPU_METRICS_PORT", "")
            port = int(env) if env else 0
        srv = MetricsServer(port=port, host=host).start()
        _server = srv
        _t_started = time.monotonic()
    if sampler:
        from . import sampler as _sampler
        _sampler.start(interval_s=sample_interval_s)
    from .. import monitor as _mon
    _mon.emit(kind="metrics_server", action="serve", host=srv.host,
              port=srv.port)
    return srv


def stop(timeout=5.0):
    """Tear the server down (idempotent): shutdown + close socket +
    join, so enable/disable cycles can't leak threads or ports. The
    sampler singleton is stopped by ``monitor.disable()`` alongside
    this."""
    global _server
    with _lock:
        srv, _server = _server, None
    if srv is not None:
        srv.stop(timeout=timeout)


def active():
    """The running MetricsServer, or None."""
    return _server


def port():
    """The bound port of the running server, or None — how tests (and
    the export smoke gate) find an ephemeral ``port=0`` server."""
    srv = _server
    return srv.port if srv is not None else None
