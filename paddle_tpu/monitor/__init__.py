"""paddle_tpu.monitor — framework-wide metrics & tracing runtime.

The observability subsystem every hot path reports through
(reference analogue: paddle/fluid/platform/profiler.cc — but that was
per-op CUDA timings printed at exit; this is a structured, queryable
record):

* ``dispatch.apply``      — per-op call counts (eager/static, grad/no-grad,
                            optional host timing), behind one flag check
* ``parallel.collective`` — per-collective issue counts + payload bytes
                            by mesh axis
* ``static.Executor``     — program run/compile counts, cache hits
* ``optimizer.step``      — step entries per optimizer class
* ``StepMonitor``         — step time, items/sec, device memory, MFU

Step-pipelining series (docs/performance.md "Step pipelining"):

* ``executor.recompile`` / ``jit.recompile`` — cache misses for a
  program/function whose earlier shapes already compiled (avoidable,
  shape-driven recompiles — the number bucketing drives to zero)
* ``executor.bucket_pad`` / ``jit.bucket_pad`` — ragged batches padded
  up to a bucket instead of minting a new executable
* ``executor.fetch_async`` / ``executor.fetch_skipped`` /
  ``executor.fetch_blocking`` — async-fetch mode accounting (blocking
  must stay 0 when ``async_fetch=True``)
* ``executor.aot_warmup``  — executables compiled ahead of time
* ``prefetch.batches`` / ``prefetch.stall_seconds`` — device-prefetch
  throughput and consumer starvation time

Resilience series (docs/robustness.md; ``paddle_tpu.resilience``):

* ``resilience.retry``          — transient-error retries (loader,
  prefetch, checkpoint I/O), with per-site JSONL events
* ``resilience.nan_skip`` / ``resilience.rollback`` /
  ``resilience.nan_raise`` — NaN-guard policy applications
* ``resilience.watchdog_stall`` — steps past the rolling deadline
  (each also emits a ``watchdog_dump`` event with a counter snapshot)
* ``resilience.preempt_save`` / ``resilience.auto_resume`` —
  preemption checkpoints and resumed runs
* ``resilience.ckpt_quarantine`` — corrupt checkpoints set aside
* ``resilience.fault_injected`` / ``resilience.drop`` — chaos-test
  injections and batches dropped after retry exhaustion
  (``prefetch.drops`` counts the same at the prefetch site)

Sharded-checkpoint series (docs/robustness.md "Sharded & elastic
checkpoints"; ``paddle_tpu.io.sharded``):

* ``ckpt.shard_bytes`` (counter) / ``ckpt.shard_seconds`` (histogram)
  — bytes written and per-shard write latency of sharded saves
* ``ckpt.restore_resharded``    — restores that landed on a mesh with
  a different topology than the one that saved (each also emits a
  ``ckpt`` JSONL event with both mesh signatures)
* ``ckpt.quorum_fallback``      — sharded checkpoints rejected by the
  quorum rule (≥1 missing/corrupt shard) during restore's fallback
  scan; the ``checkpoint.save``/``checkpoint.restore`` trace spans
  carry a ``sharded`` attribute on the sharded path
* ``resilience.elastic_attempt`` / ``elastic_restart`` /
  ``elastic_resize`` / ``elastic_preempt_stop`` — the elastic
  recovery loop's state transitions (``resilience.elastic``)

Serving series (docs/serving.md; ``paddle_tpu.serving``):

* ``serving.requests`` / ``serving.rows`` / ``serving.batches`` —
  submitted requests, their example rows, and coalesced batches
* ``serving.qps`` (gauge) / ``serving.latency_ms`` (histogram) —
  rolling completed-requests/sec and submit→resolve latency
* ``serving.queue_depth`` / ``serving.rejected`` /
  ``serving.deadline_expired`` — admission control in action
* ``serving.batch_fill`` (requests per batch) /
  ``serving.batch_occupancy`` (real rows ÷ bucket rows) /
  ``serving.pad_rows`` — how well dynamic batching amortizes
* ``serving.compiles`` — executables minted by the serving path
  (must stop growing after ``ServingEngine.warmup``)
* ``serving.retries`` / ``serving.isolated`` / ``serving.poisoned`` —
  the RetryPolicy-classified failure path
* ``serving.decode.*`` — the continuous-batching decode tier:
  ``ticks``/``tokens``/``slot_occupancy`` (fused-step cadence and how
  full the decode batch runs), ``prefills``/``prefill_tokens``/
  ``prefill_ms``/``prefill_ratio`` (prompt-ingest side of the
  prefill/decode split), ``compiles`` (decode executables minted —
  must stop growing after ``GenerateEngine.warmup``), and
  ``cache_bytes``/``cache_capacity``/``cache_headroom``/
  ``cache_grows`` (the KV pool's live footprint vs the device budget)
* ``slo.tokens_per_s`` / ``slo.decode_p99_ms`` — the rolling decode
  window the supervisor's ``tokens_floor`` scaling reads
* ``inference.{compile,cache_hit,aot_warmup,bucket_pad}`` — the
  underlying Predictor's executable-cache accounting

Gradient-communication series (docs/performance.md "Communication
overlap & quantized sync"; ``paddle_tpu.parallel.overlap``):

* ``comm.bytes_logical`` / ``comm.bytes_wire`` — f32 payload bytes of
  each gradient sync vs the bytes of its wire representation (f32 for
  exact/overlap, int8/packed-int4 + per-hop scales for quantized) —
  the quantization saving is their ratio
* ``comm.buckets`` / ``comm.bucket_compile`` / ``comm.reduce_launch``
  — bucket plan size, distinct bucket-reduce executables minted (must
  stop growing after the first step of each mode), and launched bucket
  reduces
* ``comm.exposed_wait_s`` (histogram) / ``comm.exposed_wait_s_total``
  — seconds the step loop spent *blocked* on unfinished reduces: the
  exposed wire time overlap mode is built to remove (bench.py's
  ``collective_overlap`` stage gates on it)
* ``comm.sync.<mode>`` / ``comm.lag_warmup`` — sync calls per mode and
  lag-1 warm-up steps that had no previous grads to apply
* ``comm.bucket_reduce`` / ``comm.wait`` trace spans — bucket reduces
  (on the ``comm-worker`` thread track in overlap mode, where their
  overlap with backward compute is *visible* in the Chrome export) and
  the blocking collect

Auto-sharding planner series (docs/parallelism.md;
``paddle_tpu.parallel.planner``):

* ``planner.plan`` / ``planner.auto_pick`` — plans built, and how many
  let the advisor pick the mesh (``plan(auto=True)``)
* ``planner.candidates`` (gauge) / ``planner.predicted_step_s``
  (gauge) — size of the last advisor table and the winner's predicted
  step time; each decision also lands as one ``kind="planner"`` JSONL
  record (chosen sizes, ranked table head, rule hash) cross-linked to
  the profiler's current top hotspot region, and as the ``planner``
  block of ``/snapshot``
* ``layout.degraded`` — dims a requested spec could not shard on the
  actual mesh (non-divisible or missing axes) and replicated instead;
  warned once per (param, dim), counted every time — the advisor's
  degradation penalty reads the same signal
* ``arena.flat_fallback`` — flat-arena requests that fell back to the
  per-leaf path because the layout shards params (tp/pp/ep > 1);
  warned once per config, counted every time

Span tracing & XLA-measured cost (PR 4's additions):

* ``monitor.trace``  — thread-aware span tracer (``span()`` context
  managers, ring buffer, Chrome-trace/Perfetto export, flight
  recorder). ``PADDLE_TPU_TRACE=1`` arms it alongside ``enable()``.
* ``monitor.xla``    — ``cost_analysis()``/``memory_analysis()`` of
  compiled executables as ``xla.flops.<label>`` /
  ``xla.bytes_accessed.<label>`` / ``xla.peak_memory.<label>`` gauges
  plus ``xla_cost`` JSONL records; feeds the measured-MFU columns in
  StepMonitor and bench.py.

Memory-observability series (docs/observability.md "Memory
attribution & budget"; ``paddle_tpu.monitor.memory``):

* ``memory.predicted_peak_bytes.<label>`` /
  ``memory.attributed_frac.<label>`` — the HLO buffer-liveness
  model's simulated peak HBM and the fraction of live-at-peak bytes
  credited to a registered framework scope (``memory.report()``)
* ``mem.device.<id>.hbm_headroom_bytes`` / ``mem.hbm_headroom_bytes``
  — sampler-published per-device and total headroom (limit − in-use)
* ``memory.oom`` — OOM-shaped crashes the Executor/``hapi.fit``
  handlers caught; each leaves a flight-recorder dump bundling the
  memory report + peak-contributor ledger next to the op ledger

Everything funnels into one process-global :class:`Registry` and,
when a sink is configured (``PADDLE_TPU_MONITOR_DIR`` or an explicit
path to ``enable()``), a JSONL event stream.

Cost discipline: when disabled (the default), the ONLY overhead on the
dispatch fast path is a single ``_monitor_hook is None`` check inside
``dispatch.apply`` — no dict writes, no allocation (asserted by
tests/test_monitor.py). Collective/executor/optimizer sites check
``monitor.enabled()`` once per call, off any per-element loop.

Usage::

    import paddle_tpu as pt
    from paddle_tpu import monitor

    monitor.enable("/tmp/run1")          # or PADDLE_TPU_MONITOR=1 in env
    ... train ...
    print(monitor.snapshot("dispatch."))  # per-op counts
    monitor.disable()                     # flushes a counters snapshot
"""
from __future__ import annotations

import os
import time

from .registry import Registry, JsonlSink, read_jsonl  # noqa: F401
from .step import (StepMonitor, mfu, peak_flops_for_device,  # noqa: F401
                   transformer_train_flops_per_token,
                   device_memory_stats, GoodputLedger,
                   GOODPUT_CATEGORIES,
                   BERT_BASE_PARAMS, RESNET50_TRAIN_FLOPS_PER_IMAGE)

__all__ = [
    "enable", "disable", "enabled", "registry", "counter", "gauge",
    "histogram", "emit", "snapshot", "reset", "jsonl_path",
    "record_collective", "StepMonitor", "mfu", "peak_flops_for_device",
    "transformer_train_flops_per_token", "device_memory_stats",
    "GoodputLedger", "GOODPUT_CATEGORIES",
    "read_jsonl", "trace", "xla", "serve", "export", "sampler",
    "profile", "memory", "fleet", "alerts",
]

_registry = Registry()
_sink = None
_enabled = False
_time_dispatch = False


# ---------------------------------------------------------------------------
# lifecycle

def enabled():
    return _enabled


def registry() -> Registry:
    return _registry


def jsonl_path():
    """The active sink file, or None (enabled() can be true with no sink
    — counters still collect in memory)."""
    return _sink.path if _sink is not None else None


def _resolve_sink_path(path):
    p = str(path)
    if p.endswith(".jsonl"):
        return p
    os.makedirs(p, exist_ok=True)
    return os.path.join(p, f"events-{os.getpid()}.jsonl")


def enable(path=None, time_dispatch=None, max_bytes=None,
           telemetry_dir=None):
    """Turn monitoring on. `path` is a directory (an events-<pid>.jsonl
    file is created inside) or a *.jsonl file path; default is
    $PADDLE_TPU_MONITOR_DIR, and with neither the registry collects
    in-memory only. time_dispatch=True additionally histograms host-side
    per-op dispatch latency ($PADDLE_TPU_MONITOR_TIME_DISPATCH).
    max_bytes caps the JSONL sink — past it the file rotates to
    ``.1``/``.2`` instead of growing unbounded
    ($PADDLE_TPU_MONITOR_MAX_BYTES). telemetry_dir arms the fleet
    snapshot publisher: this process periodically drops an atomic
    metrics snapshot a FleetAggregator in any process can merge
    ($PADDLE_TPU_TELEMETRY_DIR; see monitor/fleet.py). Without it, no
    publisher thread starts and no snapshot files are written.
    Returns the JSONL path (or None). Idempotent; a new path replaces
    the old sink."""
    global _enabled, _sink, _time_dispatch
    if time_dispatch is None:
        time_dispatch = os.environ.get(
            "PADDLE_TPU_MONITOR_TIME_DISPATCH", "") not in ("", "0")
    _time_dispatch = bool(time_dispatch)
    if max_bytes is None:
        env = os.environ.get("PADDLE_TPU_MONITOR_MAX_BYTES", "")
        max_bytes = int(env) if env else None

    target = path or os.environ.get("PADDLE_TPU_MONITOR_DIR")
    if target:
        fp = _resolve_sink_path(target)
        if (_sink is None or _sink.path != os.path.abspath(fp)
                or _sink.max_bytes != max_bytes):
            # close the previous sink BEFORE installing the new one — a
            # re-enable with a new path must not leak the old file handle
            old, _sink = _sink, None
            if old is not None:
                old.close()
            _sink = JsonlSink(fp, max_bytes=max_bytes)
    _enabled = True

    telemetry_target = telemetry_dir or os.environ.get(
        "PADDLE_TPU_TELEMETRY_DIR")
    if telemetry_target:
        fleet.start_publisher(telemetry_target)

    if os.environ.get("PADDLE_TPU_TRACE", "") not in ("", "0"):
        trace.enable()
    if os.environ.get("PADDLE_TPU_PROFILE", "") not in ("", "0"):
        profile.enable()

    from .. import dispatch
    dispatch.install_monitor_hook(_dispatch_hook, time_ops=_time_dispatch)
    emit(kind="monitor", action="enable", pid=os.getpid(),
         time_dispatch=_time_dispatch)

    # zero-code telemetry plane: PADDLE_TPU_METRICS_PORT=9464 (or =0
    # for ephemeral) arms the /metrics HTTP server + sampler from env
    if os.environ.get("PADDLE_TPU_METRICS_PORT", "") != "":
        serve()
    return jsonl_path()


def disable(flush_counters=True):
    """Turn monitoring off: uninstall the dispatch hook (restoring the
    zero-overhead fast path), tear down the telemetry plane (export
    server socket closed + thread joined, sampler joined), emit a final
    counters snapshot, and close the sink. The registry keeps its
    values for post-run inspection — reset() clears them."""
    global _enabled, _sink
    if flush_counters and _enabled:
        emit(kind="counters", counters=snapshot())
    from .. import dispatch
    dispatch.install_monitor_hook(None)
    sampler.stop()
    export.stop()
    fleet.stop_publisher()
    fleet.stop_server()
    _enabled = False
    if _sink is not None:
        _sink.close()
        _sink = None


def serve(port=None, host="127.0.0.1", **kw):
    """Start the live telemetry HTTP server (/metrics /healthz
    /snapshot) + periodic sampler. port=None reads
    $PADDLE_TPU_METRICS_PORT, else binds port 0 (ephemeral; read
    ``.port`` off the returned server). See monitor/export.py."""
    return export.serve(port=port, host=host, **kw)


# ---------------------------------------------------------------------------
# metric + event surface

def counter(name):
    return _registry.counter(name)


def gauge(name):
    return _registry.gauge(name)


def histogram(name, buckets=None):
    return _registry.histogram(name, buckets=buckets)


def snapshot(prefix=""):
    return _registry.snapshot(prefix)


def reset():
    _registry.reset()
    xla.reset()


def emit(kind="event", **fields):
    """Append one JSONL record (no-op without a sink)."""
    if _sink is not None:
        rec = {"ts": time.time(), "kind": kind}
        rec.update(fields)
        _sink.emit(rec)


# ---------------------------------------------------------------------------
# instrumentation hooks (called by dispatch / collective / executor /
# optimizer — each call site is behind its own enabled() gate)

def _dispatch_hook(name, grad, t0, static=False):
    """Installed into paddle_tpu.dispatch while enabled. Must stay
    allocation-light: two counter incs, plus one histogram observe (and
    one trace event when span tracing is on) when host timing is on."""
    op = name or "anon"
    _registry.counter(f"dispatch.{op}").inc()
    if static:
        _registry.counter(f"dispatch.static.{op}").inc()
    elif grad:
        _registry.counter(f"dispatch.grad.{op}").inc()
    if t0 is not None:
        t1 = time.perf_counter()
        _registry.histogram(f"dispatch_ms.{op}").observe((t1 - t0) * 1e3)
        # per-op timeline rides the same time_dispatch opt-in: the t0
        # stamp already paid the clock read the span needs
        trace.complete(f"dispatch.{op}", t0, t1)


def record_collective(op, axis_name, nbytes):
    """Per-collective accounting (parallel/collective.py calls this
    after its SPMD gate, so pure-eager identity paths don't count).
    `nbytes` is the per-shard payload at the issue site; inside a jitted
    region the count is per trace, not per device execution — see
    docs/observability.md."""
    axis = axis_name or "none"
    _registry.counter(f"collective.{op}.{axis}.calls").inc()
    _registry.counter(f"collective.{op}.{axis}.bytes").inc(int(nbytes))
    trace.instant(f"collective.{op}", axis=axis, bytes=int(nbytes))
    emit(kind="collective", op=op, axis=axis, bytes=int(nbytes))


# imported last: the submodules reach back into this namespace
# (gauge/emit/snapshot), which is fully populated by this point
from . import (trace, xla, export, sampler, profile,  # noqa: E402,F401
               memory, fleet, alerts)
